#!/usr/bin/env python3
"""Lint: no device synchronization inside the tick capture/dispatch paths.

The streaming tick pipeline (ISSUE 2) only works because JAX dispatch is
async: tick N's device round trip hides behind tick N+1's host capture.
ONE stray ``jax.device_get`` / ``.block_until_ready()`` in the capture or
dispatch path re-serializes the whole pipeline — silently, with no test
failing, just the latency win gone.  Same spirit as
``lint_swallowed_faults.py``: make the regression impossible to land
quietly.

The designated sync point is ``StreamingHostState.fetch`` (and only it):
every module on the tick path below lists the functions allowed to
synchronize; a sync call anywhere else in those files fails the lint.

Run directly (``python tools/lint_tick_sync.py``) or via
tests/test_tick_pipeline.py, which gates it under tier-1.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

# the banned synchronization spellings (attribute accesses — catches
# jax.device_get, jax.block_until_ready, and x.block_until_ready())
SYNC_ATTRS = ("device_get", "block_until_ready")

# tick-path modules -> function names allowed to synchronize there.
# fetch() is THE sync point; everything else on the capture/dispatch path
# must stay async.  The serving scheduler (ISSUE 3) joins the same
# contract: its worker overlaps batch N's device round trip with batch
# N+1's assembly, so a sync anywhere outside BatchDispatcher.fetch
# re-serializes the serve pipeline exactly like a stray sync in a tick.
TICK_MODULES: Dict[str, Set[str]] = {
    os.path.join("rca_tpu", "engine", "streaming.py"): {"fetch"},
    os.path.join("rca_tpu", "parallel", "streaming.py"): {"fetch"},
    os.path.join("rca_tpu", "engine", "live.py"): set(),
    os.path.join("rca_tpu", "features", "extract.py"): set(),
    os.path.join("rca_tpu", "cluster", "snapshot.py"): set(),
    os.path.join("rca_tpu", "serve", "dispatcher.py"): {"fetch"},
    os.path.join("rca_tpu", "serve", "loop.py"): set(),
    os.path.join("rca_tpu", "serve", "queue.py"): set(),
    os.path.join("rca_tpu", "serve", "batcher.py"): set(),
    os.path.join("rca_tpu", "serve", "client.py"): set(),
    os.path.join("rca_tpu", "serve", "metrics.py"): set(),
}


def scan_file(path: str, allowed: Set[str]) -> List[Tuple[int, str]]:
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    except SyntaxError as exc:
        return [(exc.lineno or 0, "syntax error")]

    hits: List[Tuple[int, str]] = []

    def walk(node: ast.AST, func: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if (isinstance(node, ast.Attribute)
                and node.attr in SYNC_ATTRS and func not in allowed):
            hits.append((node.lineno, node.attr))
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, "<module>")
    return hits


def run(root: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for rel, allowed in sorted(TICK_MODULES.items()):
        full = os.path.join(root, rel)
        if not os.path.exists(full):
            continue
        out += [(rel, ln, attr) for ln, attr in scan_file(full, allowed)]
    return out


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hits = run(root)
    for rel, lineno, attr in hits:
        print(
            f"{rel}:{lineno}: `{attr}` in the tick capture/dispatch path — "
            "device sync belongs ONLY in StreamingHostState.fetch (it "
            "re-serializes the tick pipeline; see PERF.md round-6)"
        )
    if hits:
        print(f"{len(hits)} stray device sync(s) in tick paths")
        return 1
    print("lint_tick_sync: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
