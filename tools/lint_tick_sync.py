#!/usr/bin/env python3
"""Lint: no device synchronization inside the tick capture/dispatch paths.

Thin shim over the graftlint framework (PR 4): the invariant now lives in
:mod:`rca_tpu.analysis.rules.ticksync` as the ``tick-sync`` rule, next to
the other six JAX/TPU-correctness rules, with suppression-comment and
baseline support.  This script keeps the PR-2 CLI contract byte-for-byte
(same messages, same exit codes) for the tier-1 gate in
tests/test_tick_pipeline.py and any operator muscle memory.

Run directly (``python tools/lint_tick_sync.py``) or use the full
analyzer: ``python -m rca_tpu.analysis`` / ``rca lint``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    from rca_tpu.analysis import run_lint

    result = run_lint(rules=["tick-sync"])
    for f in result.findings:
        print(f"{f.path}:{f.line}: {f.message}")
    if result.findings:
        print(f"{len(result.findings)} stray device sync(s) in tick paths")
        return 1
    print("lint_tick_sync: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
