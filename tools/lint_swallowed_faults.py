#!/usr/bin/env python3
"""Lint: no silently-swallowed faults outside the resilience layer.

Fails (exit 1) when any ``except Exception: pass`` / bare ``except: pass``
handler appears in the codebase outside ``rca_tpu/resilience/``.  A
swallowed fault must go through a policy —
:func:`rca_tpu.resilience.policy.suppressed` records it into the bounded
fault log the streaming health records drain, so "it failed and nobody
ever knew" cannot happen again.  Narrow handlers (``except OSError:
pass``) stay allowed: catching a SPECIFIC exception is a decision; catching
everything and discarding it is a bug farm.

Run directly (``python tools/lint_swallowed_faults.py``) or via
tests/test_resilience.py, which gates it under tier-1.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

# directories scanned, relative to the repo root
SCAN_DIRS = ("rca_tpu", "tools", "tests")
SCAN_FILES = ("bench.py",)
# the one place allowed to swallow: the policy layer itself
ALLOWED_PREFIX = os.path.join("rca_tpu", "resilience") + os.sep


def _is_swallow(handler: ast.ExceptHandler) -> bool:
    """True for ``except Exception:``/bare ``except:`` whose body is only
    ``pass`` (docstring-style constants also count as doing nothing)."""
    if handler.type is not None:
        # only the catch-everything shapes are banned
        if not (isinstance(handler.type, ast.Name)
                and handler.type.id in ("Exception", "BaseException")):
            return False
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant))
        for stmt in handler.body
    )


def scan_file(path: str) -> List[Tuple[str, int]]:
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    except SyntaxError as exc:
        return [(path, exc.lineno or 0)]
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_swallow(node):
            hits.append((path, node.lineno))
    return hits


def run(root: str) -> List[Tuple[str, int]]:
    hits: List[Tuple[str, int]] = []
    targets = list(SCAN_FILES)
    for d in SCAN_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(root, d)):
            targets += [
                os.path.join(dirpath, f)
                for f in filenames if f.endswith(".py")
            ]
    for path in targets:
        full = path if os.path.isabs(path) else os.path.join(root, path)
        if not os.path.exists(full):
            continue
        rel = os.path.relpath(full, root)
        if rel.startswith(ALLOWED_PREFIX):
            continue
        hits += [(os.path.relpath(p, root), ln) for p, ln in scan_file(full)]
    return hits


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hits = run(root)
    for path, lineno in hits:
        print(
            f"{path}:{lineno}: swallowed fault — replace "
            "`except Exception: pass` with "
            "rca_tpu.resilience.policy.suppressed(op)"
        )
    if hits:
        print(f"{len(hits)} swallowed fault(s) outside rca_tpu/resilience/")
        return 1
    print("lint_swallowed_faults: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
