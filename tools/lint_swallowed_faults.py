#!/usr/bin/env python3
"""Lint: no silently-swallowed faults outside the resilience layer.

Thin shim over the graftlint framework (PR 4): the invariant now lives in
:mod:`rca_tpu.analysis.rules.faults` as the ``swallowed-faults`` rule,
next to the other six JAX/TPU-correctness rules, with suppression-comment
and baseline support.  This script keeps the PR-1 CLI contract
byte-for-byte (same messages, same exit codes) for the tier-1 gate in
tests/test_resilience.py and any operator muscle memory.

Run directly (``python tools/lint_swallowed_faults.py``) or use the full
analyzer: ``python -m rca_tpu.analysis`` / ``rca lint``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    from rca_tpu.analysis import run_lint

    result = run_lint(rules=["swallowed-faults"])
    for f in result.findings:
        print(f"{f.path}:{f.line}: {f.message}")
    if result.findings:
        print(f"{len(result.findings)} swallowed fault(s) outside "
              "rca_tpu/resilience/")
        return 1
    print("lint_swallowed_faults: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
