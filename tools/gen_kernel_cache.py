#!/usr/bin/env python3
"""Generate the committed platform-keyed kernel winner cache (ISSUE 17).

``rca_tpu/engine/kernel_cache.<platform>.json`` ships the autotune
winners for the canonical shape buckets so a fleet worker's first
resolve of a shape serves a seeded row instead of paying the timing
race cold (``KernelRegistry._load_cached`` falls back to the shipped
file when the user cache has no row).  The file is ordinary cache
format — same ``_CACHE_VERSION`` / jax-version / ``kernel_set_hash``
header, so a jax upgrade or kernel edit invalidates it wholesale and
the fleet re-times rather than serving stale verdicts.

Run on the target platform after any kernel change::

    JAX_PLATFORMS=cpu python tools/gen_kernel_cache.py

and commit the refreshed ``kernel_cache.<platform>.json``.  Timing is
the registry's own harness (``_time_candidates`` over the full
propagation chain), so shipped rows are bit-for-bit what a live
autotune would have decided on this host class.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="cache file to write (default: the shipped "
                         "platform-keyed path)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--buckets", default=None,
                    help="comma-separated n_pad buckets (default: the "
                         "config shape buckets)")
    ap.add_argument("--edge-tiers", default="1,2",
                    help="e_pad multipliers per bucket (default 1,2: "
                         "ring-sparse and 2x-dense edge tiers)")
    args = ap.parse_args()

    from rca_tpu.config import RCAConfig, shipped_kernel_cache_path
    from rca_tpu.engine.pallas_kernels import pallas_supported
    from rca_tpu.engine.registry import (
        KERNELS, KernelRegistry, KernelRow, _backend, _eligibility,
        _pick_winner, _segscan_min, _time_candidates,
    )

    out = args.out or shipped_kernel_cache_path()
    backend = _backend()
    steps = int(args.steps)
    if args.buckets:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
    else:
        buckets = list(RCAConfig().shape_buckets)
    tiers = [int(t) for t in args.edge_tiers.split(",") if t.strip()]

    # write THROUGH the registry's own store path so the header (cache
    # version, jax version, kernel-set hash) and the atomic-replace
    # discipline are exactly what _read_cache_rows validates
    reg = KernelRegistry(cache_path=out)
    stored = 0
    for n_pad in buckets:
        for tier in tiers:
            e_pad = n_pad * tier
            eligible = _eligibility("dense", n_pad, e_pad, steps)
            candidates = [k for k in KERNELS if eligible.get(k) is True]
            if "pallas" in candidates and not pallas_supported():
                candidates.remove("pallas")
            if "segscan" in candidates and n_pad < _segscan_min():
                candidates.remove("segscan")
            if candidates == ["xla"]:
                continue  # nothing to race — the default row needs no seed
            timings = _time_candidates(n_pad, e_pad, steps, candidates)
            row = KernelRow(
                variant="dense", n_pad=n_pad, e_pad=e_pad, steps=steps,
                backend=backend, winner=_pick_winner(timings),
                source="timed", eligible=eligible, timings_ms=timings,
            )
            key = f"dense:{n_pad}:{e_pad}:{steps}:{backend}"
            reg._store_cached(key, row)
            stored += 1
            print(f"  {key:<28} winner={row.winner:<9} "
                  f"{ {k: v for k, v in timings.items()} }")

    # round-trip through the validating reader — a header mismatch here
    # means the file would be dead weight in the tree
    rows = KernelRegistry._read_cache_rows(out)
    if stored and not rows:
        print(f"FATAL: {out} failed its own header validation", file=sys.stderr)
        return 1
    with open(out, encoding="utf-8") as f:
        size = len(f.read())
    print(f"wrote {out}: {len(rows or {})} rows, {size} bytes "
          f"(backend={backend}, jax pinned in header)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
