"""Per-seed accuracy autopsy for the cascade benchmark (VERDICT r2 item 3).

``bench.py`` counts hit@1/hit@3 per cascade mode and discards the per-seed
outcomes, so a sub-1.0 cell (adversarial 0.93 in BENCH_r02) carries no
information about WHICH cascades fail or why.  This tool reruns a mode over
an explicit seed band and, for every miss, dumps the full story:

- the true root and the service that outranked it,
- the winner's role in the cascade (decoy / victim at hop h / background —
  the generator now records decoys and hop distances for exactly this),
- both services' nonzero feature channels by name,
- the score decomposition (a, h, u, m, score) for both, straight from the
  engine's diagnostic stack,
- the root's rank and the margin it lost by.

Failures are then bucketed into a taxonomy (decoy_outranks_root /
victim_outranks_root / root_suppressed_by_upstream / root_signal_dropped)
so a scoring fix can target the dominant bucket and be validated on a
DISJOINT seed band (``--seeds 2000:2060`` vs the bench's 1000:1015).

Usage:
    python tools/accuracy_report.py --mode adversarial --seeds 1000:1060
    python tools/accuracy_report.py --mode all --json autopsy.json

Runs fine on CPU (`JAX_PLATFORMS=cpu`); accuracy is backend-independent.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.append(_REPO_ROOT)

from rca_tpu.cluster.generator import CASCADE_MODES, synthetic_cascade_arrays
from rca_tpu.engine import GraphEngine
from rca_tpu.engine.propagate import PropagationParams
from rca_tpu.features.schema import SERVICE_FEATURE_NAMES

INF = np.iinfo(np.int32).max


def _feature_row(feats: np.ndarray, i: int, thresh: float = 0.05) -> dict:
    row = feats[i]
    return {
        SERVICE_FEATURE_NAMES[c]: round(float(row[c]), 3)
        for c in range(len(row))
        if row[c] >= thresh
    }


def _role(case, i: int) -> str:
    """Classify a service's role in the generated cascade."""
    if i in set(case.roots.tolist()):
        return "root"
    if case.decoys is not None and i in set(case.decoys.tolist()):
        return "decoy"
    if case.hops is not None and case.hops[i] < INF:
        return f"victim_hop{int(case.hops[i])}"
    return "background"


def _classify(miss: dict) -> str:
    """Failure taxonomy for one missed cascade."""
    role = miss["winner"]["role"]
    root = miss["root"]
    if role == "decoy":
        return "decoy_outranks_root"
    if role.startswith("victim"):
        return "victim_outranks_root"
    # root lost to a background service: either its signal was dropped
    # (missing_signals zeroed the hard channels) or explain-away ate it
    if root["decomp"]["score"] < root["decomp"]["a"] * 0.7:
        return "root_suppressed_by_upstream"
    return "root_signal_dropped"


def autopsy_mode(
    mode: str,
    seeds: range,
    n: int = 500,
    params: PropagationParams | None = None,
    k: int = 5,
    fault_mix: str = "crash",
) -> dict:
    engine = GraphEngine(params=params)
    n_roots = 3 if mode == "overlapping_roots" else 1
    misses = []
    hits1 = hits3 = 0
    for seed in seeds:
        case = synthetic_cascade_arrays(n, n_roots=n_roots, seed=seed,
                                        mode=mode, fault_mix=fault_mix)
        res = engine.analyze_case(case, k=k)
        roots = set(case.roots.tolist())
        order = np.argsort(-res.score)
        hit1 = int(order[0]) in roots
        hits1 += hit1
        hits3 += bool(roots & set(order[:3].tolist()))
        if hit1:
            continue
        winner = int(order[0])
        # the best-ranked true root (single-root modes: the root)
        root_ranks = {r: int(np.nonzero(order == r)[0][0]) for r in roots}
        best_root = min(root_ranks, key=root_ranks.get)

        def decomp(i: int) -> dict:
            return {
                "a": round(float(res.anomaly[i]), 4),
                "u": round(float(res.upstream[i]), 4),
                "m": round(float(res.impact[i]), 4),
                "score": round(float(res.score[i]), 4),
            }

        miss = {
            "seed": seed,
            "winner": {
                "index": winner,
                "role": _role(case, winner),
                "features": _feature_row(case.features, winner),
                "decomp": decomp(winner),
            },
            "root": {
                "index": int(best_root),
                "rank": root_ranks[best_root],
                "n_dependents": int(np.sum(case.dep_dst == best_root)),
                "features": _feature_row(case.features, best_root),
                "decomp": decomp(best_root),
            },
            "margin": round(
                float(res.score[winner] - res.score[best_root]), 4
            ),
        }
        miss["failure_mode"] = _classify(miss)
        misses.append(miss)
    trials = len(seeds)
    taxonomy = collections.Counter(m["failure_mode"] for m in misses)
    return {
        "mode": mode,
        "fault_mix": fault_mix,
        "n_services": n,
        "seeds": f"{seeds.start}:{seeds.stop}",
        "trials": trials,
        "hit1": round(hits1 / trials, 4),
        "hit3": round(hits3 / trials, 4),
        "failure_taxonomy": dict(taxonomy),
        "misses": misses,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--mode", default="adversarial",
                    help="cascade mode, or 'all'")
    ap.add_argument("--seeds", default="1000:1015",
                    help="start:stop seed band (bench uses 1000:1015)")
    ap.add_argument("--n", type=int, default=500)
    ap.add_argument("--fault-mix", default="crash", dest="fault_mix",
                    help="root fault archetypes: crash | mixed | oom | "
                    "image | config | pending")
    ap.add_argument("--json", help="write the full report to this path")
    ap.add_argument("--weights", help="orbax checkpoint dir (RCA_WEIGHTS)")
    args = ap.parse_args(argv)

    start, stop = (int(x) for x in args.seeds.split(":"))
    seeds = range(start, stop)
    if not len(seeds):
        ap.error(f"--seeds {args.seeds}: empty band (need start < stop)")
    params = None
    if args.weights:
        from rca_tpu.engine.train import load_params

        params = load_params(args.weights)

    modes = CASCADE_MODES if args.mode == "all" else (args.mode,)
    reports = [
        autopsy_mode(m, seeds, n=args.n, params=params,
                     fault_mix=args.fault_mix)
        for m in modes
    ]

    for rep in reports:
        print(
            f"{rep['mode']:>20}: hit@1 {rep['hit1']:.3f}  hit@3 "
            f"{rep['hit3']:.3f}  ({len(rep['misses'])} misses over "
            f"{rep['trials']} seeds)  taxonomy={rep['failure_taxonomy']}"
        )
        for m in rep["misses"]:
            w, r = m["winner"], m["root"]
            print(
                f"    seed {m['seed']}: {m['failure_mode']} — winner "
                f"#{w['index']} ({w['role']}) score={w['decomp']['score']} "
                f"vs root #{r['index']} rank={r['rank']} "
                f"score={r['decomp']['score']} (margin {m['margin']})"
            )
            print(f"      winner: a={w['decomp']['a']} u={w['decomp']['u']} "
                  f"m={w['decomp']['m']}  feats={w['features']}")
            print(f"      root:   a={r['decomp']['a']} u={r['decomp']['u']} "
                  f"m={r['decomp']['m']} deps={r['n_dependents']} "
                  f"feats={r['features']}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(reports, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
