"""bench_guard: fail the bench post-step on headline regressions.

ISSUE 12 satellite (CI/tooling): every PR re-runs ``bench.py``, but
nothing compared the new line against the repo's recorded history — a
15% tick-latency regression ships silently as long as the line still
prints.  This tool is the gate: it takes the CURRENT bench line (a file,
or ``-`` for stdin) and the LAST ``BENCH_r*.json`` committed to the repo
root, and exits nonzero when any named headline metric regressed by more
than ``--threshold`` (default 15%).

Headline metrics (all lower-is-better milliseconds):

- ``tick_ms_10k``                       — streaming tick p50 at 10k
- ``serve_throughput_2k.request_ms_p50`` — closed-loop serve p50
- ``live_sweep_capture_ms_10k``         — the capture sweep

Metrics missing on either side are reported and SKIPPED, never failed:
older rounds predate newer sections, and a bench run on different
hardware is the operator's judgment call (the report prints both
values so the call is informed).  Baseline files may be a raw bench
line or the driver's wrapper (``{"parsed": <line>, ...}``).

ISSUE 13 adds the KERNEL TABLE guard: when a shape row's engaged kernel
flips vs the last committed round's ``kernel_registry`` section without
a recorded >10% timing win for the new winner, the guard fails — the
exact failure mode being autotune noise landing as a silent kernel
regression.  Only autotuned rows (source ``timed``/``cache``) are
compared: forced/cpu-default rows flip legitimately with the env.

Usage::

    python bench.py --skip-accuracy > line.json
    python tools/bench_guard.py line.json            # exit 1 on regression
    python bench.py --skip-accuracy --guard          # same, as one step
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, Optional, Tuple

#: metric name -> key path into the bench line (all lower-is-better ms)
HEADLINE_METRICS = {
    "tick_ms_10k": ("tick_ms_10k",),
    "serve_request_ms_p50": ("serve_throughput_2k", "request_ms_p50"),
    "live_sweep_capture_ms_10k": ("live_sweep_capture_ms_10k",),
    # federation (ISSUE 15): cross-process serve p50 and the kill-leg
    # recovery wall — a regression in either means the fleet story
    # (wire hop, drain-and-reroute) got slower
    "federation_request_ms_p50": (
        "serve_federation", "request_ms_p50",
    ),
    "federation_recovery_ms": ("serve_federation", "recovery_ms"),
    # elasticmesh (ISSUE 16): serve p99 THROUGH the 2→8→2 ramp and the
    # controller's per-sweep decision wall — a regression in either
    # means scale transitions got visible to callers.  Absent in rounds
    # before 16: skipped, never failed.
    "autoscale_ramp_request_ms_p99": (
        "serve_autoscale", "ramp_request_ms_p99",
    ),
    "autoscale_scale_decision_ms_p50": (
        "serve_autoscale", "scale_decision_ms_p50",
    ),
    # planetcap (ISSUE 17): the 1M-pod soak's steady sweep tick p99 and
    # the quiet-drain p99 — a regression in either means federated
    # capture got slower at planet scale (the quiet drain is what every
    # no-change poll pays, so it is gated separately from the sweep).
    # Absent in rounds before 17: skipped, never failed.
    "planet_sweep_tick_ms_p99": (
        "planet_capture", "sweep_tick_ms_p99",
    ),
    "planet_quiet_tick_ms_p99": (
        "planet_capture", "quiet_tick_ms_p99",
    ),
}

#: metrics gated TIGHTER than the default threshold, name -> (path,
#: threshold).  causelens (ISSUE 14): attribution is lazy, so explain-off
#: serving must be within 5% of the previous round — a bigger delta means
#: the default path grew attribution work it was promised not to carry.
TIGHT_METRICS = {
    "attribution_explain_off_p50": (
        ("attribution", "explain_off_request_ms_p50"), 0.05,
    ),
    # graftspec (ISSUE 19): the lint gates every PR, so its wall time is
    # a latency budget like any other — new rules may cost at most 2x
    # the previous round's figure (threshold is fractional CHANGE, so
    # 1.00 = +100% = 2x), or the gate starts getting skipped
    "graftlint_wall_ms": (("graftlint", "wall_ms"), 1.00),
}

DEFAULT_THRESHOLD = 0.15

#: a kernel winner flip must be backed by at least this fractional
#: timing win for the new winner, or the flip reads as autotune noise
KERNEL_FLIP_WIN = 0.10

_BENCH_FILE = re.compile(r"BENCH_r(\d+)\.json$")


def _dig(line: Dict[str, Any], path: Tuple[str, ...]):
    node: Any = line
    for key in path:
        if not isinstance(node, dict):
            return None
        node = node.get(key)
    return node if isinstance(node, (int, float)) else None


def _as_line(data: Any) -> Optional[Dict[str, Any]]:
    """A bench line from either a raw line or a driver wrapper."""
    if not isinstance(data, dict):
        return None
    if isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if any(_dig(data, p) is not None for p in HEADLINE_METRICS.values()):
        return data
    return None


def latest_baseline(root: str) -> Tuple[Optional[str], Optional[Dict]]:
    """The newest parseable ``BENCH_r*.json`` under ``root`` (highest
    round number wins; unparseable or metric-free files are skipped —
    the guard compares against history, it does not validate it)."""
    candidates = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _BENCH_FILE.search(os.path.basename(path))
        if m:
            candidates.append((int(m.group(1)), path))
    for _, path in sorted(candidates, reverse=True):
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        line = _as_line(data)
        if line is not None:
            return os.path.basename(path), line
    return None, None


def _kernel_rows(line: Dict[str, Any]) -> Dict[tuple, Dict[str, Any]]:
    """Autotuned ``kernel_registry`` rows keyed by shape.  Forced and
    cpu-default rows are excluded — they flip legitimately when the env
    or host changes; the guard targets AUTOTUNE flips."""
    rows = line.get("kernel_registry")
    out: Dict[tuple, Dict[str, Any]] = {}
    if not isinstance(rows, list):
        return out
    for row in rows:
        if not isinstance(row, dict):
            continue
        if row.get("source") not in ("timed", "cache"):
            continue
        key = (row.get("variant"), row.get("n_pad"), row.get("e_pad"))
        out[key] = row
    return out


def kernel_guard(current: Dict[str, Any], baseline: Dict[str, Any],
                 win_threshold: float = KERNEL_FLIP_WIN) -> Dict[str, Any]:
    """Winner-flip gate over the kernel table (ISSUE 13 satellite):
    a shape whose engaged kernel changed vs the last committed round
    must carry a recorded timing win of more than ``win_threshold`` for
    the new winner over the old one IN THE CURRENT ROW's timings —
    otherwise the flip is indistinguishable from autotune noise and the
    guard fails.  Shapes missing on either side are skipped (new tiers,
    different hosts)."""
    cur = _kernel_rows(current)
    base = _kernel_rows(baseline)
    flips = []
    ok = True
    for key, row in cur.items():
        old = base.get(key)
        if old is None or row.get("winner") == old.get("winner"):
            continue
        timings = row.get("timings_ms") or {}
        t_new = timings.get(row.get("winner"))
        t_old = timings.get(old.get("winner"))
        justified = (
            isinstance(t_new, (int, float))
            and isinstance(t_old, (int, float))
            and t_old > 0
            and t_new < (1.0 - win_threshold) * t_old
        )
        if not justified:
            ok = False
        flips.append({
            "variant": key[0], "n_pad": key[1], "e_pad": key[2],
            "winner_was": old.get("winner"), "winner_now": row.get("winner"),
            "t_now_ms": t_new, "t_was_kernel_ms": t_old,
            "status": "justified" if justified else "unjustified-flip",
        })
    return {
        "ok": ok,
        "compared": len(set(cur) & set(base)),
        "win_threshold_pct": round(win_threshold * 100.0, 1),
        "flips": flips,
    }


def compare(current: Dict[str, Any], baseline: Dict[str, Any],
            threshold: float = DEFAULT_THRESHOLD) -> Dict[str, Any]:
    """Per-metric regression report.  ``ok`` is False iff any headline
    metric is more than ``threshold`` WORSE (higher) than baseline, or
    the kernel table records an unjustified winner flip."""
    metrics: Dict[str, Dict[str, Any]] = {}
    ok = True
    named = [
        (name, path, threshold)
        for name, path in HEADLINE_METRICS.items()
    ] + [
        (name, path, tight)
        for name, (path, tight) in TIGHT_METRICS.items()
    ]
    for name, path, gate in named:
        cur = _dig(current, path)
        base = _dig(baseline, path)
        if cur is None or base is None or base <= 0:
            metrics[name] = {
                "status": "skipped",
                "current": cur,
                "baseline": base,
                "reason": "metric missing on one side",
            }
            continue
        change = (float(cur) - float(base)) / float(base)
        regressed = change > gate
        if regressed:
            ok = False
        metrics[name] = {
            "status": "regressed" if regressed else "ok",
            "current": round(float(cur), 3),
            "baseline": round(float(base), 3),
            "threshold_pct": round(gate * 100.0, 1),
            "change_pct": round(change * 100.0, 1),
        }
    report = {
        "ok": ok,
        "threshold_pct": round(threshold * 100.0, 1),
        "metrics": metrics,
    }
    kg = kernel_guard(current, baseline)
    if kg["compared"] or kg["flips"]:
        report["kernel_table"] = kg
        report["ok"] = report["ok"] and kg["ok"]
    return report


def check_line(current: Dict[str, Any], root: str,
               threshold: float = DEFAULT_THRESHOLD) -> Dict[str, Any]:
    """The full post-step: find the last committed round and compare.
    No parseable baseline = an informational pass (first round on a
    fresh repo must not fail its own gate)."""
    name, baseline = latest_baseline(root)
    if baseline is None:
        return {"ok": True, "baseline": None,
                "reason": "no parseable BENCH_r*.json baseline"}
    report = compare(current, baseline, threshold=threshold)
    report["baseline"] = name
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_guard",
        description="compare a bench line against the last BENCH_r*.json"
    )
    parser.add_argument("current",
                        help="path to the current bench line JSON, or - "
                        "for stdin")
    parser.add_argument("--baseline", default=None,
                        help="explicit baseline file (default: highest "
                        "BENCH_r*.json under --root)")
    parser.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parser.add_argument("--threshold", type=float,
                        default=DEFAULT_THRESHOLD,
                        help="fractional regression gate (default 0.15)")
    args = parser.parse_args(argv)
    try:
        if args.current == "-":
            data = json.load(sys.stdin)
        else:
            with open(args.current, encoding="utf-8") as f:
                data = json.load(f)
    except (json.JSONDecodeError, OSError) as exc:
        print(json.dumps({"error": f"cannot read current line: {exc}"}))
        return 2
    current = _as_line(data)
    if current is None:
        print(json.dumps({"error": "current file carries no headline "
                          "metrics"}))
        return 2
    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as f:
                baseline = _as_line(json.load(f))
        except (json.JSONDecodeError, OSError) as exc:
            print(json.dumps({"error": f"cannot read baseline: {exc}"}))
            return 2
        if baseline is None:
            print(json.dumps({"error": "baseline carries no headline "
                              "metrics"}))
            return 2
        report = compare(current, baseline, threshold=args.threshold)
        report["baseline"] = args.baseline
    else:
        report = check_line(current, args.root, threshold=args.threshold)
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
