#!/usr/bin/env python3
"""Create a kind cluster with intentionally-faulted microservices.

Two profiles:

``five-service`` (default) — behavioral parity with the reference's live
test environment (reference: setup_test_cluster.py — backend busybox CPU
spin-loop :160-162, database ``sleep 30; exit 1`` restart loop :209,
api-gateway exiting on a missing required env var :256, resource-service
writing ~90MiB into a memory-backed emptyDir against a 128Mi limit
:303-310, a NetworkPolicy admitting traffic only from a nonexistent app
:329-346; kind-config.yaml:1-12).

``oom-chain-200`` — BASELINE.md row 3: ~200 pods in a dependency tree
whose root ("cache") fills a memory-backed emptyDir PAST its 128Mi limit
(the reference's :303-310 trick, pushed over the edge) so the kernel
OOM-kills it into a restart loop; every victim serves via busybox httpd
but kills its own server while its parent is unreachable, so the outage
genuinely cascades tier by tier.  Topology comes from
``rca_tpu.cluster.oomchain`` — the same source as the hermetic mock twin,
so the live cluster and the mock world cannot drift apart.

Manifests are generated programmatically; ``--dry-run`` prints them
without needing Docker, so the generator itself is testable hermetically;
``--measure`` runs the BASELINE row-3 measurement (end-to-end analyze
latency + hit@1) against the live cluster and writes ``KIND_rNN.json``.

Usage:
    python tools/setup_test_cluster.py                     # create + deploy
    python tools/setup_test_cluster.py --profile oom-chain-200
    python tools/setup_test_cluster.py --dry-run           # print manifests
    python tools/setup_test_cluster.py --profile oom-chain-200 --measure
    python tools/setup_test_cluster.py --delete            # tear down
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Dict, List

# the oom-chain topology lives in the package so the mock twin shares it;
# APPEND (not insert-at-0) so callers that temporarily push tools/ onto
# sys.path and pop(0) afterwards don't pop the wrong entry
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.append(_REPO_ROOT)

CLUSTER_NAME = "rca-tpu-test"
NAMESPACE = "test-microservices"
PROFILES = ("five-service", "oom-chain-200")


def cluster_name(profile: str = "five-service") -> str:
    """Per-profile cluster name: the two profiles need incompatible node
    topologies (1 node vs 3), so they must not share a kind cluster — a
    reused 1-node cluster would strand ~90 of the 200 pods Pending behind
    kubelet's 110-pod cap."""
    return CLUSTER_NAME if profile == "five-service" else "rca-tpu-oom"


def kind_config(profile: str = "five-service") -> Dict[str, Any]:
    """Cluster topology per profile: the 200-pod profile needs worker
    nodes (kubelet defaults to max 110 pods per node)."""
    nodes: List[Dict[str, Any]] = [
        {
            "role": "control-plane",
            "extraPortMappings": [
                {"containerPort": 30080, "hostPort": 30080,
                 "protocol": "TCP"},
            ],
        }
    ]
    if profile == "oom-chain-200":
        nodes += [{"role": "worker"}, {"role": "worker"}]
    return {
        "kind": "Cluster",
        "apiVersion": "kind.x-k8s.io/v1alpha4",
        "name": cluster_name(profile),
        "nodes": nodes,
    }




def _workload(
    name: str,
    command: List[str],
    replicas: int = 1,
    env: List[dict] | None = None,
    env_from: List[dict] | None = None,
    requests: Dict[str, str] | None = None,
    limits: Dict[str, str] | None = None,
    volumes: List[dict] | None = None,
    volume_mounts: List[dict] | None = None,
    namespace: str = NAMESPACE,
) -> Dict[str, Any]:
    container: Dict[str, Any] = {
        "name": name,
        "image": "busybox:1.36",
        "command": command,
        "resources": {
            "requests": requests or {"cpu": "50m", "memory": "64Mi"},
            "limits": limits or {"cpu": "200m", "memory": "128Mi"},
        },
    }
    if env:
        container["env"] = env
    if env_from:
        container["envFrom"] = env_from
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    spec: Dict[str, Any] = {"containers": [container]}
    if volumes:
        spec["volumes"] = volumes
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"app": name}},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": spec,
            },
        },
    }


def _service(name: str, port: int = 80,
             namespace: str = NAMESPACE) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def build_manifests() -> List[Dict[str, Any]]:
    """The 5-service faulted world as Kubernetes manifests."""
    manifests: List[Dict[str, Any]] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": NAMESPACE}},
    ]

    # frontend: healthy, 2 replicas, talks to api-gateway
    manifests.append(
        _workload(
            "frontend",
            ["sh", "-c", "while true; do sleep 30; done"],
            replicas=2,
            env=[{"name": "API_URL",
                  "value": f"http://api-gateway.{NAMESPACE}.svc"
                  ":80"}],
        )
    )
    # backend: CPU spin-loop (high CPU fault), depends on database
    manifests.append(
        _workload(
            "backend",
            ["sh", "-c",
             "while true; do echo spin | md5sum > /dev/null; done"],
            env=[{"name": "DATABASE_URL",
                  "value": f"http://database.{NAMESPACE}.svc:5432"}],
            limits={"cpu": "200m", "memory": "128Mi"},
        )
    )
    # database: restart loop (exits 1 after 30s)
    manifests.append(
        _workload(
            "database",
            ["sh", "-c",
             "echo 'INFO: Starting database...'; sleep 30; "
             "echo 'ERROR: Database initialization failed'; exit 1"],
        )
    )
    # api-gateway: requires an env var that is never provided
    manifests.append(
        _workload(
            "api-gateway",
            ["sh", "-c",
             'if [ -z "$REQUIRED_API_KEY" ]; then '
             "echo 'ERROR: Missing required environment variable'; exit 1; "
             "fi; while true; do sleep 30; done"],
            env=[{"name": "BACKEND_URL",
                  "value": f"http://backend.{NAMESPACE}.svc:8080"}],
        )
    )
    # resource-service: fills a memory-backed emptyDir near its limit
    manifests.append(
        _workload(
            "resource-service",
            ["sh", "-c",
             "dd if=/dev/zero of=/scratch/fill bs=1M count=90; "
             "while true; do sleep 30; done"],
            limits={"cpu": "100m", "memory": "128Mi"},
            volumes=[{"name": "scratch",
                      "emptyDir": {"medium": "Memory"}}],
            volume_mounts=[{"name": "scratch", "mountPath": "/scratch"}],
        )
    )
    for svc in ("frontend", "backend", "database", "api-gateway",
                "resource-service"):
        manifests.append(_service(svc))

    # NetworkPolicy admitting backend ingress only from a nonexistent app
    manifests.append(
        {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": {"name": "backend-network-policy",
                         "namespace": NAMESPACE},
            "spec": {
                "podSelector": {"matchLabels": {"app": "backend"}},
                "policyTypes": ["Ingress"],
                "ingress": [
                    {"from": [{"podSelector": {
                        "matchLabels": {"app": "non-existent-service"}
                    }}]}
                ],
            },
        }
    )
    return manifests


def build_oom_chain_manifests(n_pods: int = 200) -> List[Dict[str, Any]]:
    """BASELINE.md row 3: the ~200-pod OOMKill cascade.

    Root: PID 1 is the memory hog (``exec dd`` of 150MiB into a
    memory-backed emptyDir against a 128Mi limit), so the cgroup OOM kill
    lands on the container itself — status OOMKilled / exit 137 / restart
    loop, not a silently-killed child process.  Victims: serve ``ok`` via
    busybox httpd, probe their parent every 5s, and KILL their own httpd
    while the parent is unreachable (restarting it when the parent
    returns) — the outage cascades tier by tier down the dependency tree
    and every victim logs connection-refused errors against its parent.
    """
    from rca_tpu.cluster.oomchain import OOM_NS, OOM_ROOT, oom_chain_topology

    services, parent, replicas = oom_chain_topology(n_pods)
    manifests: List[Dict[str, Any]] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": OOM_NS}},
    ]
    # the root SERVES during its warm window (httpd daemonizes into the
    # background) so its children are healthy until the OOM kill lands —
    # otherwise the cascade would exist from deploy time and be
    # indistinguishable from a service with no endpoints.  `exec dd`
    # makes the memory hog PID 1: when the cgroup OOMs, dd dies (directly,
    # or after the killer first takes the tiny httpd and the still-filling
    # dd immediately re-triggers), the container exits 137/OOMKilled, and
    # each CrashLoopBackOff restart brings httpd back for another warm
    # window — the outage oscillates with the OOMKill loop, genuinely
    # OOM-driven.
    manifests.append(
        _workload(
            OOM_ROOT,
            ["sh", "-c",
             "mkdir -p /www; echo ok > /www/index.html; "
             "httpd -p 80 -h /www; "
             "echo 'INFO: cache warming...'; sleep 20; "
             "echo 'INFO: loading 150MiB working set'; "
             "exec dd if=/dev/zero of=/scratch/fill bs=1M count=150"],
            replicas=replicas[OOM_ROOT],
            requests={"cpu": "50m", "memory": "64Mi"},
            limits={"cpu": "100m", "memory": "128Mi"},
            volumes=[{"name": "scratch", "emptyDir": {"medium": "Memory"}}],
            volume_mounts=[{"name": "scratch", "mountPath": "/scratch"}],
            namespace=OOM_NS,
        )
    )
    victim_script = (
        "mkdir -p /www; echo ok > /www/index.html; "
        "httpd -p 80 -h /www; "
        "while true; do "
        'if wget -q -T 2 -O /dev/null "$PARENT_URL"; then '
        "pidof httpd >/dev/null || httpd -p 80 -h /www; "
        "else "
        'echo "ERROR: connection refused to $PARENT_URL (ECONNREFUSED)"; '
        "killall httpd 2>/dev/null; "
        "fi; sleep 5; done"
    )
    for svc in services:
        if svc == OOM_ROOT:
            continue
        up = parent[svc]
        manifests.append(
            _workload(
                svc,
                ["sh", "-c", victim_script],
                replicas=replicas[svc],
                env=[{"name": "PARENT_URL",
                      "value": f"http://{up}.{OOM_NS}.svc.cluster.local:80"}],
                requests={"cpu": "10m", "memory": "16Mi"},
                limits={"cpu": "100m", "memory": "64Mi"},
                namespace=OOM_NS,
            )
        )
    for svc in services:
        manifests.append(_service(svc, namespace=OOM_NS))
    return manifests


def oom_chain_expected_findings() -> List[Dict[str, str]]:
    from rca_tpu.cluster.oomchain import OOM_ROOT

    return [
        {"component": OOM_ROOT,
         "expect": "OOMKilled restart loop: 150MiB memory-backed volume "
                   "fill against a 128Mi limit (exit 137)"},
        {"component": "svc-000",
         "expect": "connection-refused probe errors against the cache "
                   "parent (first cascade tier)"},
    ]


def expected_findings() -> List[Dict[str, str]]:
    """What an analyzer must surface on this environment (the regression
    oracle; reference: setup_test_cluster.py:382-398)."""
    return [
        {"component": "database",
         "expect": "CrashLoopBackOff restart loop, exit code 1"},
        {"component": "api-gateway",
         "expect": "container exits on missing REQUIRED_API_KEY env var"},
        {"component": "backend",
         "expect": "CPU saturation near its 200m limit (spin loop)"},
        {"component": "resource-service",
         "expect": "memory-backed volume filled to ~90Mi of a 128Mi limit"},
        {"component": "backend-network-policy",
         "expect": "ingress 'from' selector matches no existing app"},
    ]


def _to_yaml(docs: List[Dict[str, Any]]) -> str:
    try:
        import yaml

        return "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
    except ImportError:
        return "\n".join(json.dumps(d) for d in docs)


def profile_parts(profile: str, n_pods: int = 200) -> Dict[str, Any]:
    """Everything profile-specific in one place."""
    if profile == "oom-chain-200":
        from rca_tpu.cluster.oomchain import OOM_NS, OOM_ROOT

        return {
            "manifests": build_oom_chain_manifests(n_pods),
            "namespace": OOM_NS,
            "oracle": oom_chain_expected_findings(),
            "root_app": OOM_ROOT,
            "require_reason": "OOMKilled",
            "metric": "oom_chain_200_analyze",
            # _live: KIND_r03.json is the committed hermetic-mock
            # placeholder BASELINE.md quotes — a live measurement must
            # never silently overwrite it
            "out": "KIND_r03_live.json",
        }
    return {
        "manifests": build_manifests(),
        "namespace": NAMESPACE,
        "oracle": expected_findings(),
        "root_app": "database",
        "require_reason": None,
        "metric": "five_service_analyze",
        "out": "KIND_five_service.json",
    }


def wait_for_fault(namespace: str, root_app: str,
                   deadline_s: int = 600,
                   require_reason: str | None = None,
                   settle_s: int = 60) -> bool:
    """Block until the profile's crashing root has restarted at least
    once (both profiles' roots crash-loop: the five-service database
    exits 1, the oom-chain cache is OOMKilled — pass
    ``require_reason="OOMKilled"`` to insist on the kill reason), then
    settle ``settle_s`` so the cascade/metrics manifest.  Measuring a
    just-applied namespace would record a healthy cluster as the row-3
    baseline.  This is the ONE wait protocol — the opt-in kind test and
    ``--measure`` both use it, so their criteria cannot drift."""
    import time

    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            out = subprocess.run(
                ["kubectl", "get", "pods", "-n", namespace,
                 "-l", f"app={root_app}",
                 "-o", "jsonpath={range .items[*]}"
                 "{.status.containerStatuses[0].restartCount} "
                 "{.status.containerStatuses[0].lastState.terminated"
                 ".reason}\n{end}"],
                capture_output=True, text=True,
                # without this, a hung API server (plausible under
                # 200-pod memory pressure) makes deadline_s meaningless
                timeout=60,
            ).stdout
        except subprocess.TimeoutExpired:
            out = ""
        for line in out.splitlines():
            parts = line.split()
            if not parts or not parts[0].isdigit() or int(parts[0]) < 1:
                continue
            if require_reason and (
                len(parts) < 2 or parts[1] != require_reason
            ):
                continue
            time.sleep(settle_s)
            return True
        time.sleep(15)
    return False


def run_measurement(namespace: str, expected_root: str, out_path: str,
                    metric: str, root_app: str,
                    wait: bool = True,
                    require_reason: str | None = None) -> int:
    """BASELINE.md row-3 hook: end-to-end analyze latency + hit@1 against
    the LIVE cluster, recorded as one JSON file for the judge."""
    from rca_tpu.cluster.k8s_client import K8sApiClient
    from rca_tpu.cluster.oomchain import measure_analyze

    client = K8sApiClient()
    if not client.is_connected():
        print("no reachable cluster for --measure", file=sys.stderr)
        return 1
    if wait and not wait_for_fault(
        namespace, root_app, require_reason=require_reason
    ):
        print(f"fault never manifested on {root_app} in {namespace}; "
              "not recording a healthy-cluster measurement",
              file=sys.stderr)
        return 1
    result = measure_analyze(client, namespace, expected_root)
    result["metric"] = metric
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return 0 if result["status"] == "completed" else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--profile", choices=PROFILES, default=None,
                    help="default five-service; with --delete, an explicit "
                    "profile scopes the teardown to that cluster only")
    ap.add_argument("--pods", type=int, default=200,
                    help="pod budget for the oom-chain profile")
    ap.add_argument("--dry-run", action="store_true",
                    help="print manifests and expected findings; no cluster")
    ap.add_argument("--delete", action="store_true",
                    help="delete the kind cluster")
    ap.add_argument("--measure", action="store_true",
                    help="run the BASELINE row-3 measurement against the "
                    "live cluster (after deploy, or alone on an existing "
                    "cluster) and write --out")
    ap.add_argument("--out", default=None,
                    help="measurement output path (with --measure); "
                    "defaults to the profile's KIND_*.json")
    ap.add_argument("--measure-only", action="store_true",
                    help="skip deploy; only measure an existing cluster")
    args = ap.parse_args(argv)

    if args.delete:
        # bare --delete tears down EVERY profile's cluster (the profiles
        # use distinct kind clusters, so a user who created oom-chain-200
        # and then ran the docstring's bare `--delete` would otherwise
        # leave the 200-pod cluster running); an explicit --profile scopes
        # the teardown to that one cluster
        names = (
            [cluster_name(args.profile)] if args.profile
            else sorted({cluster_name(pr) for pr in PROFILES})
        )
        rc = 0
        for n in names:
            rc = subprocess.call(
                ["kind", "delete", "cluster", "--name", n]
            ) or rc
        return rc

    args.profile = args.profile or "five-service"
    name = cluster_name(args.profile)

    p = profile_parts(args.profile, args.pods)
    # anchor the default to the repo root (where BASELINE.md points the
    # reader), not the caller's cwd
    out_path = args.out or os.path.join(_REPO_ROOT, p["out"])
    if args.dry_run:
        print(_to_yaml([kind_config(args.profile)]))
        print("---")
        print(_to_yaml(p["manifests"]))
        print("--- expected findings ---", file=sys.stderr)
        print(json.dumps(p["oracle"], indent=2), file=sys.stderr)
        return 0

    if args.measure_only:
        return run_measurement(
            p["namespace"], p["oracle"][0]["component"], out_path,
            p["metric"], p["root_app"],
            require_reason=p["require_reason"],
        )

    if shutil.which("kind") is None or shutil.which("kubectl") is None:
        print("kind/kubectl not found — run with --dry-run to inspect "
              "manifests", file=sys.stderr)
        return 1
    profile_cfg = kind_config(args.profile)
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(_to_yaml([profile_cfg]))
        kind_cfg = f.name
    existing = subprocess.run(
        ["kind", "get", "clusters"], capture_output=True, text=True
    ).stdout.split()
    if name not in existing:
        rc = subprocess.call(
            ["kind", "create", "cluster", "--config", kind_cfg]
        )
        if rc:
            return rc
    else:
        # a reused cluster must satisfy the profile's node topology: the
        # 200-pod profile on a 1-node cluster leaves ~90 pods Pending
        # behind kubelet's 110-pod cap and records a broken cascade
        have = len(subprocess.run(
            ["kind", "get", "nodes", "--name", name],
            capture_output=True, text=True,
        ).stdout.split())
        need = len(profile_cfg["nodes"])
        if have < need:
            print(f"existing cluster {name} has {have} node(s); "
                  f"profile {args.profile} needs {need}. Run --delete "
                  "first to recreate with the right topology.",
                  file=sys.stderr)
            return 1
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(_to_yaml(p["manifests"]))
        manifest_path = f.name
    rc = subprocess.call(["kubectl", "apply", "-f", manifest_path])
    if rc == 0:
        print(json.dumps(
            {"cluster": name, "namespace": p["namespace"],
             "profile": args.profile,
             "expected_findings": p["oracle"]},
            indent=2,
        ))
        if args.measure:
            return run_measurement(
                p["namespace"], p["oracle"][0]["component"], out_path,
                p["metric"], p["root_app"],
                require_reason=p["require_reason"],
            )
    return rc


if __name__ == "__main__":
    sys.exit(main())
