#!/usr/bin/env python3
"""Create a kind cluster with 5 intentionally-faulted microservices.

Behavioral parity with the reference's live test environment (reference:
setup_test_cluster.py — backend busybox CPU spin-loop :160-162, database
``sleep 30; exit 1`` restart loop :209, api-gateway exiting on a missing
required env var :256, resource-service writing ~90MiB into a memory-backed
emptyDir against a 128Mi limit :303-310, a NetworkPolicy admitting traffic
only from a nonexistent app :329-346; kind-config.yaml:1-12) — with the
manifests generated programmatically and a ``--dry-run`` mode that prints
them without needing Docker, so the generator itself is testable hermetically.

Usage:
    python tools/setup_test_cluster.py                 # create + deploy
    python tools/setup_test_cluster.py --dry-run       # print manifests
    python tools/setup_test_cluster.py --delete        # tear down
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Dict, List

CLUSTER_NAME = "rca-tpu-test"
NAMESPACE = "test-microservices"

KIND_CONFIG: Dict[str, Any] = {
    "kind": "Cluster",
    "apiVersion": "kind.x-k8s.io/v1alpha4",
    "name": CLUSTER_NAME,
    "nodes": [
        {
            "role": "control-plane",
            "extraPortMappings": [
                {"containerPort": 30080, "hostPort": 30080,
                 "protocol": "TCP"},
            ],
        }
    ],
}


def _workload(
    name: str,
    command: List[str],
    replicas: int = 1,
    env: List[dict] | None = None,
    env_from: List[dict] | None = None,
    requests: Dict[str, str] | None = None,
    limits: Dict[str, str] | None = None,
    volumes: List[dict] | None = None,
    volume_mounts: List[dict] | None = None,
) -> Dict[str, Any]:
    container: Dict[str, Any] = {
        "name": name,
        "image": "busybox:1.36",
        "command": command,
        "resources": {
            "requests": requests or {"cpu": "50m", "memory": "64Mi"},
            "limits": limits or {"cpu": "200m", "memory": "128Mi"},
        },
    }
    if env:
        container["env"] = env
    if env_from:
        container["envFrom"] = env_from
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    spec: Dict[str, Any] = {"containers": [container]}
    if volumes:
        spec["volumes"] = volumes
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": NAMESPACE,
                     "labels": {"app": name}},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": spec,
            },
        },
    }


def _service(name: str, port: int = 80) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def build_manifests() -> List[Dict[str, Any]]:
    """The 5-service faulted world as Kubernetes manifests."""
    manifests: List[Dict[str, Any]] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": NAMESPACE}},
    ]

    # frontend: healthy, 2 replicas, talks to api-gateway
    manifests.append(
        _workload(
            "frontend",
            ["sh", "-c", "while true; do sleep 30; done"],
            replicas=2,
            env=[{"name": "API_URL",
                  "value": f"http://api-gateway.{NAMESPACE}.svc"
                  ":80"}],
        )
    )
    # backend: CPU spin-loop (high CPU fault), depends on database
    manifests.append(
        _workload(
            "backend",
            ["sh", "-c",
             "while true; do echo spin | md5sum > /dev/null; done"],
            env=[{"name": "DATABASE_URL",
                  "value": f"http://database.{NAMESPACE}.svc:5432"}],
            limits={"cpu": "200m", "memory": "128Mi"},
        )
    )
    # database: restart loop (exits 1 after 30s)
    manifests.append(
        _workload(
            "database",
            ["sh", "-c",
             "echo 'INFO: Starting database...'; sleep 30; "
             "echo 'ERROR: Database initialization failed'; exit 1"],
        )
    )
    # api-gateway: requires an env var that is never provided
    manifests.append(
        _workload(
            "api-gateway",
            ["sh", "-c",
             'if [ -z "$REQUIRED_API_KEY" ]; then '
             "echo 'ERROR: Missing required environment variable'; exit 1; "
             "fi; while true; do sleep 30; done"],
            env=[{"name": "BACKEND_URL",
                  "value": f"http://backend.{NAMESPACE}.svc:8080"}],
        )
    )
    # resource-service: fills a memory-backed emptyDir near its limit
    manifests.append(
        _workload(
            "resource-service",
            ["sh", "-c",
             "dd if=/dev/zero of=/scratch/fill bs=1M count=90; "
             "while true; do sleep 30; done"],
            limits={"cpu": "100m", "memory": "128Mi"},
            volumes=[{"name": "scratch",
                      "emptyDir": {"medium": "Memory"}}],
            volume_mounts=[{"name": "scratch", "mountPath": "/scratch"}],
        )
    )
    for svc in ("frontend", "backend", "database", "api-gateway",
                "resource-service"):
        manifests.append(_service(svc))

    # NetworkPolicy admitting backend ingress only from a nonexistent app
    manifests.append(
        {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": {"name": "backend-network-policy",
                         "namespace": NAMESPACE},
            "spec": {
                "podSelector": {"matchLabels": {"app": "backend"}},
                "policyTypes": ["Ingress"],
                "ingress": [
                    {"from": [{"podSelector": {
                        "matchLabels": {"app": "non-existent-service"}
                    }}]}
                ],
            },
        }
    )
    return manifests


def expected_findings() -> List[Dict[str, str]]:
    """What an analyzer must surface on this environment (the regression
    oracle; reference: setup_test_cluster.py:382-398)."""
    return [
        {"component": "database",
         "expect": "CrashLoopBackOff restart loop, exit code 1"},
        {"component": "api-gateway",
         "expect": "container exits on missing REQUIRED_API_KEY env var"},
        {"component": "backend",
         "expect": "CPU saturation near its 200m limit (spin loop)"},
        {"component": "resource-service",
         "expect": "memory-backed volume filled to ~90Mi of a 128Mi limit"},
        {"component": "backend-network-policy",
         "expect": "ingress 'from' selector matches no existing app"},
    ]


def _to_yaml(docs: List[Dict[str, Any]]) -> str:
    try:
        import yaml

        return "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
    except ImportError:
        return "\n".join(json.dumps(d) for d in docs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true",
                    help="print manifests and expected findings; no cluster")
    ap.add_argument("--delete", action="store_true",
                    help="delete the kind cluster")
    args = ap.parse_args(argv)

    if args.delete:
        return subprocess.call(
            ["kind", "delete", "cluster", "--name", CLUSTER_NAME]
        )

    manifests = build_manifests()
    if args.dry_run:
        print(_to_yaml([KIND_CONFIG]))
        print("---")
        print(_to_yaml(manifests))
        print("--- expected findings ---", file=sys.stderr)
        print(json.dumps(expected_findings(), indent=2), file=sys.stderr)
        return 0

    if shutil.which("kind") is None or shutil.which("kubectl") is None:
        print("kind/kubectl not found — run with --dry-run to inspect "
              "manifests", file=sys.stderr)
        return 1
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(_to_yaml([KIND_CONFIG]))
        kind_cfg = f.name
    existing = subprocess.run(
        ["kind", "get", "clusters"], capture_output=True, text=True
    ).stdout.split()
    if CLUSTER_NAME not in existing:
        rc = subprocess.call(
            ["kind", "create", "cluster", "--config", kind_cfg]
        )
        if rc:
            return rc
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(_to_yaml(manifests))
        manifest_path = f.name
    rc = subprocess.call(["kubectl", "apply", "-f", manifest_path])
    if rc == 0:
        print(json.dumps(
            {"cluster": CLUSTER_NAME, "namespace": NAMESPACE,
             "expected_findings": expected_findings()}, indent=2,
        ))
    return rc


if __name__ == "__main__":
    sys.exit(main())
