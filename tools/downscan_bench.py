"""Down-scan (impact) step-cost breakdown + candidate kernels at 10k/50k.

The 50k propagation is bound by the 8 serial down-scan steps
(PERF.md edge-layout study: ~33 ns/edge attributed to TPU scatter
serialization).  VERDICT r3 item 1 asks for either a log-depth operator
doubling or a Pallas dst-sorted segment-scan.  Doubling loses on paper —
reaching depth 8 needs |A^<=8| = 13.9x the edges at 50k (measured on the
generator), and scatter cost is per-edge — so before building anything
this script ATTRIBUTES the step cost:

- ``coo``        : the production step (gather src + scatter-add dst).
- ``gather_only``: same chain with the scatter replaced by a cheap
  reduction — isolates the E-sized gather's share.
- ``scatter_only``: same chain with the gather replaced by a broadcast —
  isolates the scatter's share.
- ``xla_cumsum`` : dst-sorted edges, jnp.cumsum + boundary gather
  (the round-3 rejected candidate, as the XLA reference point).
- ``pallas_cumsum``: dst-sorted edges, single-pass in-VMEM Pallas cumsum
  + boundary gather (the round-4 candidate: one kernel, no log-depth HBM
  passes, no per-edge serialization).

Every variant runs the REAL 8-step serial recursion (each step consumes
the previous step's m), timed by the marginal method (t_2R - t_R)/R with
fori_loop reps and per-dispatch salt, synced through a fetch — the same
methodology as bench.py / PERF.md.  Parity vs the coo step is asserted
before timing (1e-4 tolerance; cumsum reassociates float adds).

Run on the real TPU:  python tools/downscan_bench.py --n 50000
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.append(_REPO_ROOT)

import jax
import jax.numpy as jnp

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.config import RCAConfig, bucket_for

LANES = 128
SUBLANES = 8


# The production kernel (one definition): the engine's segmented scan.
# This tool originally carried the prototype; it now measures the SAME
# kernel the engine ships so the benchmark cannot drift from production
# semantics (round-4 review finding).
from rca_tpu.engine.segscan import pallas_segscan  # noqa: E402


# ---------------------------------------------------------------------------
# step variants (all compute m_{k+1} from m_k with the SAME semantics)
# ---------------------------------------------------------------------------

def make_variants(n_pad, e_pad, case):
    """Returns dict name -> (step_fn(m, aux) -> m_new, aux) plus the
    dst-sorted metadata shared by the cumsum variants."""
    dummy = n_pad - 1
    src = np.full(e_pad, dummy, np.int32)
    dst = np.full(e_pad, dummy, np.int32)
    src[: len(case.dep_src)] = case.dep_src
    dst[: len(case.dep_dst)] = case.dep_dst

    # dst-sorted copies + per-service boundary rows (padded edges land in
    # the dummy service's run, whose output row is zeroed anyway)
    order = np.argsort(dst, kind="stable")
    src_sorted = src[order]
    dst_sorted = dst[order]
    counts = np.bincount(dst_sorted, minlength=n_pad)
    ends = np.cumsum(counts)            # [n_pad] end position per service
    starts = ends - counts

    rng = np.random.default_rng(0)
    a_ex = jnp.asarray(
        np.maximum(rng.uniform(-0.5, 0.8, n_pad), 0.0), jnp.float32
    )
    deg = np.maximum(counts, 1.0).astype(np.float32)
    inv_deg = jnp.asarray(1.0 / deg)
    decay = 0.7

    sj = jnp.asarray(src)
    dj = jnp.asarray(dst)
    ssj = jnp.asarray(src_sorted)
    startsj = jnp.asarray(np.maximum(ends - counts, 0).astype(np.int32))
    endsj = jnp.asarray((ends - 1).clip(0).astype(np.int32))
    has_edges = jnp.asarray((counts > 0).astype(np.float32))

    def coo_step(m):
        vals = a_ex[sj] + decay * m[sj]
        return jnp.zeros_like(m).at[dj].add(vals) * inv_deg

    def gather_only_step(m):
        vals = a_ex[sj] + decay * m[sj]
        # fold the gathered values without a scatter: keeps the serial
        # dependence and the gather, drops the scatter
        return (m + vals.sum() * 1e-9) * (inv_deg * 0 + 1.0) * 0.99 + (
            a_ex * 0.01
        )

    def scatter_only_step(m):
        # no gather: edge values derived from a scalar of m (serial dep)
        vals = a_ex[:e_pad] if e_pad <= n_pad else jnp.pad(
            a_ex, (0, e_pad - n_pad)
        )
        vals = vals + m.sum() * 1e-9
        return jnp.zeros_like(m).at[dj].add(vals) * inv_deg

    def xla_cumsum_step(m):
        vals = a_ex[ssj] + decay * m[ssj]
        c = jnp.cumsum(vals)
        seg = jnp.where(
            has_edges > 0, c[endsj] - jnp.where(startsj > 0,
                                                c[startsj - 1], 0.0), 0.0
        )
        return seg * inv_deg

    # segment-start flags for the segmented scan (first edge of each
    # service's dst-sorted run)
    flags = np.zeros(e_pad, np.float32)
    flags[np.maximum(ends - counts, 0)[counts > 0]] = 1.0
    flagsj = jnp.asarray(flags)

    def pallas_segscan_step(m):
        vals = a_ex[ssj] + decay * m[ssj]
        s = pallas_segscan(vals, flagsj)
        # S at each segment's LAST edge is the segment total — no
        # subtraction, no cross-segment accumulation
        seg = jnp.where(has_edges > 0, s[endsj], 0.0)
        return seg * inv_deg

    return {
        "coo": coo_step,
        "gather_only": gather_only_step,
        "scatter_only": scatter_only_step,
        "xla_cumsum": xla_cumsum_step,
        "pallas_segscan": pallas_segscan_step,
    }, a_ex


def chain(step_fn, steps=8):
    """reps x (8-step chain) inside one jit — the tunnel RTT (~90-115 ms
    per dispatch) dwarfs device compute, so only the marginal
    (t_2R - t_R)/R isolates the chain cost (PERF.md methodology)."""
    def make(reps):
        @jax.jit
        def run(m0, salt):
            def rep_body(j, m):
                def body(i, m):
                    return step_fn(m * (1.0 + salt + j * 1e-9 + i * 1e-9))
                return jax.lax.fori_loop(0, steps, body, m0 + m * 1e-9)
            return jax.lax.fori_loop(0, reps, rep_body, m0)
        return run
    return make


def marginal_chain_ms(make, m0, reps=8, outer=8):
    """Marginal cost of ONE 8-step chain: (min t_2R - min t_R) / R."""

    def min_total(r):
        run = make(r)
        jax.device_get(run(m0, jnp.float32(1e-7))[:4])
        outs = []
        for j in range(outer):
            salt = jnp.float32((j + 2) * 1e-7)
            t0 = time.perf_counter()
            jax.device_get(run(m0, salt)[:4])
            outs.append((time.perf_counter() - t0) * 1e3)
        return float(np.min(outs))

    for _ in range(3):
        t_r = min_total(reps)
        t_2r = min_total(2 * reps)
        if t_2r > t_r:
            return (t_2r - t_r) / reps
        reps *= 4
    return float("nan")


# ---------------------------------------------------------------------------
# registry kernel A/B (ISSUE 13 satellite): the full propagation chain
# under EVERY registry kernel, per tier — the table PERF.md round 13
# cites and bench.py's `kernel_ab` section embeds
# ---------------------------------------------------------------------------

def _kernel_chain_ms(kernel, n_pad, e_pad, case, steps, reps=8):
    """Amortized full-chain timing for one kernel over the REAL cascade
    graph at this tier (evidence + both scans via propagate_auto — the
    same traced body production dispatches), marginal-rep methodology.
    Returns None when the kernel cannot build/run at this tier."""
    import jax
    import jax.numpy as jnp

    from rca_tpu.engine.runner import propagate_auto, up_ell_for

    dummy = n_pad - 1
    src = np.full(e_pad, dummy, np.int32)
    dst = np.full(e_pad, dummy, np.int32)
    src[: len(case.dep_src)] = case.dep_src
    dst[: len(case.dep_dst)] = case.dep_dst
    edges = jnp.asarray(np.stack([src, dst]))
    f = np.zeros((n_pad, case.features.shape[1]), np.float32)
    f[: case.n] = case.features
    fj = jnp.asarray(f)
    from rca_tpu.engine.propagate import default_params

    p = default_params(steps)
    aw, hw = p.weight_arrays()
    down_seg = up_seg = up_ell = dbl = None
    try:
        if kernel == "segscan":
            from rca_tpu.engine.segscan import build_seg_layouts

            down_seg, up_seg = build_seg_layouts(
                n_pad, e_pad, case.dep_src, case.dep_dst
            )
        elif kernel == "doubling":
            from rca_tpu.engine.doubling import build_doubling

            dbl = build_doubling(
                n_pad, e_pad, case.dep_src, case.dep_dst, steps
            )
            if dbl is None:
                return None  # frontier cap declined this graph
        else:
            up_ell = up_ell_for(n_pad, case.dep_src, case.dep_dst)

        def make_many(reps_):
            @jax.jit
            def many(x, salt):
                def body(i, acc):
                    out = propagate_auto(
                        x * (1.0 + salt + i * 1e-7), edges, aw, hw,
                        p.steps, p.decay, p.explain_strength,
                        p.impact_bonus, up_ell=up_ell, down_seg=down_seg,
                        up_seg=up_seg, kernel=kernel, dbl=dbl,
                    )
                    return acc + out[4]
                return jax.lax.fori_loop(0, reps_, body, jnp.zeros(n_pad))
            return many

        def min_total(r):
            run = make_many(r)
            jax.device_get(run(fj, jnp.float32(1e-7))[:4])
            outs = []
            for j in range(4):
                salt = jnp.float32((j + 2) * 1e-7)
                t0 = time.perf_counter()
                jax.device_get(run(fj, salt)[:4])
                outs.append((time.perf_counter() - t0) * 1e3)
            return float(np.min(outs))

        t_r, t_2r = min_total(reps), min_total(2 * reps)
        if t_2r <= t_r:
            return None
        return (t_2r - t_r) / reps
    except Exception:
        return None


def registry_kernel_ab(tiers=(2_000, 10_000), steps: int = 8,
                       kernels=None) -> dict:
    """A/B every registry kernel per tier over the real cascade
    generator graph.  CPU-host honest: the report stamps the backend
    AND whether the Pallas kernels ran interpreted — interpret-mode
    numbers prove mechanics, not speed, and are labeled as such."""
    import jax

    from rca_tpu.engine.registry import KERNELS
    from rca_tpu.engine.segscan import interpret_mode

    kernels = tuple(kernels or KERNELS)
    backend = jax.devices()[0].platform
    out = {
        "backend": backend,
        "pallas_interpreted": bool(interpret_mode()),
        "steps": steps,
        "tiers": {},
    }
    buckets = RCAConfig().shape_buckets
    for n in tiers:
        case = synthetic_cascade_arrays(n, n_roots=3, seed=0)
        n_pad = bucket_for(n + 1, buckets)
        e_pad = bucket_for(len(case.dep_src), buckets)
        timings = {
            k: _kernel_chain_ms(k, n_pad, e_pad, case, steps)
            for k in kernels
        }
        measured = {k: t for k, t in timings.items() if t is not None}
        out["tiers"][str(n)] = {
            "n_pad": n_pad,
            "e_pad": e_pad,
            "n_edges": len(case.dep_src),
            "timings_ms": {
                k: (round(t, 4) if t is not None else None)
                for k, t in timings.items()
            },
            "fastest": (
                min(measured, key=measured.get) if measured else None
            ),
        }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=50_000)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--ab", action="store_true",
                    help="A/B the FULL chain under every registry "
                    "kernel per tier instead of the step-cost "
                    "attribution (ISSUE 13)")
    ap.add_argument("--tiers", default="2000,10000,50000",
                    help="comma-separated tiers for --ab")
    args = ap.parse_args(argv)

    if args.ab:
        import json as _json

        tiers = tuple(
            int(x) for x in args.tiers.split(",") if x.strip()
        )
        print(_json.dumps(
            registry_kernel_ab(tiers=tiers, steps=args.steps), indent=2
        ))
        return 0

    print(f"backend: {jax.devices()[0].platform} ({jax.devices()[0]})")
    case = synthetic_cascade_arrays(args.n, n_roots=3, seed=0)
    buckets = RCAConfig().shape_buckets
    n_pad = bucket_for(args.n + 1, buckets)
    e_pad = bucket_for(len(case.dep_src), buckets)
    print(f"n={args.n} n_pad={n_pad} E={len(case.dep_src)} e_pad={e_pad}")

    variants, a_ex = make_variants(n_pad, e_pad, case)
    m0 = jnp.zeros(n_pad, jnp.float32)

    # parity vs coo (gather_only / scatter_only are attribution probes,
    # not candidates — they are exempt)
    ref = np.asarray(
        chain(variants["coo"], args.steps)(1)(m0, jnp.float32(0))
    )
    for name in ("xla_cumsum", "pallas_segscan"):
        got = np.asarray(
            chain(variants[name], args.steps)(1)(m0, jnp.float32(0))
        )
        err = np.abs(got - ref).max()
        print(f"parity {name}: max|diff|={err:.3e}")
        # xla_cumsum is the round-3 REJECTED reference: its global
        # accumulation error (measured 5e-3 after 8 steps at 50k) is one
        # of the reasons it was rejected — report, don't assert
        if name == "pallas_segscan":
            assert err < 1e-4, (name, err)

    for name, step in variants.items():
        ms = marginal_chain_ms(chain(step, args.steps), m0)
        print(f"{name:14s}: marginal {args.steps}-step chain {ms:8.3f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
