"""rca_tpu — a TPU-native Kubernetes root-cause-analysis framework.

Built from scratch in JAX/XLA/Pallas with the capability surface of the
reference system (``vobbilis/kubernetes-rca-system``): six diagnostic signal
agents (metrics / logs / events / topology / traces / resources), a
coordinator that fuses findings into ranked root causes, a chat-style query
interface with prioritized suggestions, a hypothesis → evidence → conclusion
investigation workflow, persistent resumable investigations with full audit
logging, and both live-cluster and hermetic mock backends.

Where the reference correlates evidence with serial per-agent Python loops
and LLM calls (reference: agents/mcp_coordinator.py:624-666), this framework
recasts evidence fusion as a batched causal-graph inference kernel on TPU:
vectorized feature extraction packs per-pod/per-service signals into padded
device arrays, and a jit-compiled message-passing pass over the
service-dependency graph ranks root causes — shardable across a device mesh
via shard_map/ppermute for large topologies.

Layering (bottom-up; see SURVEY.md §7):

- :mod:`rca_tpu.cluster`      typed snapshot layer (real + mock backends,
                              watch-driven incremental change feeds)
- :mod:`rca_tpu.features`     vectorized feature extraction → device arrays
- :mod:`rca_tpu.graph`        topology construction → typed COO arrays;
                              accelerator Brandes for SPOF centrality
- :mod:`rca_tpu.engine`       jit'd causal propagation + ranking, learned
                              weights (optax/orbax, shippability-gated),
                              Pallas kernels, streaming sessions, the
                              sharded multi-device engine selector
- :mod:`rca_tpu.parallel`     mesh / sharding / collective utilities
- :mod:`rca_tpu.agents`       deterministic + LLM agent families
- :mod:`rca_tpu.coordinator`  orchestration, chat, suggestions, hypotheses
- :mod:`rca_tpu.llm`          LLM backend with a real tool-execution loop
- :mod:`rca_tpu.store`        investigation persistence (file-locked JSON)
- :mod:`rca_tpu.obslog`       evidence / prompt audit logs
- :mod:`rca_tpu.native`       C/C++ hot-path twins (log scan, sanitizer)
- :mod:`rca_tpu.ui`           Streamlit UI surface (import-gated)
"""

from rca_tpu.version import __version__

__all__ = ["__version__"]
