"""causelens: provenance blocks, attribution digests, and blame trees.

The engine half of ISSUE 14 lives in :mod:`rca_tpu.engine.attribution`
(the fused counterfactual/saliency dispatch); this module is the
observability half — the schema-versioned ``provenance`` block that
rides findings JSON and serve responses, the stable digest that replay
parity-checks against the tape, and the ASCII blame tree ``rca why``
renders.

Digest contract: :func:`attribution_digest` hashes a canonicalized
(float-rounded) copy of the block, so the digest is stable across the
JSON round trip a recording frame takes while still pinning every
attribution value to ~1e-6.  The block itself contains NO wall times —
:func:`rca_tpu.engine.attribution.compute_attribution` keeps cost
telemetry in the kernel registry row instead — which is what makes
"recompute from the tape, compare digests" a sound parity gate.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

#: provenance wrapper schema (the inner attribution block carries its
#: own schema from engine/attribution.py)
PROVENANCE_SCHEMA = 1

#: float rounding applied before digesting (decimal places) — wide
#: enough that any real attribution change moves the digest, tight
#: enough that JSON round-trip representation noise cannot
_DIGEST_DECIMALS = 6


def _canonical(obj: Any) -> Any:
    if isinstance(obj, float):
        return round(obj, _DIGEST_DECIMALS)
    if isinstance(obj, dict):
        return {k: _canonical(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    return obj


def attribution_digest(block: Optional[Dict[str, Any]]) -> Optional[str]:
    """Stable content digest of one attribution/provenance block (None
    in = None out).  Uses the replay subsystem's object digest so the
    recorded and recomputed sides hash identically."""
    if block is None:
        return None
    from rca_tpu.replay.format import digest_obj

    return digest_obj(_canonical(block))


def provenance_block(
    attribution: Dict[str, Any],
    engine: Optional[str] = None,
    source: str = "causelens",
) -> Dict[str, Any]:
    """Wrap an engine attribution block as the ``provenance`` object
    findings JSON / serve responses carry: schema-versioned, digested,
    with the producing engine stamped for forensics."""
    out: Dict[str, Any] = {
        "schema": PROVENANCE_SCHEMA,
        "source": source,
        "attribution": attribution,
        "digest": attribution_digest(attribution),
    }
    if engine is not None:
        out["engine"] = engine
    return out


# -- rendering (`rca why`) ----------------------------------------------------

def _fmt(x: Any, nd: int = 3) -> str:
    if isinstance(x, float):
        return f"{x:.{nd}f}"
    return str(x)


def render_blame_tree(provenance: Dict[str, Any],
                      max_channels: int = 4,
                      max_counterfactuals: int = 3) -> str:
    """The ASCII blame tree: evidence channels → blame edges → ranked
    service, one box per candidate.  Takes either the wrapped provenance
    block or a bare engine attribution block."""
    block = provenance.get("attribution", provenance)
    lines: List[str] = []
    lines.append(
        f"causelens v{block.get('schema', '?')} · "
        f"{block.get('n_services', '?')} services / "
        f"{block.get('n_edges', '?')} edges · kernel "
        f"{block.get('kernel') or '-'} · formula "
        f"v{block.get('score_formula_version', '?')}"
    )
    digest = provenance.get("digest")
    if digest:
        lines.append(f"digest {digest}")
    cands = block.get("candidates") or []
    if not cands:
        lines.append("(no ranked candidates to attribute)")
        return "\n".join(lines)
    for entry in cands:
        lines.append("")
        lines.append(
            f"#{entry.get('rank')} {entry.get('component')}"
            f"  score {_fmt(entry.get('score'))}"
        )
        factors = entry.get("factors") or {}
        rec_err = entry.get("reconstruction_error")
        err_s = f"{rec_err:.1e}" if isinstance(rec_err, float) else "-"
        lines.append(
            f"├─ factors: evidence {_fmt(factors.get('evidence'))}"
            f" × impact {_fmt(factors.get('impact'))}"
            f" × suppression {_fmt(factors.get('suppression'))}"
            f"   (rebuilt {_fmt(entry.get('reconstructed_score'))},"
            f" err {err_s})"
        )
        channels = sorted(
            entry.get("channels") or [],
            key=lambda c: -c.get("contribution", 0.0),
        )[:max_channels]
        if channels:
            lines.append(
                "├─ evidence: " + " · ".join(
                    f"{c['channel']} {_fmt(c.get('contribution'), 2)}"
                    for c in channels
                )
            )
        path = entry.get("blame_path") or []
        if path:
            hops = " → ".join(
                f"{hop['to']} (h {_fmt(hop.get('hard'), 2)})"
                for hop in path
            )
            lines.append(f"├─ blame path: {entry.get('component')} → {hops}")
        else:
            lines.append("├─ blame path: (no broken upstream dependency)")
        cf = [
            c for c in (entry.get("counterfactuals") or [])
            if c.get("score_drop", 0.0) != 0.0
        ][:max_counterfactuals]
        if cf:
            lines.append(
                "└─ counterfactuals: " + " · ".join(
                    ("-self" if c.get("self")
                     else f"-{c['component']}")
                    + f" Δ{_fmt(c.get('score_drop'))}"
                    for c in cf
                )
            )
        else:
            lines.append("└─ counterfactuals: (none moved this score)")
    rows = block.get("saliency_rows") or []
    if rows:
        lines.append("")
        lines.append(
            "saliency (∂score/∂features, top rows): " + " · ".join(
                f"{r['component']} {_fmt(r.get('grad_l1'), 2)}"
                for r in rows[:5]
            )
        )
    return "\n".join(lines)
