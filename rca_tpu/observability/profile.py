"""``rca profile``: an opt-in jax.profiler capture around N live ticks.

ROADMAP item 4's standing diagnosis gap: every bench round since r02
reports ``pallas_engaged: false``, and nothing attributed the choice to
a shape.  This capture makes the XLA-vs-Pallas decision visible per
request: it runs a mock-cluster streaming session for ``ticks`` polls
inside ``jax.profiler.trace`` (TensorBoard/Perfetto-loadable), wraps
each poll in a ``jax.profiler.StepTraceAnnotation`` so device ops group
under tick numbers, engages :func:`rca_tpu.observability.spans.
device_annotation` inside the serve/tick dispatch paths, and stamps the
ENGAGED kernel per shape bucket — the part a round-level flag cannot
say — into the span attributes and the returned summary.  (The retired
process-level ``noisyor_path`` stamp is gone: ISSUE 14 satellite.)
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from rca_tpu.observability.spans import (
    Tracer,
    default_tracer,
    set_profiling,
)


def profile_ticks(
    out_dir: str,
    ticks: int = 20,
    services: int = 200,
    seed: int = 7,
    tracer: Optional[Tracer] = None,
    clock: Callable[[], float] = time.perf_counter,
) -> Dict[str, Any]:
    """Capture a ``jax.profiler`` trace around ``ticks`` polls of a
    synthetic streaming session; returns the capture summary (the CLI
    prints it as one JSON line).  The profile lands under ``out_dir``;
    host spans for every tick land in ``tracer`` (default: the process
    tracer) with the kernel attribution attached."""
    import jax

    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession

    if tracer is None:
        # an explicit profile capture is its own opt-in: record spans
        # even when RCA_TRACE is off (the process default stays null)
        tracer = default_tracer()
        if not tracer.enabled:
            tracer = Tracer()
    os.makedirs(out_dir, exist_ok=True)
    world = synthetic_cascade_world(
        int(services), n_roots=1, seed=int(seed), namespace="profile"
    )
    client = MockClusterClient(world)
    session = LiveStreamingSession(
        client, "profile", k=5, tracer=tracer,
    )
    kernel_path = getattr(session.session, "kernel_path", None)
    n_pad = getattr(session.session, "_n_pad", None)
    set_profiling(True)
    t0 = clock()
    try:
        with jax.profiler.trace(out_dir):
            for i in range(int(ticks)):
                with jax.profiler.StepTraceAnnotation("rca_tick",
                                                      step_num=i):
                    session.poll()
    finally:
        set_profiling(False)
    wall_ms = (clock() - t0) * 1e3
    return {
        "ticks": int(ticks),
        "services": int(services),
        "trace_dir": out_dir,
        "wall_ms": round(wall_ms, 3),
        "ms_per_tick": round(wall_ms / max(1, int(ticks)), 3),
        # the per-shape attribution the round-level flag cannot carry:
        # which kernel this session's padded shape actually ENGAGED
        "kernel_by_shape": (
            {str(n_pad): kernel_path} if n_pad is not None else {}
        ),
        "spans_recorded": tracer.stats()["recorded"],
        "profile_files": sum(
            len(files) for _r, _d, files in os.walk(out_dir)
        ),
    }
