"""Span vocabulary + the ONE tracer seam (ISSUE 11 tentpole).

A :class:`Span` is one named time interval inside a trace; a trace is
every span sharing a ``trace_id``, connected by ``parent_id`` edges.
Context enters the system at the gateway via the ``X-RCA-Trace`` header
(``<trace_id>-<span_id>``, generated when absent, echoed in responses),
rides :class:`rca_tpu.serve.request.ServeRequest` through the queue, the
batcher, pool routing, replica dispatch/fetch, and the resident delta
path, and lands in the :class:`Tracer`'s bounded ring buffer — exported
by :mod:`rca_tpu.observability.export`.

Discipline (graftlint rule ``span-discipline``, ANALYSIS.md):

- spans are opened ONLY through the tracer seam — ``tracer.span(...)``
  as a ``with`` block for synchronous scopes, or ``tracer.record(...)``
  for phases whose start/end are known timestamps (queue wait, a device
  round trip whose ends live in different methods).  Raw ``Span(...)``
  construction outside this module is unlandable, so an unclosed span
  cannot exist;
- the tracer times through an injectable ``clock`` (nondet-discipline:
  this module is replay-covered — spans embedded in recordings must be
  host-independent on replay, so no wall reads outside the seam);
- ``RCA_TRACE=0`` (the default) swaps in :data:`NULL_TRACER`: every
  call is a constant no-op behind one ``enabled`` check, nothing
  allocates, and results are bit-identical to a build without tracing
  (property-tested in tests/test_observability.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional

from rca_tpu.config import trace_buffer_cap, trace_enabled
from rca_tpu.util.threads import make_lock

#: wire header carrying trace context across the gateway boundary
TRACE_HEADER = "X-RCA-Trace"


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The identity a child span parents onto: ``(trace_id, span_id)``.
    Immutable — contexts are shared across threads freely."""

    trace_id: str   # 16 hex chars
    span_id: str    # 8 hex chars

    def to_wire(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    @staticmethod
    def from_wire(value: Optional[str]) -> Optional["SpanContext"]:
        """Parse an ``X-RCA-Trace`` header; None for anything malformed
        (a bad header must start a fresh trace, never 500 the wire)."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) != 2:
            return None
        trace_id, span_id = parts
        if not (1 <= len(trace_id) <= 32 and 1 <= len(span_id) <= 16):
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        return SpanContext(trace_id, span_id)


@dataclasses.dataclass
class Span:
    """One recorded interval.  Times are seconds in the minting tracer's
    clock domain (monotonic by default); attributes are JSON-safe."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: float
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start": self.start, "end": self.end,
            "attrs": dict(self.attrs),
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Span":
        return Span(
            name=d["name"], trace_id=d["trace_id"], span_id=d["span_id"],
            parent_id=d.get("parent_id"), start=float(d["start"]),
            end=float(d["end"]), attrs=dict(d.get("attrs") or {}),
        )


class Tracer:
    """Span minting + the lock-disciplined bounded ring buffer.

    One tracer serves a whole process (``default_tracer()``); components
    take an injectable ``tracer=`` for tests.  IDs come from a seeded
    ``random.Random`` so a fixed seed yields a byte-stable span stream
    (the replay tests pin one); ``seed=None`` draws system entropy once
    at construction — ids differ across processes, never within a trace.

    The buffer drops the OLDEST spans past ``cap`` and counts the drops:
    saturation sheds history, it never blocks or grows.  The lock is a
    leaf (nothing is called while holding it)."""

    def __init__(
        self,
        enabled: bool = True,
        cap: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        seed: Optional[int] = None,
    ):
        self.enabled = bool(enabled)
        self.cap = int(cap) if cap is not None else trace_buffer_cap()
        if self.cap < 1:
            raise ValueError(f"trace buffer cap must be >= 1, got {cap}")
        self.clock = clock
        self._rng = random.Random(seed)
        self._lock = make_lock("Tracer._lock")
        self._buffer: "deque[Span]" = deque(maxlen=self.cap)
        self.dropped = 0
        self.recorded = 0

    # -- id minting ----------------------------------------------------------
    def _trace_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(64):016x}"

    def _span_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(32):08x}"

    def new_context(
        self, parent: Optional[SpanContext] = None
    ) -> Optional[SpanContext]:
        """Mint the identity of a span BEFORE recording it — the serve
        path hands a request's root context to children (queue, batch,
        dispatch) that finish before the root span itself is recorded at
        completion.  A child keeps the parent's trace_id; no parent
        starts a fresh trace.  None when disabled (zero-allocation)."""
        if not self.enabled:
            return None
        trace_id = parent.trace_id if parent is not None else self._trace_id()
        return SpanContext(trace_id, self._span_id())

    # -- recording -----------------------------------------------------------
    def _push(self, span: Span) -> Span:
        with self._lock:
            if len(self._buffer) == self.cap:
                self.dropped += 1
            self._buffer.append(span)
            self.recorded += 1
        return span

    def record(
        self,
        name: str,
        start: float,
        end: float,
        parent: Optional[SpanContext] = None,
        context: Optional[SpanContext] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Span]:
        """A COMPLETE span from caller-supplied timestamps (the caller's
        clock domain) — the form for phases that start and end in
        different methods, where a with-block cannot exist.  ``context``
        records under a pre-minted identity (``new_context``); otherwise
        a fresh child of ``parent`` is minted."""
        if not self.enabled:
            return None
        ctx = context if context is not None else self.new_context(parent)
        return self._push(Span(
            name=name, trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=parent.span_id if parent is not None else None,
            start=float(start), end=float(end), attrs=dict(attrs or {}),
        ))

    def event(
        self,
        name: str,
        at: float,
        parent: Optional[SpanContext] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Span]:
        """A zero-duration marker (steal moves, breaker flips)."""
        return self.record(name, at, at, parent=parent, attrs=attrs)

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: Optional[SpanContext] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Optional[Span]]:
        """A synchronous scope, timed on the tracer's clock and recorded
        at exit even when the body raises.  MUST be used as a ``with``
        block (graftlint rule span-discipline) — that is what guarantees
        every opened span closes."""
        if not self.enabled:
            yield None
            return
        ctx = self.new_context(parent)
        span = Span(
            name=name, trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=parent.span_id if parent is not None else None,
            start=self.clock(), end=0.0, attrs=dict(attrs or {}),
        )
        try:
            yield span
        finally:
            span.end = self.clock()
            self._push(span)

    # -- reading -------------------------------------------------------------
    def spans(
        self,
        trace_id: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """A consistent snapshot of the buffer (oldest first), optionally
        filtered to one trace and/or capped to the NEWEST ``limit``."""
        with self._lock:
            out = [s.to_dict() for s in self._buffer]
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._buffer.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "recorded": self.recorded, "dropped": self.dropped,
                "buffered": len(self._buffer), "cap": self.cap,
            }


class _NullTracer(Tracer):
    """The ``RCA_TRACE=0`` path: same surface, constant no-ops.  One
    shared instance — components hold it without allocating anything."""

    def __init__(self) -> None:
        super().__init__(enabled=False, cap=1, seed=0)


#: the shared disabled tracer (never records; ``enabled`` is False)
NULL_TRACER = _NullTracer()

_DEFAULT: Optional[Tracer] = None


def default_tracer() -> Tracer:
    """The process tracer: a real one when ``RCA_TRACE=1`` (buffer sized
    by ``RCA_TRACE_BUFFER``), else :data:`NULL_TRACER`.  Resolved once;
    tests inject tracers explicitly (or call ``set_default_tracer``)
    instead of mutating the environment mid-process."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Tracer() if trace_enabled() else NULL_TRACER
    return _DEFAULT


def set_default_tracer(tracer: Optional[Tracer]) -> None:
    """Override (or with None, re-resolve from env on next use) the
    process tracer — the CLI entry points and tests use this."""
    global _DEFAULT
    _DEFAULT = tracer


# -- jax.profiler hooks -------------------------------------------------------

_PROFILING = False


def profiling_active() -> bool:
    """Is an ``rca profile`` capture in progress?  Device annotations
    engage only then — ``jax.profiler.TraceAnnotation`` is cheap but not
    free, and outside a capture there is no trace to annotate."""
    return _PROFILING


def set_profiling(active: bool) -> None:
    global _PROFILING
    _PROFILING = bool(active)


def device_annotation(name: str, **kwargs):
    """A ``jax.profiler.TraceAnnotation`` naming the host scope that
    issues device work, so the profiler's device timeline lines up under
    the serve/tick spans; a no-op context outside a profile capture."""
    if not _PROFILING:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)
