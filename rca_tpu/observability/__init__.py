"""Span-based distributed tracing + SLO telemetry (ISSUE 11).

The serving plane's only timing signals used to be aggregate —
``PhaseStats`` medians, Prometheus counters — so "where did THIS slow
request spend its 40 ms" had no answer.  This package is the answer's
substrate: :class:`Tracer` mints and collects spans (OBSERVABILITY.md),
:mod:`export` turns them into Perfetto-loadable Chrome trace JSON and
NDJSON wire dumps, and :mod:`profile` wraps a live session in a
``jax.profiler`` capture so device work lines up under the host spans.
"""

from rca_tpu.observability.spans import (  # noqa: F401
    NULL_TRACER,
    Span,
    SpanContext,
    Tracer,
    default_tracer,
    device_annotation,
    set_default_tracer,
)
from rca_tpu.observability.export import (  # noqa: F401
    DURATION_BUCKETS_S,
    LatencyHistogram,
    chrome_trace,
    ndjson_spans,
    recording_trace,
)
from rca_tpu.observability.causelens import (  # noqa: F401
    PROVENANCE_SCHEMA,
    attribution_digest,
    provenance_block,
    render_blame_tree,
)

__all__ = [
    "PROVENANCE_SCHEMA",
    "attribution_digest",
    "provenance_block",
    "render_blame_tree",
    "NULL_TRACER",
    "Span",
    "SpanContext",
    "Tracer",
    "default_tracer",
    "set_default_tracer",
    "device_annotation",
    "DURATION_BUCKETS_S",
    "LatencyHistogram",
    "chrome_trace",
    "ndjson_spans",
    "recording_trace",
]
