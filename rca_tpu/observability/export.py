"""Trace + latency exports: Chrome trace JSON, NDJSON, histograms.

Three consumers, one span vocabulary (:mod:`rca_tpu.observability.spans`):

- **Perfetto / chrome://tracing** — :func:`chrome_trace` renders spans as
  complete ("ph": "X") trace events, one timeline row per trace, so one
  request's gateway→queue→batch→dispatch→fetch life reads left to right
  (OBSERVABILITY.md shows the load);
- **the wire** — :func:`ndjson_spans` backs the gateway's
  ``GET /v1/traces`` (one span JSON per line, newest last);
- **recordings** — :func:`recording_trace` rebuilds the SAME Chrome
  trace from a flight recording's tick frames (spans are embedded in
  every tick health record), so ``rca replay --trace-out`` reconstructs
  a recorded incident's timeline byte-for-byte without re-running it.

Plus :class:`LatencyHistogram`: the fixed-bucket per-tenant duration
histogram behind ``rca_request_duration_seconds`` and the SLO burn
counters in ``/metrics`` (ISSUE 11 satellite — burn rate needs ``le``
buckets, not quantile gauges).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

#: ``rca_request_duration_seconds`` bucket upper bounds (seconds); the
#: +Inf bucket is implicit (count == _count).  Prometheus-conventional
#: spacing: SLO targets in the 50 ms – 5 s range land mid-ladder
DURATION_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class LatencyHistogram:
    """One cumulative fixed-bucket histogram (NOT thread-safe: holders
    record under their own lock — same discipline as PhaseStats)."""

    def __init__(self) -> None:
        self.counts = [0] * len(DURATION_BUCKETS_S)
        self.count = 0
        self.sum_s = 0.0

    def record(self, seconds: float) -> None:
        s = float(seconds)
        self.count += 1
        self.sum_s += s
        for i, le in enumerate(DURATION_BUCKETS_S):
            if s <= le:
                self.counts[i] += 1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "buckets": {
                str(le): n for le, n in zip(DURATION_BUCKETS_S, self.counts)
            },
            "count": self.count,
            "sum_s": round(self.sum_s, 6),
        }


# -- Chrome trace-event export ------------------------------------------------

def chrome_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Span dicts → a Chrome trace-event JSON object Perfetto loads.

    Layout: one ``pid`` for the whole process, one ``tid`` LANE per
    trace (allocated in first-seen order, named by a metadata event), so
    concurrent requests stack as parallel rows.  Events are complete
    ("ph": "X") with microsecond ``ts``/``dur`` rebased to the earliest
    span — Perfetto renders from zero instead of hours of monotonic
    uptime.  Span identity and parentage ride in ``args``."""
    lanes: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    t0 = min((float(s["start"]) for s in spans), default=0.0)
    for s in spans:
        trace_id = s["trace_id"]
        tid = lanes.get(trace_id)
        if tid is None:
            tid = len(lanes) + 1
            lanes[trace_id] = tid
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"trace {trace_id}"},
            })
        start = float(s["start"])
        end = float(s["end"])
        events.append({
            "name": s["name"],
            "cat": s["name"].split(".", 1)[0],
            "ph": "X",
            "pid": 1,
            "tid": tid,
            "ts": round((start - t0) * 1e6, 3),
            "dur": round(max(0.0, end - start) * 1e6, 3),
            "args": {
                "trace_id": trace_id,
                "span_id": s["span_id"],
                "parent_id": s.get("parent_id"),
                **(s.get("attrs") or {}),
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def ndjson_spans(spans: List[Dict[str, Any]]) -> str:
    """One span JSON object per line (the ``GET /v1/traces`` body)."""
    return "".join(json.dumps(s) + "\n" for s in spans)


def recording_trace(path: str) -> Dict[str, Any]:
    """The Chrome trace of a RECORDED session: every span embedded in
    the recording's tick-frame health records (plus serve frames' trace
    ids as instant markers), in frame order.  This is how ``rca replay
    --trace-out`` reconstructs an incident's timeline — from the tape,
    not from a re-run, so the times are the ones the incident actually
    had."""
    from rca_tpu.replay.format import read_frames

    frames, _status = read_frames(path)
    spans: List[Dict[str, Any]] = []
    for frame in frames:
        if frame.get("kind") == "tick":
            for s in (frame.get("health") or {}).get("spans") or []:
                spans.append(s)
        elif frame.get("kind") == "serve" and frame.get("trace_id"):
            # serve frames carry identity, not timing — surface them as
            # zero-length markers so a serve recording still maps
            # requests onto trace lanes
            spans.append({
                "name": "serve.recorded",
                "trace_id": frame["trace_id"],
                "span_id": f"{int(frame.get('index', 0)):08x}",
                "parent_id": None,
                "start": float(frame.get("index", 0)),
                "end": float(frame.get("index", 0)),
                "attrs": {
                    "request_id": frame.get("request_id"),
                    "tenant": frame.get("tenant"),
                },
            })
    return chrome_trace(spans)


def write_chrome_trace(spans_or_trace, out_path: str) -> str:
    """Dump a Chrome trace JSON file; accepts either a span-dict list or
    an already-rendered trace object.  Returns ``out_path``."""
    trace = (
        spans_or_trace if isinstance(spans_or_trace, dict)
        else chrome_trace(spans_or_trace)
    )
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    return out_path
