"""kernelscope: the two runtime watchdogs the compiler/device layer lacked.

ISSUE 12 tentpole, sitting on top of :mod:`rca_tpu.engine.registry`:

- **RecompileMonitor** — a ``jax_log_compiles``-fed hook that watches
  every XLA compilation for the life of a session.  tracecheck (PR 4)
  proves each entry point compiles once in a 2-call probe; this is the
  dynamic complement, running CONTINUOUSLY on hot tick/serve paths.  A
  compilation whose log signature (function + abstract shapes) was
  ALREADY compiled in this process is a **recompile**: the jit cache
  should have served it, so some cache key changed between bit-identical
  calls — a fresh ``jnp`` constant, an unhashable static, a donation
  mismatch.  First-seen signatures are ``fresh`` compiles (new shape
  tiers, new batch widths, resync rebuilds) and are expected; repeats
  are the regression class that lands green and shows up weeks later as
  a 30 s stall per production tick.  Counts flow into tick health
  records, serve summaries, and ``/metrics`` (``rca_recompiles_total``).
- **Device-memory accountant** — periodic ``live_buffers``/
  ``memory_stats`` sampling (tick health + ServeMetrics surfaces, gauge
  ``rca_device_bytes_in_use``) with a monotonic-growth **leak gate**
  over soak runs: a session whose device footprint only ever grows is
  leaking buffers even if no single tick looks wrong.

Both watchdogs are on by default (``RCA_KERNELSCOPE=0`` disables) and
cost nothing measurable: the monitor is a passive logging handler (XLA
compiles are rare by construction), and memory samples run every
``RCA_MEM_SAMPLE_EVERY`` ticks (or per ``/metrics`` scrape).
"""

from __future__ import annotations

import hashlib
import logging
import re
from collections import deque
from typing import Any, Dict, List, Optional

from rca_tpu.config import kernelscope_enabled, memory_sample_every
from rca_tpu.util.threads import make_lock

# a compile event whose arguments are ALL scalars (``float32[]``) is an
# eager constant-creation compile (``jnp.ones(n)`` → broadcast_in_dim):
# the log message elides static args — including the output SHAPE — so
# two different constants alias to one signature and dedupe would call
# the second a recompile.  Hot-path executables always carry real array
# arguments, so scalar-only events are excluded from recompile
# accounting (still counted as compiles).
_HAS_ARRAY_ARG = re.compile(r"\w\[[0-9]")

# eager single-op dispatches compile under the PRIMITIVE's name
# (``x[idx]`` outside jit → "Compiling gather ...") with the op's static
# configuration (gather dimension numbers, reduce axes, pad config)
# elided from the message — two different eager gathers over same-shaped
# inputs alias to one signature.  The watchdog's contract is the
# JIT-COMPILED hot-path executables (python-function names like
# ``_propagate_ranked``); eager primitive names are excluded from
# recompile accounting.  Curated from the lax primitives the engine's
# host paths eagerly dispatch; an entry here only mutes the repeat
# heuristic, the compile still counts.
_EAGER_PRIMITIVES = frozenset({
    "abs", "add", "all", "any", "argmax", "argmin", "asarray", "and",
    "broadcast_in_dim", "clamp", "clip", "concatenate",
    "convert_element_type", "copy", "cumsum", "div", "dot_general",
    "dynamic_slice", "dynamic_update_slice", "eq", "exp", "expand_dims",
    "floor_divide", "gather", "ge", "gt", "integer_pow", "iota",
    "isfinite", "isinf", "isnan", "le", "log", "logistic", "lt",
    "matmul", "max", "min", "mul", "ne", "neg", "not", "or", "pad",
    "pow", "reduce_and", "reduce_max", "reduce_min", "reduce_or",
    "reduce_prod", "reduce_sum", "rem", "reshape", "rev", "rsqrt",
    "scatter", "scatter-add", "scatter_add", "select_n", "sign",
    "slice", "sort", "split", "sqrt", "squeeze", "stack", "sub",
    "take", "tanh", "top_k", "transpose", "true_divide", "where",
    "_where", "xor",
})


class _CompileLog:
    """Process-wide compile-event collector (one instance, refcounted).

    Mirrors :func:`rca_tpu.analysis.tracecheck.compile_log_capture`'s
    logger handling — ``jax_log_compiles`` promotes compile logs to
    WARNING, our handler becomes the jax logger's only one so the
    chatter never reaches stderr — but stays installed for the life of
    the monitored session instead of a 2-call probe.  tracecheck's
    save/restore nests cleanly inside an installed monitor (it stashes
    and restores our handler with the rest)."""

    #: compile events kept for monitor windows; far above any real
    #: process's compile count — a trim only loses ancient history
    EVENT_CAP = 100_000

    def __init__(self) -> None:
        self._lock = make_lock("kernelscope._CompileLog._lock")
        self._refs = 0
        self._seen: Dict[str, int] = {}   # signature -> last event seq
        self._seq = 0
        self._events: List[Dict[str, Any]] = []
        self._handler: Optional[logging.Handler] = None
        self._saved: Optional[tuple] = None

    # -- the handler ---------------------------------------------------------
    def _emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if not msg.startswith("Compiling "):
            return
        sig = hashlib.sha1(msg.encode("utf-8", "replace")).hexdigest()[:16]
        parts = msg.split()
        name = parts[1] if len(parts) > 1 else "?"
        relevant = (
            _HAS_ARRAY_ARG.search(msg) is not None
            and name not in _EAGER_PRIMITIVES
        )
        with self._lock:
            self._seq += 1
            self._events.append({
                "seq": self._seq, "name": name, "sig": sig,
                # the log message elides STATIC args, so an identical
                # signature may be a different executable; monitors only
                # call a pair a recompile when both compiles fall inside
                # one monitored window (see RecompileMonitor.snapshot)
                "prev_seq": self._seen.get(sig),
                "relevant": relevant,
            })
            self._seen[sig] = self._seq
            if len(self._events) > self.EVENT_CAP:
                del self._events[: self.EVENT_CAP // 2]

    def install(self) -> None:
        with self._lock:
            self._refs += 1
            if self._refs > 1:
                return
        import jax

        logger = logging.getLogger("jax")

        outer = self

        class _Handler(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                outer._emit(record)

        self._handler = _Handler(level=logging.WARNING)
        self._saved = (
            list(logger.handlers), logger.level, logger.propagate,
            jax.config.jax_log_compiles,
        )
        logger.handlers = [self._handler]
        if logger.level > logging.WARNING or logger.level == logging.NOTSET:
            logger.setLevel(logging.WARNING)
        logger.propagate = False
        jax.config.update("jax_log_compiles", True)

    def uninstall(self) -> None:
        with self._lock:
            if self._refs == 0:
                return
            self._refs -= 1
            if self._refs > 0:
                return
            saved = self._saved
            self._saved = None
            self._handler = None
        if saved is None:
            return
        import jax

        logger = logging.getLogger("jax")
        handlers, level, propagate, flag = saved
        logger.handlers = handlers
        logger.setLevel(level)
        logger.propagate = propagate
        jax.config.update("jax_log_compiles", flag)

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def events_since(self, seq: int) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events if e["seq"] > seq]


_LOG = _CompileLog()


class RecompileMonitor:
    """One session's view over the shared compile log: counts since this
    monitor's ``start()`` (and since ``mark_warm()``), so concurrent
    sessions each read their own deltas.  Use as a context manager or
    explicit ``start()``/``stop()``; disabled monitors are free no-ops
    with the same surface (``RCA_KERNELSCOPE=0``)."""

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (
            kernelscope_enabled() if enabled is None else bool(enabled)
        )
        self._started = False
        self._start_seq = 0
        self._warm_seq: Optional[int] = None

    def start(self) -> "RecompileMonitor":
        if self.enabled and not self._started:
            _LOG.install()
            self._started = True
            self._start_seq = _LOG.seq()
        return self

    def stop(self) -> None:
        if self._started:
            _LOG.uninstall()
            self._started = False

    def __enter__(self) -> "RecompileMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def mark_warm(self) -> None:
        """Stamp the end of warmup: ``recompiles_post_warm`` counts from
        here.  (Repeat-signature compiles are anomalous whenever they
        happen; the warm mark exists so soaks can assert a hard ZERO on
        the steady state without caring how warmup interleaved.)"""
        if self._started:
            self._warm_seq = _LOG.seq()

    def snapshot(self) -> Dict[str, Any]:
        """Counts over THIS monitor's window.  A recompile = an
        array-argument compile whose signature was ALREADY compiled
        inside the same window — the log message elides static args, so
        pairing across windows (another session's executable with
        different statics) would alias distinct executables; within one
        session's window the statics are fixed and a repeat means a
        cache key drifted between bit-identical calls."""
        if not self._started:
            return {"enabled": False, "compiles": 0, "recompiles": 0,
                    "recompiles_post_warm": 0, "recompiled": []}
        events = _LOG.events_since(self._start_seq)
        warm_seq = (
            self._warm_seq if self._warm_seq is not None
            else _LOG.seq()
        )
        repeats = [
            e for e in events
            if e["relevant"] and e["prev_seq"] is not None
            and e["prev_seq"] > self._start_seq
        ]
        return {
            "enabled": True,
            "compiles": len(events),
            "recompiles": len(repeats),
            "recompiles_post_warm": sum(
                1 for e in repeats if e["seq"] > warm_seq
            ),
            "recompiled": [e["name"] for e in repeats][-8:],
        }


# -- device memory ------------------------------------------------------------

def sample_device_memory() -> Dict[str, Any]:
    """One sample of the process's device footprint: per-device
    allocator stats where the backend reports them (TPU/GPU
    ``memory_stats``), plus the live-buffer census (count and summed
    bytes of every live ``jax.Array``) — the portable signal CPU test
    hosts gate on.  ``bytes_in_use`` is the allocator total when
    available, else the live-buffer total."""
    import jax

    devices: Dict[str, Dict[str, Any]] = {}
    allocator_total: Optional[int] = None
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (RuntimeError, NotImplementedError, AttributeError,
                TypeError):
            stats = None
        if not stats:
            continue
        rec = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        }
        devices[str(getattr(d, "id", d))] = rec
        if rec["bytes_in_use"] is not None:
            allocator_total = (
                (allocator_total or 0) + int(rec["bytes_in_use"])
            )
    try:
        live = jax.live_arrays()
    except (RuntimeError, AttributeError):
        live = []
    live_bytes = int(sum(int(getattr(a, "nbytes", 0) or 0) for a in live))
    return {
        "devices": devices,
        "live_buffers": len(live),
        "live_bytes": live_bytes,
        "bytes_in_use": (
            allocator_total if allocator_total is not None else live_bytes
        ),
    }


def leak_gate(byte_samples: List[int], warmup: int = 1,
              slack_bytes: int = 1 << 20) -> Dict[str, Any]:
    """The monotonic-growth leak gate over a soak's memory samples:
    FAILS only when the post-warmup series never goes down AND ends more
    than ``slack_bytes`` above where it started — steady-state sessions
    plateau (scatter reuses the donated buffer), and legitimate churn
    (resyncs, cache evictions) shows dips.  A series that only climbs is
    a buffer leak even if no single sample looks alarming."""
    series = [int(b) for b in byte_samples][warmup:]
    if len(series) < 3:
        return {"ok": True, "samples": len(series),
                "reason": "too few samples to gate"}
    monotonic = all(b >= a for a, b in zip(series, series[1:]))
    growth = series[-1] - series[0]
    ok = not (monotonic and growth > slack_bytes)
    return {
        "ok": bool(ok),
        "samples": len(series),
        "first_bytes": series[0],
        "last_bytes": series[-1],
        "growth_bytes": int(growth),
        "monotonic_growth": bool(monotonic),
        "slack_bytes": int(slack_bytes),
    }


class DeviceMemoryAccountant:
    """Periodic device-memory sampling for tick/serve health surfaces.
    ``maybe_sample(tick)`` samples every ``sample_every``-th call (the
    live-buffer walk is cheap, not free); the recorded byte series feeds
    :func:`leak_gate`.  Disabled accountants sample nothing."""

    def __init__(self, sample_every: Optional[int] = None,
                 enabled: Optional[bool] = None, cap: int = 1024):
        self.enabled = (
            kernelscope_enabled() if enabled is None else bool(enabled)
        )
        self.sample_every = (
            memory_sample_every() if sample_every is None
            else max(1, int(sample_every))
        )
        self._bytes: "deque[int]" = deque(maxlen=cap)
        self.samples_taken = 0

    def maybe_sample(self, tick: int) -> Optional[Dict[str, Any]]:
        if not self.enabled or int(tick) % self.sample_every != 0:
            return None
        return self.sample()

    def sample(self) -> Optional[Dict[str, Any]]:
        if not self.enabled:
            return None
        out = sample_device_memory()
        self._bytes.append(int(out["bytes_in_use"]))
        self.samples_taken += 1
        return out

    def byte_series(self) -> List[int]:
        return list(self._bytes)

    def gate(self, warmup: int = 1,
             slack_bytes: int = 1 << 20) -> Dict[str, Any]:
        if not self.enabled:
            return {"ok": True, "samples": 0, "reason": "disabled"}
        return leak_gate(self.byte_series(), warmup=warmup,
                         slack_bytes=slack_bytes)
