"""Native (C++) log scanner: build-on-first-use via g++ + ctypes.

The reference did all log scanning with Python ``re`` loops (reference:
agents/logs_agent.py:146-149); here the 13-class scan is a C++ single-pass
matcher ~10x faster, compiled lazily from :mod:`rca_tpu.native.logscan`
source with the Python regex path as the always-available fallback
(``RCA_NATIVE_SCAN=0`` disables; parity enforced by
tests/test_native.py::test_native_matches_python_regex).

The alternative table below mirrors rca_tpu.features.logscan.LOG_PATTERNS
exactly — alternation order included, because findall counts depend on which
branch consumes first.  Tokens: \\x01 digit, \\x02 word+, \\x03 ws*,
\\x04 ws, \\x06 greedy-any-then-literal-tail.  Flags: 1 = word boundary,
2 = case sensitive.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from rca_tpu.config import env_raw, env_str

D = "\x01"   # one digit
W = "\x02"   # one or more word chars
WS0 = "\x03"  # zero or more whitespace
WS1 = "\x04"  # exactly one whitespace
ANY = "\x06"  # greedy within-line any, followed by literal tail

# (flags, pattern) per alternative; order matches the regex alternation in
# rca_tpu.features.logscan.LOG_PATTERNS.
SPEC_TABLE: List[Tuple[str, List[Tuple[int, str]]]] = [
    ("oom_kill", [
        (0, "out of memory"), (0, "oomkilled"),
        (0, "signal:" + WS0 + "killed"),
        (0, "oom-kill"), (0, "oom_kill"), (0, "oomkill"),
    ]),
    ("connection_refused", [(0, "connection refused"), (0, "econnrefused")]),
    ("permission_denied", [
        (0, "permission denied"), (0, "access denied"), (1, "forbidden"),
    ]),
    ("timeout", [
        # timed?\s?-?out expanded, greedy order (d, ws, dash present first)
        (0, "timed" + WS1 + "-out"), (0, "timed" + WS1 + "out"),
        (0, "timed-out"), (0, "timedout"),
        (0, "time" + WS1 + "-out"), (0, "time" + WS1 + "out"),
        (0, "time-out"), (0, "timeout"),
        (0, "etimedout"), (0, "deadline exceeded"),
    ]),
    ("crash_loop", [
        (0, "crashloopbackoff"),
        (0, "back-off restarting"), (0, "backoff restarting"),
    ]),
    ("api_error", [
        (2, "api server error"), (2, "StatusCode=5" + D + D),
    ]),
    ("volume_mount", [
        (0, "unable to attach or mount volumes"),
        (0, "unable to mount volumes"),
        (0, "mountvolume." + W + " failed"),
    ]),
    ("image_pull", [
        (0, "errimagepull"), (0, "imagepullbackoff"),
        (0, "failed to pull image"),
    ]),
    ("dns_resolution", [
        (0, "could not resolve"), (0, "dns resolution failed"),
        (0, "no such host"),
    ]),
    ("authentication", [(0, "unauthorized"), (0, "authentication fail")]),
    ("config_error", [
        (0, "invalid configuration"),
        (0, "configmap " + ANY + "not found"),
        (0, "secret " + ANY + "not found"),
    ]),
    ("internal_server_error", [
        (0, "internal server error"), (0, "internal servererror"),
        (0, "internalserver error"), (0, "internalservererror"),
        (0, "500 internal"),
    ]),
    ("exception", [
        (1, "exception"), (1, "error"), (0, "traceback"),
        (1, "fatal"), (1, "critical"), (0, "panic:"), (0, "panic"),
    ]),
]

SPEC_CLASS_NAMES = [name for name, _ in SPEC_TABLE]


def serialize_spec() -> bytes:
    classes = []
    for _, alts in SPEC_TABLE:
        classes.append(
            "\x1f".join(chr(ord("0") + flags) + pat for flags, pat in alts)
        )
    return "\x1e".join(classes).encode("latin-1")


_SOURCE = Path(__file__).with_name("logscan.cpp")
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _default_cache_dir() -> Path:
    """User-private cache location: ~/.cache/rca_tpu when HOME resolves,
    else a uid-suffixed tempdir.  A world-shared path (the old
    /tmp/rca_tpu_native) would let any local user pre-seed a .so whose
    hash tag is computable from the public source, and load_sanitize()
    imports that file as a full CPython extension — arbitrary code
    execution.  The dir is created 0700 and re-verified before any load."""
    try:
        home = Path.home()  # raises RuntimeError in HOME-less containers
        if home != Path("/") and os.access(str(home), os.W_OK):
            return home / ".cache" / "rca_tpu"
    except (RuntimeError, OSError):
        pass
    return Path(tempfile.gettempdir()) / f"rca_tpu_native-{os.getuid()}"


def _owned_and_private(path: Path, is_dir: bool) -> bool:
    """True when *path* is owned by us and not writable by group/other —
    the precondition for trusting a cached artifact enough to dlopen it."""
    try:
        st = os.stat(path, follow_symlinks=False)
    except OSError:
        return False
    if st.st_uid != os.getuid():
        return False
    if is_dir and not os.path.isdir(path):
        return False
    return (st.st_mode & 0o022) == 0


def _compile_cached(source: Path, out_prefix: str,
                    extra_flags: List[str]) -> Optional[Path]:
    """Shared lazy-compile pipeline: hash-tagged cache in a user-private
    0700 dir (RCA_NATIVE_CACHE overrides the location, not the ownership
    checks), unpredictable-suffix tmp + atomic rename, g++; None when the source,
    toolchain, or a trustworthy cache dir is unavailable.  Used by both
    the ctypes log scanner and the sanitize CPython extension."""
    import sysconfig

    try:
        src = source.read_bytes()
    except OSError:
        return None
    # the tag must bind the artifact to THIS interpreter's ABI: a CPython
    # extension built under another Python would be dlopen'd from the
    # shared cache and crash, not fall back (the ctypes logscan .so is
    # ABI-independent but rides the same scheme harmlessly)
    abi = sysconfig.get_config_var("SOABI") or "unknown-abi"
    tag = hashlib.sha256(src + abi.encode()).hexdigest()[:16]
    env_dir = env_raw("RCA_NATIVE_CACHE")
    if env_dir:
        # an explicitly-configured path may be the user's own symlink to a
        # private scratch dir; check the TARGET's ownership, not the
        # link's lstat-mode-0777
        cache_dir = Path(env_dir).resolve()
    else:
        cache_dir = _default_cache_dir()
        if cache_dir.is_symlink():
            # the /tmp fallback name is predictable and /tmp is
            # world-writable: a pre-seeded symlink would redirect the
            # chmod+compile into an attacker-chosen victim-owned dir
            return None
    try:
        if env_dir:
            # an explicitly-configured location may sit under deliberately
            # shared parents: those follow the site's umask so teammates
            # keep traversal rights.  The LEAF is still created 0700 (a
            # fresh leaf is ours; an existing one is ownership-checked,
            # never chmod'ed, below)
            cache_dir.parent.mkdir(parents=True, exist_ok=True)
            cache_dir.mkdir(mode=0o700, exist_ok=True)
        else:
            # default location: mkdir(parents=True) gives INTERMEDIATE
            # dirs the umask default, which under umask 002 would leave a
            # freshly-created ~/.cache group-writable and void the leaf
            # ownership check — create every missing component 0700
            for part in (*reversed(cache_dir.parents), cache_dir):
                if not part.exists():
                    part.mkdir(mode=0o700, exist_ok=True)
    except OSError:
        return None
    if not _owned_and_private(cache_dir, is_dir=True):
        # DEFAULT dir + our uid: our own artifact of a looser-umask era —
        # repair like the stale-.so branch below.  An env-configured dir
        # may be deliberately shared (mode 2775 team cache): never mutate
        # its permissions; anyone else's dir stays untrusted.  Either
        # rejection must be observable, not a silent permanent fallback
        # to the slow Python paths.
        repairable = False
        if not env_dir:
            try:
                repairable = os.stat(
                    cache_dir, follow_symlinks=False
                ).st_uid == os.getuid()
            except OSError:
                return None
        if not repairable:
            import warnings
            warnings.warn(
                f"native cache dir {cache_dir} is not exclusively owned "
                "by this user; native log scanner/sanitizer disabled "
                "(point RCA_NATIVE_CACHE at a private, user-owned path)",
                RuntimeWarning, stacklevel=2,
            )
            return None
        try:
            os.chmod(cache_dir, 0o700)
        except OSError:
            return None
        if not _owned_and_private(cache_dir, is_dir=True):
            return None
    out = cache_dir / f"{out_prefix}-{tag}.so"
    if out.exists():
        if _owned_and_private(out, is_dir=False):
            return out
        # the dir passed the ownership check, so nobody else could have
        # written this — it's our own stale artifact from a looser umask
        # era; rebuild rather than silently losing the native path forever
        try:
            # missing_ok: a concurrent process may have won the same repair
            out.unlink(missing_ok=True)
        except OSError:
            return None
    # unpredictable tmp name: a pid suffix could be pre-planted as a
    # symlink while the dir was still loose, and g++ -o writes THROUGH a
    # symlink (O_TRUNC on the victim file)
    import secrets
    tmp = out.with_suffix(f".{secrets.token_hex(8)}.tmp.so")
    cmd = (["g++", "-O2", "-shared", "-fPIC"] + extra_flags
           + [str(source), "-o", str(tmp)])
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            return None
        # g++ output inherits the umask; under umask 0002 that leaves the
        # group-write bit set and every LATER process would reject the
        # cached artifact via _owned_and_private and silently lose the
        # native path — normalize so fresh artifacts pass their own check
        os.chmod(tmp, 0o600)
        os.replace(tmp, out)
        return out
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        try:
            tmp.unlink(missing_ok=True)  # no-op when os.replace moved it
        except OSError:
            pass


def _build_library() -> Optional[Path]:
    """Compile logscan.cpp into a cached .so; None when no toolchain."""
    return _compile_cached(_SOURCE, "liblogscan", ["-std=c++17"])


def load_native() -> Optional[ctypes.CDLL]:
    """The compiled scanner, or None (disabled / no compiler / failed)."""
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if env_str("RCA_NATIVE_SCAN", "auto", choices=("auto", "0", "1")) == "0":
        return None
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
        lib.rca_load_spec.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.rca_load_spec.restype = ctypes.c_int
        lib.rca_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.rca_scan.restype = ctypes.c_int
        spec = serialize_spec()
        n = lib.rca_load_spec(spec, len(spec))
        if n != len(SPEC_TABLE):
            return None
        _lib = lib
    except OSError:
        return None
    return _lib


def native_available() -> bool:
    return load_native() is not None


def scan_text_native(text: str) -> Optional[np.ndarray]:
    """Counts per class via the C++ scanner; None when unavailable."""
    lib = load_native()
    if lib is None:
        return None
    data = text.encode("utf-8", errors="replace")
    counts = (ctypes.c_int32 * len(SPEC_TABLE))()
    rc = lib.rca_scan(data, len(data), counts)
    if rc != 0:
        return None
    return np.asarray(list(counts), dtype=np.int32)


# ---- native sanitizer (CPython extension; see sanitizec.c) ---------------

_SAN_SOURCE = Path(__file__).with_name("sanitizec.c")
_san_mod = None
_san_load_attempted = False


def _build_sanitize_ext() -> Optional[Path]:
    """Compile sanitizec.c into a cached extension .so; None w/o toolchain."""
    import sysconfig

    include = sysconfig.get_paths().get("include")
    if not include or not os.path.exists(os.path.join(include, "Python.h")):
        return None
    return _compile_cached(
        _SAN_SOURCE, "sanitizec", ["-x", "c", f"-I{include}"]
    )


def load_sanitize():
    """The native sanitize extension module, or None (disabled/unbuildable).

    Extension modules must be loaded through importlib's machinery (they
    export PyInit_*, not a C ABI), so this is not a ctypes load like the
    log scanner's."""
    global _san_mod, _san_load_attempted
    if _san_load_attempted:
        return _san_mod
    _san_load_attempted = True
    if env_str("RCA_NATIVE_SANITIZE", "auto",
               choices=("auto", "0", "1")) == "0":
        return None
    path = _build_sanitize_ext()
    if path is None:
        return None
    try:
        import importlib.machinery
        import importlib.util

        # the module name MUST match the C PyInit_<name> symbol
        loader = importlib.machinery.ExtensionFileLoader(
            "sanitizec", str(path)
        )
        spec = importlib.util.spec_from_loader("sanitizec", loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        _san_mod = mod
    except Exception:
        _san_mod = None
    return _san_mod
