/* Native K8s-object sanitizer: CPython extension twin of
 * rca_tpu/cluster/sanitize.py.
 *
 * The Python sanitizer walks ~1.2M nodes per 10k-pod snapshot — pure
 * CPython call overhead (~0.6 s); this extension does the same walk with
 * identical copy-on-write semantics in ~tens of ms.  Exact behavioral
 * parity with the Python implementation is enforced by
 * tests/test_native.py (fuzzed objects through both, deep equality) —
 * any divergence is a bug HERE, the Python version is the spec.
 *
 * Built lazily by rca_tpu.native.load_sanitize() with g++ against the
 * interpreter's own headers; the Python path is the always-available
 * fallback (RCA_NATIVE_SANITIZE=0 disables).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* ---- key sets (mirror sanitize.py; keep sorted groups in sync) ------- */

static const char *DICT_KEYS[] = {
    "metadata", "spec", "status", "labels", "annotations", "selector",
    "matchLabels", "template", "involvedObject", "source", "resources",
    "requests", "limits", "state", "lastState", "waiting", "running",
    "terminated", "securityContext", "configMapRef", "secretRef",
    "configMapKeyRef", "secretKeyRef", "valueFrom", "configMap", "secret",
    "emptyDir", "backend", "service", "http", "scaleTargetRef",
    "podSelector", "namespaceSelector", "capacity", "allocatable",
    "nodeInfo", "hard", "used", NULL,
};

static const char *LIST_KEYS[] = {
    "containers", "initContainers", "containerStatuses",
    "initContainerStatuses", "conditions", "env", "envFrom", "volumes",
    "volumeMounts", "subsets", "addresses", "notReadyAddresses", "ports",
    "rules", "paths", "ingress", "egress", "from", "to", "items",
    "ownerReferences", "accessModes", NULL,
};

static const char *NAMED_LIST_KEYS[] = {
    "containers", "initContainers", "containerStatuses",
    "initContainerStatuses", "env", NULL,
};

static const char *STR_MAP_KEYS[] = {
    "labels", "annotations", "matchLabels", "nodeSelector", NULL,
};

static const char *INT_KEYS[] = {
    "restartCount", "replicas", "readyReplicas", "availableReplicas",
    "updatedReplicas", "currentReplicas", "desiredReplicas", "minReplicas",
    "maxReplicas", "exitCode", "count", "observedGeneration",
    "numberReady", "desiredNumberScheduled", "currentNumberScheduled", NULL,
};

static const char *STR_KEYS[] = {
    "phase", "reason", "message", "type", "kind", "namespace", "fieldPath",
    "host", "image", "apiVersion", "component", "firstTimestamp",
    "lastTimestamp", "creationTimestamp", "startedAt", "finishedAt", NULL,
};

static int in_set(const char *key, const char **set) {
    if (key == NULL) return 0;
    const char k0 = key[0];
    for (const char **p = set; *p; ++p) {
        /* first-char pre-filter: most probes fail here without a strcmp */
        if ((*p)[0] == k0 && strcmp(key, *p) == 0) return 1;
    }
    return 0;
}

/* utf8 of an exact-str key, or NULL for non-string / non-encodable keys
 * (a lone-surrogate key sets a UnicodeEncodeError that MUST be cleared,
 * or the extension returns a value with an exception pending) */
static const char *key_utf8(PyObject *k) {
    /* subclass-of-str keys must classify like the spec (frozenset
     * membership is hash/eq based), so Check, not CheckExact */
    if (!PyUnicode_Check(k)) return NULL;
    const char *s = PyUnicode_AsUTF8(k);
    if (s == NULL) PyErr_Clear();
    return s;
}

/* str(x or "") — falsy -> "", else str(x).  New reference. */
static PyObject *str_or_empty(PyObject *x) {
    int truthy = x == NULL ? 0 : PyObject_IsTrue(x);
    if (truthy < 0) return NULL;
    if (!truthy) return PyUnicode_FromString("");
    return PyObject_Str(x);
}

static PyObject *empty_metadata(void) {
    PyObject *md = PyDict_New();
    if (!md) return NULL;
    PyObject *name = PyUnicode_FromString("");
    PyObject *labels = PyDict_New();
    if (!name || !labels ||
        PyDict_SetItemString(md, "name", name) < 0 ||
        PyDict_SetItemString(md, "labels", labels) < 0) {
        Py_XDECREF(name); Py_XDECREF(labels); Py_DECREF(md);
        return NULL;
    }
    Py_DECREF(name); Py_DECREF(labels);
    return md;
}

/* forward */
static PyObject *sanitize(PyObject *obj, const char *parent_key);

/* metadata name/labels repair on a dict; returns new ref (may be obj). */
static PyObject *fix_metadata(PyObject *md, PyObject *orig) {
    PyObject *name = PyDict_GetItemString(md, "name");      /* borrowed */
    PyObject *labels = PyDict_GetItemString(md, "labels");  /* borrowed */
    int name_ok = name != NULL && PyUnicode_CheckExact(name);
    int labels_ok = labels != NULL && PyDict_CheckExact(labels);
    if (name_ok && labels_ok) { Py_INCREF(md); return md; }
    PyObject *out = md == orig ? PyDict_Copy(md) : (Py_INCREF(md), md);
    if (!out) return NULL;
    PyObject *fixed_name = name_ok ? NULL : str_or_empty(name);
    if (!name_ok) {
        if (!fixed_name || PyDict_SetItemString(out, "name", fixed_name) < 0) {
            Py_XDECREF(fixed_name); Py_DECREF(out); return NULL;
        }
        Py_DECREF(fixed_name);
    }
    if (!labels_ok) {
        PyObject *fresh = PyDict_New();
        if (!fresh || PyDict_SetItemString(out, "labels", fresh) < 0) {
            Py_XDECREF(fresh); Py_DECREF(out); return NULL;
        }
        Py_DECREF(fresh);
    }
    return out;
}

static PyObject *sanitize_dict(PyObject *obj, const char *parent_key) {
    if (in_set(parent_key, STR_MAP_KEYS)) {
        /* all-string fast path */
        PyObject *k, *v; Py_ssize_t pos = 0; int clean = 1;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (!PyUnicode_CheckExact(k) || !PyUnicode_CheckExact(v)) {
                clean = 0; break;
            }
        }
        if (clean) { Py_INCREF(obj); return obj; }
        PyObject *out = PyDict_New();
        if (!out) return NULL;
        pos = 0;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            PyObject *ks = PyObject_Str(k);
            PyObject *vs = v == Py_None ? PyUnicode_FromString("")
                                        : PyObject_Str(v);
            if (!ks || !vs || PyDict_SetItem(out, ks, vs) < 0) {
                Py_XDECREF(ks); Py_XDECREF(vs); Py_DECREF(out); return NULL;
            }
            Py_DECREF(ks); Py_DECREF(vs);
        }
        return out;
    }

    int in_conditions = parent_key && strcmp(parent_key, "conditions") == 0;
    PyObject *out = NULL;  /* allocated only when something changes */
    PyObject *k, *v; Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &k, &v)) {
        const char *ku = key_utf8(k);
        /* "status" is a dict at object top level but a STRING inside
         * condition entries — strip the key context there (spec:
         * sanitize.py child_key) */
        if (in_conditions && ku && strcmp(ku, "status") == 0) ku = "";
        PyObject *nv = sanitize(v, ku);  /* new ref */
        if (!nv) { Py_XDECREF(out); return NULL; }
        if (nv == Py_None) {
            if (in_set(ku, INT_KEYS)) {
                Py_DECREF(nv); nv = PyLong_FromLong(0);
            } else if (in_set(ku, STR_KEYS)) {
                Py_DECREF(nv); nv = PyUnicode_FromString("");
            }
        } else if (in_set(ku, DICT_KEYS) && !PyDict_CheckExact(nv)) {
            /* a replaced metadata must still satisfy the name/labels
             * invariant — same repair as the None branch (spec:
             * sanitize.py metadata coercion) */
            Py_DECREF(nv);
            nv = (ku && strcmp(ku, "metadata") == 0) ? empty_metadata()
                                                     : PyDict_New();
        } else if (in_set(ku, LIST_KEYS) && !PyList_CheckExact(nv)) {
            Py_DECREF(nv); nv = PyList_New(0);
        }
        if (!nv) { Py_XDECREF(out); return NULL; }
        if (nv != v) {
            if (out == NULL) {
                out = PyDict_Copy(obj);
                if (!out) { Py_DECREF(nv); return NULL; }
            }
            if (PyDict_SetItem(out, k, nv) < 0) {
                Py_DECREF(nv); Py_DECREF(out); return NULL;
            }
        }
        Py_DECREF(nv);
    }
    PyObject *result = out ? out : (Py_INCREF(obj), obj);
    if (parent_key && strcmp(parent_key, "metadata") == 0) {
        PyObject *fixed = fix_metadata(result, obj);
        Py_DECREF(result);
        return fixed;
    }
    return result;
}

static PyObject *sanitize_list(PyObject *obj, const char *parent_key) {
    int named = in_set(parent_key, NAMED_LIST_KEYS);
    int is_env = parent_key && strcmp(parent_key, "env") == 0;
    int obj_entries = in_set(parent_key, LIST_KEYS) &&
        !(parent_key && strcmp(parent_key, "accessModes") == 0);
    PyObject *out = NULL;
    Py_ssize_t n = PyList_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject *v = PyList_GET_ITEM(obj, i);  /* borrowed */
        PyObject *nv;
        if (v == Py_None && obj_entries) {
            nv = PyDict_New();
        } else {
            nv = sanitize(v, parent_key);
        }
        if (!nv) { Py_XDECREF(out); return NULL; }
        if (PyDict_CheckExact(nv)) {
            if (named) {
                PyObject *name = PyDict_GetItemString(nv, "name");
                if (name == NULL || !PyUnicode_CheckExact(name)) {
                    PyObject *copy = nv == v ? PyDict_Copy(nv)
                                             : (Py_INCREF(nv), nv);
                    Py_DECREF(nv);
                    if (!copy) { Py_XDECREF(out); return NULL; }
                    nv = copy;
                    PyObject *fixed = str_or_empty(name);
                    if (!fixed ||
                        PyDict_SetItemString(nv, "name", fixed) < 0) {
                        Py_XDECREF(fixed); Py_DECREF(nv);
                        Py_XDECREF(out); return NULL;
                    }
                    Py_DECREF(fixed);
                }
            }
            if (is_env) {
                PyObject *vf = PyDict_GetItemString(nv, "valueFrom");
                int has_vf = vf == NULL ? 0 : PyObject_IsTrue(vf);
                if (has_vf < 0) { Py_DECREF(nv); Py_XDECREF(out); return NULL; }
                /* spec uses nv.get("value") is None: a MISSING value key
                 * is normalized to "" too */
                PyObject *val = PyDict_GetItemString(nv, "value");
                int val_is_null = (val == NULL || val == Py_None);
                if (!has_vf && val_is_null) {
                    PyObject *copy = nv == v ? PyDict_Copy(nv)
                                             : (Py_INCREF(nv), nv);
                    Py_DECREF(nv);
                    if (!copy) { Py_XDECREF(out); return NULL; }
                    nv = copy;
                    PyObject *empty = PyUnicode_FromString("");
                    if (!empty ||
                        PyDict_SetItemString(nv, "value", empty) < 0) {
                        Py_XDECREF(empty); Py_DECREF(nv);
                        Py_XDECREF(out); return NULL;
                    }
                    Py_DECREF(empty);
                }
            }
        }
        if (nv != v) {
            if (out == NULL) {
                out = PyList_GetSlice(obj, 0, n);
                if (!out) { Py_DECREF(nv); return NULL; }
            }
            /* PyList_SetItem steals nv */
            if (PyList_SetItem(out, i, nv) < 0) {
                Py_DECREF(out); return NULL;
            }
        } else {
            Py_DECREF(nv);
        }
    }
    return out ? out : (Py_INCREF(obj), obj);
}

static PyObject *sanitize(PyObject *obj, const char *parent_key) {
    if (obj == Py_None) {
        if (parent_key && strcmp(parent_key, "metadata") == 0)
            return empty_metadata();
        if (in_set(parent_key, DICT_KEYS)) return PyDict_New();
        if (in_set(parent_key, LIST_KEYS)) return PyList_New(0);
        Py_RETURN_NONE;
    }
    if (PyDict_CheckExact(obj) || PyList_CheckExact(obj)) {
        /* convert hostile nesting depth into RecursionError like the
         * Python spec, instead of overflowing the C stack */
        if (Py_EnterRecursiveCall(" in rca_tpu native sanitize"))
            return NULL;
        PyObject *out = PyDict_CheckExact(obj)
            ? sanitize_dict(obj, parent_key)
            : sanitize_list(obj, parent_key);
        Py_LeaveRecursiveCall();
        return out;
    }
    Py_INCREF(obj);
    return obj;
}

/* ---- module ---------------------------------------------------------- */

static PyObject *py_sanitize_object(PyObject *self, PyObject *args) {
    PyObject *obj;
    const char *parent_key = "";
    if (!PyArg_ParseTuple(args, "O|s", &obj, &parent_key)) return NULL;
    return sanitize(obj, parent_key);
}

static PyMethodDef Methods[] = {
    {"sanitize_object", py_sanitize_object, METH_VARARGS,
     "Recursively normalize one K8s object (native twin of "
     "rca_tpu.cluster.sanitize.sanitize_object)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "sanitizec", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit_sanitizec(void) {
    return PyModule_Create(&moduledef);
}
