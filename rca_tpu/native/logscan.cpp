// Native multi-pattern log scanner (the hot host-side op of feature
// extraction).  Counts non-overlapping matches of N pattern classes, each an
// ordered list of alternatives, mirroring Python re.findall alternation
// semantics (leftmost position, first matching branch, consume the span).
//
// Pattern mini-language (compiled by rca_tpu/native/__init__.py):
//   ordinary byte        literal (spec is pre-lowercased for CI classes)
//   \x01                 exactly one ASCII digit
//   \x02                 one or more word chars [A-Za-z0-9_] (max-munch)
//   \x03                 zero or more whitespace chars
//   \x04                 exactly one whitespace char
//   \x06                 greedy any-chars-within-line, must be followed by a
//                        literal tail: consumes up to the LAST occurrence of
//                        that tail on the current line (mirrors greedy `.*`)
//
// Per-alternative flags: bit0 = whole-word boundary at both ends,
// bit1 = case-sensitive (match against the original text, not the
// lowercased copy).
//
// Serialized spec: classes joined by '\x1e'; alternatives joined by '\x1f';
// each alternative = one flags byte ('0' + flags) followed by pattern bytes.
//
// Exposed C ABI:
//   rca_scan(text, len, counts_out)     counts per class into int32[n]
//   rca_load_spec(spec, len) -> n       compile the spec (process-global)

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

inline bool is_word(unsigned char c) {
    return std::isalnum(c) || c == '_';
}

struct Alt {
    std::string pat;   // token string
    bool word_bound;
    bool case_sensitive;
};

struct Class {
    std::vector<Alt> alts;
    bool first[256] = {false};  // possible first bytes in lowercased text
};

// Mark the possible first bytes of pattern p (from token k) into `first`,
// as seen in the lowercased text (case-sensitive alts fold to lowercase —
// a sound over-approximation since lower[i] == tolower(orig[i])).
void mark_first_bytes(const std::string& p, size_t k, bool* first) {
    if (k >= p.size()) return;
    unsigned char tok = p[k];
    if (tok == 0x01) {
        for (unsigned char c = '0'; c <= '9'; ++c) first[c] = true;
    } else if (tok == 0x02) {
        for (int c = 0; c < 256; ++c)
            if (is_word((unsigned char)c)) first[c] = true;
    } else if (tok == 0x03) {
        for (int c = 0; c < 256; ++c)
            if (std::isspace(c) && c != '\n') first[c] = true;
        mark_first_bytes(p, k + 1, first);  // \x03 may match empty
    } else if (tok == 0x04) {
        for (int c = 0; c < 256; ++c)
            if (std::isspace(c)) first[c] = true;
    } else if (tok == 0x06) {
        for (int c = 0; c < 256; ++c) first[c] = true;
    } else {
        first[std::tolower(tok)] = true;
    }
}

std::vector<Class> g_classes;

// Try to match one alternative at text[pos..]; returns match end or -1.
// `lower` is the lowercased text, `orig` the original; both share length n.
long match_at(const Alt& alt, const char* lower, const char* orig, long n,
              long pos) {
    const char* text = alt.case_sensitive ? orig : lower;
    if (alt.word_bound && pos > 0 && is_word(text[pos - 1])) return -1;
    long i = pos;
    const std::string& p = alt.pat;
    for (size_t k = 0; k < p.size(); ++k) {
        unsigned char tok = p[k];
        if (tok == 0x01) {                      // one digit
            if (i >= n || !std::isdigit((unsigned char)text[i])) return -1;
            ++i;
        } else if (tok == 0x02) {               // 1+ word chars
            long start = i;
            while (i < n && is_word(text[i])) ++i;
            if (i == start) return -1;
        } else if (tok == 0x03) {               // 0+ whitespace
            while (i < n && std::isspace((unsigned char)text[i]) &&
                   text[i] != '\n')
                ++i;
        } else if (tok == 0x04) {               // exactly 1 whitespace
            if (i >= n || !std::isspace((unsigned char)text[i])) return -1;
            ++i;
        } else if (tok == 0x06) {               // greedy .* then literal tail
            std::string tail = p.substr(k + 1);
            if (tail.empty()) return -1;
            long line_end = i;
            while (line_end < n && text[line_end] != '\n') ++line_end;
            // last occurrence of tail in [i, line_end)
            long best = -1;
            long limit = line_end - (long)tail.size();
            for (long j = i; j <= limit; ++j) {
                if (std::memcmp(text + j, tail.data(), tail.size()) == 0)
                    best = j;
            }
            if (best < 0) return -1;
            i = best + (long)tail.size();
            k = p.size();  // tail consumed the rest of the pattern
            break;
        } else {                                // literal byte
            if (i >= n || text[i] != (char)tok) return -1;
            ++i;
        }
    }
    if (alt.word_bound && i < n && is_word(text[i])) return -1;
    return i;
}

}  // namespace

extern "C" {

// Compile the serialized spec; returns the number of classes (or -1).
int rca_load_spec(const char* spec, long len) {
    g_classes.clear();
    std::string s(spec, (size_t)len);
    size_t start = 0;
    while (start <= s.size()) {
        size_t end = s.find('\x1e', start);
        if (end == std::string::npos) end = s.size();
        std::string cls = s.substr(start, end - start);
        Class c;
        size_t a = 0;
        while (a <= cls.size() && !cls.empty()) {
            size_t b = cls.find('\x1f', a);
            if (b == std::string::npos) b = cls.size();
            std::string alt = cls.substr(a, b - a);
            if (!alt.empty()) {
                int flags = alt[0] - '0';
                Alt rec;
                rec.word_bound = flags & 1;
                rec.case_sensitive = flags & 2;
                rec.pat = alt.substr(1);
                mark_first_bytes(rec.pat, 0, c.first);
                c.alts.push_back(rec);
            }
            if (b == cls.size()) break;
            a = b + 1;
        }
        g_classes.push_back(c);
        if (end == s.size()) break;
        start = end + 1;
    }
    return (int)g_classes.size();
}

// Count matches for every class into counts[0..n_classes).
int rca_scan(const char* text, long n, int32_t* counts) {
    std::string lower((size_t)n, '\0');
    for (long i = 0; i < n; ++i)
        lower[(size_t)i] = (char)std::tolower((unsigned char)text[i]);
    const char* lo = lower.data();

    for (size_t ci = 0; ci < g_classes.size(); ++ci) {
        const Class& cls = g_classes[ci];
        int32_t count = 0;
        long pos = 0;
        while (pos < n) {
            if (!cls.first[(unsigned char)lo[pos]]) {  // fast reject
                ++pos;
                continue;
            }
            long end = -1;
            for (const Alt& alt : cls.alts) {
                end = match_at(alt, lo, text, n, pos);
                if (end >= 0) break;
            }
            if (end >= 0 && end > pos) {
                ++count;
                pos = end;
            } else {
                ++pos;
            }
        }
        counts[ci] = count;
    }
    return 0;
}

}  // extern "C"
