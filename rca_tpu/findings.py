"""Finding / reasoning-step data model shared by every agent and the engine.

Schema parity with the reference's finding dicts
(reference: agents/base_agent.py:33-52 — ``{component, issue, severity,
evidence, recommendation, timestamp}``) and its severity ladder
(reference: agents/coordinator.py:148 — info < low < medium < high < critical).
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

SEVERITY_ORDER: List[str] = ["info", "low", "medium", "high", "critical"]
SEVERITY_RANK: Dict[str, int] = {s: i for i, s in enumerate(SEVERITY_ORDER)}


def severity_rank(severity: str) -> int:
    """Rank of a severity string; unknown severities rank below ``info``."""
    return SEVERITY_RANK.get(str(severity).lower(), -1)


def max_severity(severities) -> str:
    """Highest severity in an iterable (defaults to ``info`` when empty)."""
    best = "info"
    for s in severities:
        if severity_rank(s) > severity_rank(best):
            best = s
    return best


def utcnow_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def humanize_age(creation_ts: str, now_ts: str) -> str:
    """'2d ago' / '3h ago' / '5m ago' from two ISO timestamps (reference:
    utils/k8s_client.py:949-1013 adds a createdAgo humanization to
    resource details).  Unparseable inputs return ''."""
    import datetime as _dt

    def parse(ts: str):
        return _dt.datetime.fromisoformat(str(ts).replace("Z", "+00:00"))

    try:
        delta = parse(now_ts) - parse(creation_ts)
    except (ValueError, TypeError):
        return ""
    seconds = max(int(delta.total_seconds()), 0)
    if seconds >= 86400:
        return f"{seconds // 86400}d ago"
    if seconds >= 3600:
        return f"{seconds // 3600}h ago"
    if seconds >= 60:
        return f"{seconds // 60}m ago"
    return f"{seconds}s ago"


def annotate_created_ago(data: dict, now_ts: str) -> dict:
    """Add the reference's ``createdAgo`` humanization to a resource-details
    dict (shared by both cluster clients so the logic cannot drift)."""
    meta = data.get("metadata", {}) or {}
    age = humanize_age(meta.get("creationTimestamp", ""), now_ts)
    if age:
        data["createdAgo"] = age
    return data


def attach_provenance(
    obj: Dict[str, Any], provenance: Optional[Dict[str, Any]]
) -> Dict[str, Any]:
    """Attach a causelens ``provenance`` block (ISSUE 14) to a findings
    JSON object (a correlate result, a finding dict) — the ONE place the
    block's schema is checked before it rides outward, so a malformed
    producer fails here instead of at a consumer.  ``None`` is a no-op
    (explain off)."""
    if provenance is None:
        return obj
    if not isinstance(provenance, dict) or not isinstance(
        provenance.get("schema"), int
    ):
        raise ValueError(
            "provenance must be a schema-versioned dict "
            "(rca_tpu.observability.causelens.provenance_block)"
        )
    obj["provenance"] = provenance
    return obj


def make_finding(
    component: str,
    issue: str,
    severity: str,
    evidence: Any,
    recommendation: str,
    timestamp: Optional[str] = None,
    **extra: Any,
) -> Dict[str, Any]:
    finding = {
        "component": component,
        "issue": issue,
        "severity": severity,
        "evidence": evidence,
        "recommendation": recommendation,
        "timestamp": timestamp or utcnow_iso(),
    }
    finding.update(extra)
    return finding


def make_reasoning_step(
    observation: str, conclusion: str, timestamp: Optional[str] = None
) -> Dict[str, str]:
    return {
        "observation": observation,
        "conclusion": conclusion,
        "timestamp": timestamp or utcnow_iso(),
    }


class FindingsMixin:
    """Accumulates findings + reasoning steps (the agent result contract)."""

    def __init__(self) -> None:
        self.findings: List[Dict[str, Any]] = []
        self.reasoning_steps: List[Dict[str, str]] = []

    def add_finding(
        self,
        component: str,
        issue: str,
        severity: str,
        evidence: Any,
        recommendation: str,
        **extra: Any,
    ) -> Dict[str, Any]:
        finding = make_finding(
            component, issue, severity, evidence, recommendation, **extra
        )
        self.findings.append(finding)
        return finding

    def add_reasoning_step(self, observation: str, conclusion: str) -> None:
        self.reasoning_steps.append(make_reasoning_step(observation, conclusion))

    def get_results(self) -> Dict[str, Any]:
        return {
            "findings": self.findings,
            "reasoning_steps": self.reasoning_steps,
        }

    def reset(self) -> None:
        self.findings = []
        self.reasoning_steps = []
