"""Feature-channel schemas for the packed pod and service arrays.

Pod channels encode the signals the reference's rule agents read one dict at
a time (reference: agents/resource_analyzer.py:275-351 status buckets,
agents/metrics_agent.py:88-151 utilization thresholds, agents/events_agent.py
:292-328 event counts).  Service channels are the fused per-service signal
vector the causal engine propagates; the first 8 match
:mod:`rca_tpu.cluster.generator`'s synthetic channels so generated cascades
and extracted worlds feed the same engine.
"""

from __future__ import annotations

import enum

from rca_tpu.features.logscan import LOG_PATTERN_NAMES


class PodF(enum.IntEnum):
    """Pod-level feature channels (float32)."""

    PHASE_PENDING = 0
    PHASE_RUNNING = 1
    PHASE_SUCCEEDED = 2
    PHASE_FAILED = 3
    PHASE_UNKNOWN = 4
    NOT_READY = 5          # any container not ready
    RESTARTS = 6           # raw restart count
    RESTARTS_SAT = 7       # 1 - exp(-restarts/5), saturating
    WAIT_CRASHLOOP = 8
    WAIT_IMAGEPULL = 9
    WAIT_CONFIG = 10       # CreateContainerConfigError family
    WAIT_OTHER = 11
    TERM_NONZERO = 12      # terminated (current or last) with exit code != 0
    TERM_OOM = 13          # terminated with reason OOMKilled
    INIT_FAILED = 14       # failing init container
    CPU_PCT = 15           # cpu usage / limit, 0..1+
    MEM_PCT = 16           # mem usage / limit, 0..1+
    WARN_EVENTS = 17       # warning-event count for this pod
    WARN_EVENTS_SAT = 18   # min(1, count/10)
    NO_LOGS = 19           # running but produced no logs
    LOG0 = 20              # first of the 13 log-pattern count channels


NUM_POD_FEATURES = int(PodF.LOG0) + len(LOG_PATTERN_NAMES)

POD_FEATURE_NAMES = [f.name.lower() for f in PodF if f != PodF.LOG0] + [
    f"log_{n}" for n in LOG_PATTERN_NAMES
]


class SvcF(enum.IntEnum):
    """Service-level feature channels (float32). First 8 mirror
    rca_tpu.cluster.generator channel order."""

    CRASH = 0        # crash/failed-pod fraction
    ERROR_RATE = 1   # trace error rate 0..1
    LATENCY = 2      # latency degradation score 0..1
    RESTARTS = 3     # saturating restart pressure
    EVENTS = 4       # saturating warning-event pressure
    LOG_ERRORS = 5   # saturating error-log pressure
    NOT_READY = 6    # unready pod / missing endpoint fraction
    RESOURCE = 7     # cpu/mem saturation 0..1
    IMAGE = 8        # image-pull failure fraction
    CONFIG = 9       # config/secret reference failure signal
    PENDING = 10     # unschedulable/pending fraction
    OOM = 11         # OOM-kill signal
    # DERIVED absence-evidence channel (VERDICT r3 item 4): not-ready with
    # zero crash/restart/log evidence.  A crashing pod proves it STARTED;
    # an image-pull / unschedulable / config-error root never does — its
    # victims crash and log while the root itself is silent, so "down but
    # silent" is evidence of being a root in its own right, surviving
    # adversarial dropout of the archetype's defining channel.  Computed by
    # :func:`derive_silent_channel` in BOTH the extractor and the
    # generator; never observed directly, so dropout never applies to it.
    SILENT = 12


# raw (observed) channels: everything before the derived block
NUM_RAW_SERVICE_FEATURES = int(SvcF.SILENT)
NUM_SERVICE_FEATURES = len(SvcF)

SERVICE_FEATURE_NAMES = [f.name.lower() for f in SvcF]


def derive_silent_channel(svc_features) -> None:
    """Fill ``SvcF.SILENT`` in-place from the raw channels of a
    ``[S, NUM_SERVICE_FEATURES]`` float array: the not-ready level damped
    by every channel that proves the workload actually ran (crashes,
    restarts, log output).  Quiet healthy services score ~0 (their
    not_ready is ~0); crash/oom roots score ~0 (their crash channel is
    high); an image/pending/config root whose pod never started scores
    near its not_ready level."""
    import numpy as np

    f = svc_features
    ran = (
        (1.0 - np.clip(f[:, SvcF.CRASH], 0.0, 1.0))
        * (1.0 - np.clip(f[:, SvcF.RESTARTS], 0.0, 1.0))
        * (1.0 - np.clip(f[:, SvcF.LOG_ERRORS], 0.0, 1.0))
    )
    f[:, SvcF.SILENT] = np.clip(f[:, SvcF.NOT_READY], 0.0, 1.0) * ran
