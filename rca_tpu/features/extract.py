"""Snapshot → packed feature arrays (the engine's host-side front end).

One pass over the snapshot builds:

- ``pod_features``  float32 [P, NUM_POD_FEATURES]
- ``service_features`` float32 [S, NUM_SERVICE_FEATURES] (segment-aggregated
  from pods + traces + endpoints)
- index maps (pod→service, pod→node) for segment ops on device.

This is the TPU-first replacement for the reference's per-pod Python loops
(reference: agents/resource_analyzer.py:275-351, mcp_coordinator.py:1205-1241):
parse once, aggregate with numpy segment ops, ship dense arrays to the
device.  Regex scanning stays on CPU (reference taxonomy, SURVEY.md §7.2);
only its counts go on device.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

import numpy as np

from rca_tpu.cluster.labels import SelectorIndex, selector_matches
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.features.logscan import LOG_PATTERN_NAMES, scan_pod_logs
from rca_tpu.features.schema import (
    NUM_POD_FEATURES,
    NUM_SERVICE_FEATURES,
    PodF,
    SvcF,
    derive_silent_channel,
)

_PHASES = {
    "Pending": PodF.PHASE_PENDING,
    "Running": PodF.PHASE_RUNNING,
    "Succeeded": PodF.PHASE_SUCCEEDED,
    "Failed": PodF.PHASE_FAILED,
}


@dataclasses.dataclass
class FeatureSet:
    namespace: str
    pod_names: List[str]
    pod_features: np.ndarray        # [P, NUM_POD_FEATURES] float32
    service_names: List[str]
    service_features: np.ndarray    # [S, NUM_SERVICE_FEATURES] float32
    pod_service: np.ndarray         # [P] int32 primary owner, -1 unmatched
    # full pod↔service membership as COO pairs — one pod can back several
    # services (e.g. a ClusterIP and a headless service sharing a selector)
    memb_pod: np.ndarray            # [M] int32 pod indices
    memb_svc: np.ndarray            # [M] int32 service indices
    node_names: List[str]
    pod_node: np.ndarray            # [P] int32, -1 when unknown
    node_features: np.ndarray       # [N, 2] float32 (cpu_pct, mem_pct)

    def service_members(self, j: int) -> np.ndarray:
        """Pod indices backing service ``j`` (all matches, not just primary)."""
        return self.memb_pod[self.memb_svc == j]

    @property
    def num_pods(self) -> int:
        return len(self.pod_names)

    @property
    def num_services(self) -> int:
        return len(self.service_names)


# back-compat alias; canonical definition lives in rca_tpu.cluster.labels
_selector_matches = selector_matches


def _container_status_flags(pod: dict, feat: np.ndarray) -> None:
    statuses = pod.get("status", {}).get("containerStatuses", []) or []
    restarts = 0
    any_not_ready = False
    for cs in statuses:
        restarts += int(cs.get("restartCount", 0) or 0)
        if not cs.get("ready", False):
            any_not_ready = True
        state = cs.get("state") or {}
        waiting = state.get("waiting") or {}
        reason = waiting.get("reason", "")
        if reason:
            if "CrashLoopBackOff" in reason:
                feat[PodF.WAIT_CRASHLOOP] = 1.0
            elif reason in ("ImagePullBackOff", "ErrImagePull", "InvalidImageName"):
                feat[PodF.WAIT_IMAGEPULL] = 1.0
            elif reason in ("CreateContainerConfigError", "CreateContainerError"):
                feat[PodF.WAIT_CONFIG] = 1.0
            else:
                feat[PodF.WAIT_OTHER] = 1.0
        for key in ("state", "lastState"):
            term = (cs.get(key) or {}).get("terminated") or {}
            if term:
                if int(term.get("exitCode", 0) or 0) != 0:
                    feat[PodF.TERM_NONZERO] = 1.0
                if term.get("reason") == "OOMKilled":
                    feat[PodF.TERM_OOM] = 1.0
    for ics in pod.get("status", {}).get("initContainerStatuses", []) or []:
        state = ics.get("state") or {}
        waiting = state.get("waiting") or {}
        term = state.get("terminated") or {}
        if "CrashLoopBackOff" in waiting.get("reason", "") or (
            term and int(term.get("exitCode", 0) or 0) != 0
        ):
            feat[PodF.INIT_FAILED] = 1.0
    feat[PodF.NOT_READY] = 1.0 if any_not_ready else 0.0
    feat[PodF.RESTARTS] = float(restarts)
    feat[PodF.RESTARTS_SAT] = 1.0 - math.exp(-restarts / 5.0)


def _metric_pcts(rec: Optional[dict]) -> tuple:
    if not rec:
        return 0.0, 0.0
    cpu = (rec.get("cpu") or {}).get("usage_percentage")
    mem = (rec.get("memory") or {}).get("usage_percentage")
    return (float(cpu or 0.0) / 100.0, float(mem or 0.0) / 100.0)


def _warn_counts(snapshot: ClusterSnapshot) -> Dict[str, int]:
    """Warning-event counts grouped by involved pod (one pass)."""
    warn_counts: Dict[str, int] = {}
    for ev in snapshot.events:
        if ev.get("type") == "Normal":
            continue
        obj = ev.get("involvedObject", {}) or {}
        if obj.get("kind") == "Pod":
            warn_counts[obj.get("name", "")] = warn_counts.get(
                obj.get("name", ""), 0
            ) + int(ev.get("count", 1) or 1)
    return warn_counts


def _pod_feature_row(
    pod: dict,
    warn_count: int,
    metrics_rec: Optional[dict],
    logs: Optional[Dict[str, str]],
    log_counts=None,
) -> np.ndarray:
    """One pod's feature row — THE row definition, shared by the full
    extraction and the incremental cache so the two cannot drift.
    ``log_counts`` lets a caller supply memoized regex-scan counts (a pure
    function of the log text, the most expensive part of the row)."""
    feat = np.zeros(NUM_POD_FEATURES, dtype=np.float32)
    status = pod.get("status", {}) or {}
    phase = status.get("phase", "Unknown")
    feat[_PHASES.get(phase, PodF.PHASE_UNKNOWN)] = 1.0
    _container_status_flags(pod, feat)
    cpu, mem = _metric_pcts(metrics_rec)
    feat[PodF.CPU_PCT] = cpu
    feat[PodF.MEM_PCT] = mem
    feat[PodF.WARN_EVENTS] = float(warn_count)
    feat[PodF.WARN_EVENTS_SAT] = min(1.0, warn_count / 10.0)
    if logs is not None:
        counts = log_counts if log_counts is not None else scan_pod_logs(logs)
        feat[PodF.LOG0 : PodF.LOG0 + len(LOG_PATTERN_NAMES)] = counts
        if phase == "Running" and not any(t.strip() for t in logs.values()):
            feat[PodF.NO_LOGS] = 1.0
    return feat


class IncrementalExtractor:
    """Snapshot → FeatureSet with per-service/pod memoization across
    repeated captures (ISSUE 2: the busy-poll capture path re-derived every
    unchanged row every tick — at 10k services that is 10k regex log scans
    and 10k selector matches to refresh a handful of journaled changes).

    Three caches, each keyed so a stale hit is impossible:

    - **row cache** — full pod feature rows keyed by the pod object's
      ``metadata.resourceVersion`` plus the row's other inputs (warn-event
      count, cpu/mem percentages, log content key).  Every API-server write
      bumps ``resourceVersion`` (the mock ``World`` mirrors this in
      ``touch``), so an unchanged rv + unchanged sidecar inputs means an
      unchanged row.  Pods without an rv (hand-built fixtures) are simply
      recomputed — correctness never depends on the cache.  Consulted only
      on ``incremental=True`` extractions (the watch patch path, where
      every mutation is journal-mediated by construction); full sweeps
      recompute rows and REFRESH the cache, so an out-of-band mutation
      corrected by a sweep cannot resurrect from a stale entry.
    - **log-scan cache** — regex pattern counts keyed by the log text
      itself (a pure function of content, valid in every mode; Python
      memoizes string hashes, so the key costs one hash per new string).
    - **selector memo** — pod→service matches keyed by the pod's label set,
      reset whenever any service selector changes (also content-keyed and
      mode-independent).

    The numpy service aggregation (segment ops over the memberships) is
    vectorized over the full matrix either way — it is microseconds next
    to the per-pod Python work this class avoids.

    ``extract_features`` (the plain function) runs a fresh instance in
    full mode, so the one-shot path is bit-identical by construction;
    parity after arbitrary update/delete sequences is property-tested in
    tests/test_tick_pipeline.py.
    """

    def __init__(self) -> None:
        self._rows: Dict[str, tuple] = {}
        self._log_counts: Dict[tuple, np.ndarray] = {}
        self._hits_memo: Dict[tuple, List[int]] = {}
        self._selector_sig: Optional[tuple] = None

    def extract(self, snapshot: ClusterSnapshot,
                incremental: bool = True) -> FeatureSet:
        if getattr(snapshot, "columnar", None) is not None:
            # columnar capture (ISSUE 10): the per-pod work was already
            # done as row writes when the world mutated; the view carries
            # the assembled matrix + memberships, so extraction is just
            # the (vectorized) service aggregation.  Bit-identical to the
            # dict loop below — property-tested in tests/test_columnar.py.
            return _extract_columnar(snapshot)
        pods = snapshot.pods
        P = len(pods)
        pod_names = [
            p.get("metadata", {}).get("name", f"pod-{i}")
            for i, p in enumerate(pods)
        ]
        warn_counts = _warn_counts(snapshot)
        metrics_by_pod = (snapshot.pod_metrics or {}).get("pods", {})

        node_names = [
            n.get("metadata", {}).get("name", "") for n in snapshot.nodes
        ]
        node_index = {n: i for i, n in enumerate(node_names)}
        pod_node = np.full(P, -1, dtype=np.int32)

        # -- pod → service assignment (selector ⊆ labels) ------------------
        service_names = [
            s.get("metadata", {}).get("name", f"svc-{j}")
            for j, s in enumerate(snapshot.services)
        ]
        selectors = [
            (s.get("spec", {}) or {}).get("selector") or {}
            for s in snapshot.services
        ]
        try:
            selector_sig = tuple(
                (service_names[j], tuple(sorted(selectors[j].items())))
                for j in range(len(service_names))
            )
        except TypeError:
            selector_sig = None  # unhashable selector values: no memo
        if selector_sig != self._selector_sig or selector_sig is None:
            self._hits_memo = {}
            self._selector_sig = selector_sig
        # inverted selector index: O(labels) per pod.  Every matching
        # service is recorded (one pod may back several services, e.g.
        # ClusterIP + headless sharing a selector); pod_service keeps the
        # first match as primary owner.
        index = SelectorIndex(selectors)
        hits_memo = self._hits_memo

        pod_features = np.zeros((P, NUM_POD_FEATURES), dtype=np.float32)
        pod_service = np.full(P, -1, dtype=np.int32)
        memb_pod: List[int] = []
        memb_svc: List[int] = []
        new_rows: Dict[str, tuple] = {}
        new_log_counts: Dict[tuple, np.ndarray] = {}

        for i, pod in enumerate(pods):
            name = pod_names[i]
            md = pod.get("metadata", {}) or {}
            wc = warn_counts.get(name, 0)
            rec = metrics_by_pod.get(name)
            logs = snapshot.logs.get(name)
            logs_key: Optional[tuple] = None
            counts = None
            if logs is not None:
                try:
                    logs_key = tuple(sorted(logs.items()))
                except TypeError:
                    logs_key = None
                if logs_key is not None:
                    counts = self._log_counts.get(logs_key)
            rv = md.get("resourceVersion")
            sig = (rv, wc, _metric_pcts(rec), logs_key)
            row = None
            if incremental and rv is not None:
                cached = self._rows.get(name)
                if cached is not None and cached[0] == sig:
                    row = cached[1]
            if row is None:
                if logs is not None and counts is None:
                    counts = scan_pod_logs(logs)
                row = _pod_feature_row(pod, wc, rec, logs, counts)
            if logs_key is not None and counts is not None:
                new_log_counts[logs_key] = counts
            if rv is not None:
                new_rows[name] = (sig, row)
            pod_features[i] = row

            labels = md.get("labels", {}) or {}
            try:
                labels_key: Optional[tuple] = tuple(sorted(labels.items()))
            except TypeError:
                labels_key = None
            hits = (
                hits_memo.get(labels_key) if labels_key is not None else None
            )
            if hits is None:
                hits = index.matches(labels)
                if labels_key is not None:
                    hits_memo[labels_key] = hits
            if hits:
                pod_service[i] = hits[0]
                memb_pod.extend([i] * len(hits))
                memb_svc.extend(hits)

            node = pod.get("spec", {}).get("nodeName")
            if node in node_index:
                pod_node[i] = node_index[node]

        # replace (not merge) the per-name/content caches: entries for
        # deleted pods and superseded log tails drop out here, so the
        # cache footprint tracks the live cluster, not its history
        self._rows = new_rows
        self._log_counts = new_log_counts

        memb_pod_arr = np.asarray(memb_pod, dtype=np.int32)
        memb_svc_arr = np.asarray(memb_svc, dtype=np.int32)
        return _aggregate_services(
            snapshot, pod_names, pod_features, service_names, selectors,
            pod_service, memb_pod_arr, memb_svc_arr,
            node_names, pod_node,
        )


def extract_features(snapshot: ClusterSnapshot) -> FeatureSet:
    """One-shot full extraction (a fresh :class:`IncrementalExtractor` in
    full mode — ONE row/aggregation definition for both paths)."""
    return IncrementalExtractor().extract(snapshot, incremental=False)


def _extract_columnar(snapshot: ClusterSnapshot) -> FeatureSet:
    """[no-dict-scan] Vectorized extraction off a columnar capture: every
    per-pod quantity (feature rows, selector memberships, node indices)
    was assembled from column slices at capture time
    (:meth:`rca_tpu.cluster.columnar.ColumnarWorld.build_view`); only the
    shared service aggregation — already numpy segment ops — runs here."""
    v = snapshot.columnar
    return _aggregate_services(
        snapshot, v.pod_names, v.pod_features, v.service_names,
        v.selectors, v.pod_service, v.memb_pod, v.memb_svc,
        v.node_names, v.pod_node,
    )


def _aggregate_services(
    snapshot: ClusterSnapshot,
    pod_names: List[str],
    pod_features: np.ndarray,
    service_names: List[str],
    selectors: List[dict],
    pod_service: np.ndarray,
    memb_pod_arr: np.ndarray,
    memb_svc_arr: np.ndarray,
    node_names: List[str],
    pod_node: np.ndarray,
) -> FeatureSet:

    # -- service-level aggregation (numpy segment ops over memberships) ----
    S = len(service_names)
    svc = np.zeros((S, NUM_SERVICE_FEATURES), dtype=np.float32)
    seg = memb_svc_arr
    pf = pod_features[memb_pod_arr]
    pods_per_svc = np.zeros(S, dtype=np.float32)
    np.add.at(pods_per_svc, seg, 1.0)
    denom = np.maximum(pods_per_svc, 1.0)

    def frac(channel: int) -> np.ndarray:
        acc = np.zeros(S, dtype=np.float32)
        np.add.at(acc, seg, pf[:, channel])
        return acc / denom

    def seg_max(channel: int) -> np.ndarray:
        acc = np.zeros(S, dtype=np.float32)
        # NaN from poisoned telemetry propagates into the service row by
        # design (the engine's finite-mask pass zeroes the whole row on
        # device); suppress numpy's warning — this is the intended path
        with np.errstate(invalid="ignore"):
            np.maximum.at(acc, seg, pf[:, channel])
        return acc

    crashy = np.clip(
        pf[:, PodF.WAIT_CRASHLOOP] + pf[:, PodF.PHASE_FAILED] + pf[:, PodF.TERM_NONZERO],
        0.0, 1.0,
    )
    acc = np.zeros(S, dtype=np.float32)
    np.add.at(acc, seg, crashy)
    svc[:, SvcF.CRASH] = acc / denom
    svc[:, SvcF.RESTARTS] = seg_max(PodF.RESTARTS_SAT)
    svc[:, SvcF.EVENTS] = seg_max(PodF.WARN_EVENTS_SAT)
    log_total = pf[:, PodF.LOG0 : PodF.LOG0 + len(LOG_PATTERN_NAMES)].sum(axis=1)
    acc = np.zeros(S, dtype=np.float32)
    np.add.at(acc, seg, log_total)
    svc[:, SvcF.LOG_ERRORS] = np.minimum(1.0, acc / 5.0)
    svc[:, SvcF.NOT_READY] = frac(PodF.NOT_READY)
    svc[:, SvcF.RESOURCE] = np.minimum(
        1.0, np.maximum(seg_max(PodF.CPU_PCT), seg_max(PodF.MEM_PCT))
    )
    svc[:, SvcF.IMAGE] = frac(PodF.WAIT_IMAGEPULL)
    svc[:, SvcF.CONFIG] = frac(PodF.WAIT_CONFIG)
    svc[:, SvcF.PENDING] = frac(PodF.PHASE_PENDING)
    svc[:, SvcF.OOM] = seg_max(PodF.TERM_OOM)

    # -- endpoints: a selector-bearing service with no ready addresses ------
    ep_by_name = {
        e.get("metadata", {}).get("name", ""): e for e in snapshot.endpoints
    }
    for j, name in enumerate(service_names):
        if not selectors[j]:
            continue
        ep = ep_by_name.get(name)
        if ep is not None:
            has_addr = any(
                (sub.get("addresses") or []) for sub in ep.get("subsets", []) or []
            )
            if not has_addr:
                svc[j, SvcF.NOT_READY] = 1.0

    # -- derived absence evidence (after endpoints finalize NOT_READY) -----
    derive_silent_channel(svc)

    # -- traces: error rates + latency degradation -------------------------
    traces = snapshot.traces or {}
    err = traces.get("error_rates") or {}
    for j, name in enumerate(service_names):
        if name in err:
            svc[j, SvcF.ERROR_RATE] = float(err[name])
    lat = traces.get("latency") or {}
    p99s = {
        name: float((lat.get(name) or {}).get("p99", 0.0)) for name in service_names
    }
    nonzero = [v for v in p99s.values() if v > 0]
    if nonzero:
        baseline = float(np.median(nonzero))
        if baseline > 0:
            for j, name in enumerate(service_names):
                v = p99s.get(name, 0.0)
                if v > 0:
                    svc[j, SvcF.LATENCY] = float(
                        np.clip((v / baseline - 1.0) / 4.0, 0.0, 1.0)
                    )

    # -- node features -----------------------------------------------------
    node_feat = np.zeros((len(node_names), 2), dtype=np.float32)
    nm = snapshot.node_metrics or {}
    for i, name in enumerate(node_names):
        rec = nm.get(name) or {}
        node_feat[i, 0] = float((rec.get("cpu") or {}).get("usage_percentage", 0.0)) / 100.0
        node_feat[i, 1] = float((rec.get("memory") or {}).get("usage_percentage", 0.0)) / 100.0

    return FeatureSet(
        namespace=snapshot.namespace,
        pod_names=pod_names,
        pod_features=pod_features,
        service_names=service_names,
        service_features=svc,
        pod_service=pod_service,
        memb_pod=memb_pod_arr,
        memb_svc=memb_svc_arr,
        node_names=node_names,
        pod_node=pod_node,
        node_features=node_feat,
    )
