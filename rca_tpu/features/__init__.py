"""Vectorized feature extraction: snapshot → padded device-ready arrays.

Replaces the reference's per-pod Python dict crunching (reference:
agents/resource_analyzer.py:275-351 pod bucketing, agents/metrics_agent.py
threshold loops, agents/logs_agent.py per-container regex scans, and the
chat-path hot loop at agents/mcp_coordinator.py:1205-1241) with one pass
that packs every signal into numpy arrays ready for ``jnp`` transfer.
"""

from rca_tpu.features.logscan import (
    LOG_PATTERNS,
    LOG_PATTERN_NAMES,
    pattern_recommendation,
    pattern_severity,
    scan_text,
)
from rca_tpu.features.schema import PodF, SvcF, POD_FEATURE_NAMES, SERVICE_FEATURE_NAMES
from rca_tpu.features.extract import FeatureSet, extract_features

__all__ = [
    "LOG_PATTERNS",
    "LOG_PATTERN_NAMES",
    "pattern_recommendation",
    "pattern_severity",
    "scan_text",
    "PodF",
    "SvcF",
    "POD_FEATURE_NAMES",
    "SERVICE_FEATURE_NAMES",
    "FeatureSet",
    "extract_features",
]
