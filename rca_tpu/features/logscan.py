"""Log-pattern scanner: 13 named error classes.

Same taxonomy as the reference's log agent (reference: agents/logs_agent.py
:20-34 pattern table, :416-437 severity map, :451-477 recommendation table)
with independently-written patterns.  Patterns are compiled once; scanning
returns a count vector aligned with :data:`LOG_PATTERN_NAMES`, which the
feature extractor packs straight into the device array.
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List

import numpy as np

# class name -> compiled pattern (verbose, case-handled per class)
LOG_PATTERNS: Dict[str, re.Pattern] = {
    "oom_kill": re.compile(
        r"out of memory|oomkilled|signal:\s*killed|oom[-_]?kill", re.I
    ),
    "connection_refused": re.compile(r"connection refused|ECONNREFUSED", re.I),
    "permission_denied": re.compile(r"permission denied|access denied|\bforbidden\b", re.I),
    "timeout": re.compile(r"timed?\s?-?out|ETIMEDOUT|deadline exceeded", re.I),
    "crash_loop": re.compile(r"crashloopbackoff|back-?off restarting", re.I),
    "api_error": re.compile(r"api server error|StatusCode=5\d\d"),
    "volume_mount": re.compile(r"unable to (?:attach or )?mount volumes|MountVolume\.\w+ failed", re.I),
    "image_pull": re.compile(r"ErrImagePull|ImagePullBackOff|failed to pull image", re.I),
    "dns_resolution": re.compile(r"could not resolve|DNS resolution failed|no such host", re.I),
    "authentication": re.compile(r"unauthorized|authentication fail", re.I),
    "config_error": re.compile(r"invalid configuration|configmap .*not found|secret .*not found", re.I),
    "internal_server_error": re.compile(r"internal ?server ?error|500 Internal", re.I),
    "exception": re.compile(r"\bexception\b|\berror\b|traceback|\bFATAL\b|\bCRITICAL\b|panic:?", re.I),
}

LOG_PATTERN_NAMES: List[str] = list(LOG_PATTERNS.keys())

_SEVERITY = {
    **{k: "high" for k in ("oom_kill", "crash_loop", "image_pull")},
    **{k: "medium" for k in ("connection_refused", "timeout", "volume_mount",
                             "dns_resolution", "internal_server_error")},
    **{k: "low" for k in ("permission_denied", "authentication", "config_error")},
}

_RECOMMENDATIONS = {
    "oom_kill": "Raise the container memory limit or reduce the application's memory footprint",
    "connection_refused": "Verify the target service is running, its endpoints are populated, and no network policy blocks it",
    "permission_denied": "Review RBAC bindings, the pod's service account, and security contexts",
    "timeout": "Look for network degradation, raise timeout budgets, or speed up the slow dependency",
    "crash_loop": "Read the container's previous logs to find the crash cause and fix the application",
    "api_error": "Inspect Kubernetes API-server health and the client's configuration",
    "volume_mount": "Check PVC binding status, the storage class, and volume permissions",
    "image_pull": "Confirm the image tag exists, pull credentials are valid, and the registry is reachable",
    "dns_resolution": "Check cluster DNS (CoreDNS) health and any network policies blocking port 53",
    "authentication": "Verify credentials, token expiry, and auth configuration",
    "config_error": "Ensure every referenced ConfigMap and Secret exists with the expected keys",
    "internal_server_error": "Investigate the upstream service returning 5xx responses",
    "exception": "Debug the application stack trace to resolve the underlying exception",
}


def pattern_severity(name: str) -> str:
    return _SEVERITY.get(name, "info")


def pattern_recommendation(name: str) -> str:
    return _RECOMMENDATIONS.get(
        name, "Inspect the surrounding log context to identify the root cause"
    )


def scan_text_python(text: str) -> np.ndarray:
    """Pure-Python reference scanner (the parity oracle for the C++ path)."""
    counts = np.zeros(len(LOG_PATTERN_NAMES), dtype=np.int32)
    if not text:
        return counts
    for i, name in enumerate(LOG_PATTERN_NAMES):
        counts[i] = len(LOG_PATTERNS[name].findall(text))
    return counts


def scan_text(text: str) -> np.ndarray:
    """Count matches of every pattern class in one log text → int32 [13].

    Uses the native C++ scanner (rca_tpu.native) when a toolchain is
    available — ~10x faster on the host-side hot path — falling back to the
    Python regex oracle (identical counts, enforced by tests/test_native.py).
    """
    if not text:
        return np.zeros(len(LOG_PATTERN_NAMES), dtype=np.int32)
    from rca_tpu.native import scan_text_native

    counts = scan_text_native(text)
    if counts is not None:
        return counts
    return scan_text_python(text)


@functools.lru_cache(maxsize=4096)
def _scan_text_cached(text: str) -> bytes:
    """Memoized scan keyed by log content (ISSUE 10: the columnar row
    encoder re-derives a pod's counts on every journaled log touch, and
    unchanged tails — the common case under pod-status churn — would
    otherwise re-run all 13 regexes).  Returns immutable bytes so cached
    entries cannot be mutated through a returned array."""
    return scan_text(text).tobytes()


def scan_text_cached(text: str) -> np.ndarray:
    """Content-memoized :func:`scan_text` (same counts, enforced by the
    parity tests); the cache is process-wide and bounded."""
    return np.frombuffer(
        _scan_text_cached(text), dtype=np.int32
    ).copy()


def scan_pod_logs(logs_by_container: Dict[str, str]) -> np.ndarray:
    """Sum pattern counts across a pod's containers → int32 [13]."""
    counts = np.zeros(len(LOG_PATTERN_NAMES), dtype=np.int32)
    for text in logs_by_container.values():
        counts += scan_text(text)
    return counts
