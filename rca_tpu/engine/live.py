"""Live streaming: cluster client → feature deltas → fused device tick.

Closes the loop BASELINE.md row 4 implies (10k services, 1 Hz metric
ticks): :class:`StreamingSession` keeps the feature matrix device-resident
and re-ranks in one fused dispatch, but expects the caller to hand it row
updates.  :class:`LiveStreamingSession` is that caller — it polls a
``ClusterClient``, re-extracts the vectorized features (host-side numpy,
~0.4 s at 10k services), diffs against the previous matrix, and uploads
ONLY the changed rows.  The reference has no streaming mode at all; its
closest analog is re-running a full analysis per chat turn (reference:
agents/mcp_coordinator.py:624-665 re-fetches everything serially).

Topology changes (services added/removed, dependency edges changed) force
a session rebuild — edges are device-pinned for the session, so a changed
graph is a new session, counted in ``resyncs``.

Host-side envelope at 10k services (measured, PERF.md methodology):
snapshot+sanitize ~0.7 s, feature extraction ~0.4 s, dependency-edge
rebuild ~0.9 s.  The device tick itself is ~10 ms — so the edge rebuild
only runs every ``topology_check_every`` polls, keeping the steady-state
poll ~1.1 s; a production deployment at this scale would drive deltas
from K8s watches rather than full list sweeps, which this class treats as
an interchangeable capture step.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.engine.streaming import StreamingSession
from rca_tpu.features.extract import extract_features
from rca_tpu.graph.build import service_dependency_edges


class LiveStreamingSession:
    """Poll-driven streaming analysis over a live (or mock) cluster."""

    def __init__(
        self,
        client,
        namespace: str,
        k: int = 5,
        engine: Optional[GraphEngine] = None,
        topology_check_every: int = 5,
    ):
        """``topology_check_every``: rebuild+compare the dependency edges on
        every Nth poll rather than all of them — the edge build is the most
        expensive host step (~0.9 s at 10k services) while topology changes
        are rare.  A service-set change (cheap to detect) still triggers an
        immediate resync on any poll; an edge-only change (same services,
        new dependency) is picked up within N polls.  Set 1 to check every
        poll."""
        self.client = client
        self.namespace = namespace
        self.k = k
        # single-device by design: see StreamingSession.__init__ — the
        # donated-buffer delta-scatter session has no sharded twin yet
        self.engine = engine or GraphEngine()
        self.topology_check_every = max(1, int(topology_check_every))
        self._polls = 0
        self.resyncs = -1  # first _resync is initialization, not a resync
        self._resync()

    # -- topology (re)build -------------------------------------------------
    def _resync(self, snap=None, fs=None, edges=None) -> None:
        """Rebuild from an ALREADY-captured snapshot when the caller has
        one (poll() detected the change on it) — re-capturing here would
        sweep the cluster twice per resync tick and rebuild from different
        state than the change-detection examined."""
        if snap is None:
            snap = ClusterSnapshot.capture(self.client, self.namespace)
        if fs is None:
            fs = extract_features(snap)
        src, dst = edges if edges is not None else service_dependency_edges(
            snap, fs
        )
        self._names = list(fs.service_names)
        self._edge_key = (src.tobytes(), dst.tobytes())
        self._features = np.array(fs.service_features, np.float32)
        self.session = StreamingSession(
            self._names, src, dst,
            num_features=self._features.shape[1],
            engine=self.engine, k=self.k,
        )
        self.session.set_all(self._features)
        self.resyncs += 1

    # -- one poll+tick ------------------------------------------------------
    def poll(self) -> Dict[str, Any]:
        """Capture → diff → delta upload → fused tick.

        Returns the tick result plus ``changed_rows`` (real changed services
        before padding), ``resynced`` (topology changed → full rebuild this
        poll), and ``capture_ms`` (host-side snapshot+extract time)."""
        t0 = time.perf_counter()
        self._polls += 1
        snap = ClusterSnapshot.capture(self.client, self.namespace)
        fs = extract_features(snap)
        resynced = False
        edges = None
        if list(fs.service_names) != self._names:
            resynced = True
        elif self._polls % self.topology_check_every == 0:
            edges = service_dependency_edges(snap, fs)
            if (edges[0].tobytes(), edges[1].tobytes()) != self._edge_key:
                resynced = True
        if resynced:
            self._resync(snap=snap, fs=fs, edges=edges)
            capture_ms = (time.perf_counter() - t0) * 1e3
            out = self.session.tick()
            out.update(
                changed_rows=len(self._names), resynced=True,
                capture_ms=round(capture_ms, 2), resyncs=self.resyncs,
                # session-lifetime counter: the inner StreamingSession is
                # replaced on resync, so its "tick" restarts at 1 and the
                # CLI/UI sequence would go non-monotonic
                tick=self._polls,
            )
            return out

        new = np.asarray(fs.service_features, np.float32)
        changed = np.flatnonzero(np.any(new != self._features, axis=1))
        if len(changed):
            self.session.update_many(
                {int(i): new[i] for i in changed}
            )
            self._features[changed] = new[changed]
        capture_ms = (time.perf_counter() - t0) * 1e3
        out = self.session.tick()
        out.update(
            changed_rows=int(len(changed)), resynced=False,
            capture_ms=round(capture_ms, 2), resyncs=self.resyncs,
            tick=self._polls,
        )
        return out
