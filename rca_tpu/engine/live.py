"""Live streaming: cluster changes → feature deltas → fused device tick.

Closes the loop BASELINE.md row 4 implies (10k services, 1 Hz metric
ticks): :class:`StreamingSession` keeps the feature matrix device-resident
and re-ranks in one fused dispatch; :class:`LiveStreamingSession` feeds it
from a ``ClusterClient``.  The reference has no streaming mode at all; its
closest analog is re-running a full analysis per chat turn (reference:
agents/mcp_coordinator.py:624-665 re-fetches everything serially).

Two capture strategies, auto-selected (VERDICT r2 item 6):

- **watch-driven** (default when the client supports ``watch_changes``):
  polls drain an incremental change feed — the mock's ``World`` mutation
  journal, or kubernetes watch pumps on a live cluster.  A QUIET poll
  (no changes) costs one feed drain + one device tick: no list sweep, no
  feature extraction — the 10k-service quiet poll drops from ~1.1 s to
  single-digit ms (bench: ``live_quiet_capture_ms_10k``).  A busy poll
  re-fetches only the changed objects and patches the previous snapshot;
  a change to a topology-shaping kind (services, deployments, config...)
  or a feed expiry (410 Gone, journal trim, pump death) forces a full
  resync — correctness never depends on the feed's completeness.
- **sweep** (fallback when the feed is unsupported, e.g. kubectl-only
  clients): every poll re-lists the namespace, re-extracts features
  host-side, and diffs against the previous matrix, uploading only the
  changed rows.

Round 6 (ISSUE 2) threads the tick PIPELINE through ``poll()``: with
``pipeline_depth >= 2`` (``RCA_PIPELINE_DEPTH``), each poll dispatches
this capture's fused tick and fetches the one issued depth-1 polls ago,
so the ~90–110 ms tunneled-device round trip and the host capture hide
behind each other instead of summing.  Rankings are exactly the serial
sequence delivered depth-1 polls late (parity-tested); depth 1 is the
bit-identical serial default.  Busy-poll captures also stop re-deriving
unchanged feature rows: :class:`rca_tpu.features.extract.
IncrementalExtractor` memoizes rows by object resourceVersion and log
scans/selector matches by content.

Either way, topology changes (services added/removed, dependency edges
changed) force a session rebuild — edges are device-pinned for the
session, so a changed graph is a new session, counted in ``resyncs``.
Trace-derived dependency drift is invisible to both the journal and the
watch, so every ``topology_check_every``-th poll still does one full
sweep + edge compare (the steady-state cost stays amortized).

One sampling caveat: snapshot capture bounds HEALTHY-pod log fetches
(``_prioritize_pods_for_logs``, 25 by default), and which healthy pods
fall inside the cap shifts as other pods change state.  A watch session
keeps its original sample until something journals those pods (their
logs then refetch) or a resync runs — so above the cap, a session's
log-derived channels for quiet healthy pods can lag a fresh capture's.
Below the cap the patched session is bit-identical to a fresh one
(property-tested in tests/test_watch.py).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.config import (
    columnar_enabled,
    compile_cache_status,
    enable_compile_cache,
    pipeline_depth_from_env,
)
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.engine.streaming import StreamingSession, make_streaming_session
from rca_tpu.features.extract import IncrementalExtractor
from rca_tpu.graph.build import service_dependency_edges
from rca_tpu.resilience.policy import (
    drain_faults,
    record_fault,
    retry_counter,
    suppressed,
)

# degradation ladder rungs (engine guards): repeated device-dispatch
# failure walks the session down one rung at a time instead of crashing
# poll() — see RESILIENCE.md
DEGRADATION_LADDER = ("full", "single-device", "interpret")
# consecutive tick failures before stepping down a rung
_TICK_FAILURES_TO_DEGRADE = 2

# change kinds that shape the dependency graph: cheaper to rebuild the
# session than to prove a patch preserves the edges
_TOPOLOGY_KINDS = frozenset({
    "service", "deployment", "statefulset", "daemonset", "cronjob",
    "endpoints", "ingress", "networkpolicy", "configmap", "secret",
    "pvc", "resourcequota", "hpa", "node",
})


class LiveStreamingSession:
    """Change-driven streaming analysis over a live (or mock) cluster."""

    def __init__(
        self,
        client,
        namespace: str,
        k: int = 5,
        engine: Optional[GraphEngine] = None,
        topology_check_every: int = 5,
        use_watch: bool = True,
        pipeline_depth: Optional[int] = None,
        recorder=None,
        clock=None,
        use_columnar: Optional[bool] = None,
        tracer=None,
        explain: Optional[bool] = None,
    ):
        """``topology_check_every``: do a full sweep + dependency-edge
        compare on every Nth poll — the edge build is the most expensive
        host step (~0.9 s at 10k services) and trace-derived edges drift
        invisibly to the change feed.  ``use_watch=False`` forces the
        sweep strategy even when the client has a change feed (the bench
        uses this to measure the sweep baseline).

        ``pipeline_depth`` (default ``RCA_PIPELINE_DEPTH``, else 1): 1 runs
        each poll serially (capture → dispatch → fetch, the pre-round-6
        behavior, bit-identical); N >= 2 keeps N-1 ticks in flight — each
        poll dispatches this capture's tick and fetches the one issued N-1
        polls ago, so the device round trip hides behind the NEXT poll's
        host capture.  Rankings are identical to serial, delivered N-1
        polls late (the first N-1 polls are pipeline-fill ticks carrying
        the last known ranking); the lag is surfaced in every tick's
        health record.

        ``recorder`` (a :class:`rca_tpu.replay.recorder.Recorder`) makes
        this session a FLIGHT-RECORDED one: the client is wrapped so
        every call it answers (bootstrap capture included) lands in the
        log, and each poll seals a tick frame with the delivered ranking
        — ``rca replay`` re-drives the real engine from that log and
        asserts bit-identity (REPLAY.md).  ``clock`` is the injectable
        monotonic timer (default ``time.perf_counter``) so latency
        accounting never reads the wall directly (nondet-discipline)."""
        self.namespace = namespace
        self.k = k
        self._clock = clock or time.perf_counter
        # tracing (ISSUE 11): one trace per session, one parentless root
        # span per tick with capture/dispatch/fetch children — recorded
        # into the tracer's ring buffer AND embedded in each tick's
        # health record, which is how recordings carry the timeline
        # (`rca replay --trace-out` rebuilds it from the tape)
        from rca_tpu.observability.spans import default_tracer

        self.tracer = tracer if tracer is not None else default_tracer()
        self._trace_ctx = self.tracer.new_context()
        # kernelscope watchdogs (ISSUE 12): the recompile monitor runs
        # for the session's whole life (a post-warmup compilation of an
        # already-compiled signature on the tick path is a regression
        # tracecheck's 2-call probe cannot see), and the device-memory
        # accountant samples every RCA_MEM_SAMPLE_EVERY-th tick into the
        # health record.  RCA_KERNELSCOPE=0 turns both into no-ops.
        from rca_tpu.observability.kernelscope import (
            DeviceMemoryAccountant,
            RecompileMonitor,
        )

        self.recompile_monitor = RecompileMonitor().start()
        self.memory_accountant = DeviceMemoryAccountant()
        self._warm_marked = False
        # tick pipeline (ISSUE 2 tentpole): in-flight handles, oldest first
        self.pipeline_depth = (
            pipeline_depth_from_env() if pipeline_depth is None
            else max(1, int(pipeline_depth))
        )
        # flight recorder (ISSUE 5): wrap BEFORE the bootstrap capture so
        # the recording replays the session from construction, not from
        # some mid-life tick
        self.recorder = recorder
        # causelens (ISSUE 14): per-tick attribution of the delivered
        # ranking (RCA_EXPLAIN, or the explicit param — replay pins the
        # recorded value).  Each explained tick computes the provenance
        # block from the session's host mirror and stamps its DIGEST
        # into the tick output — recordings carry it, so `rca replay
        # --explain` parity-checks attributions against the tape.
        from rca_tpu.config import explain_enabled

        self._explain = (
            explain_enabled() if explain is None else bool(explain)
        )
        if recorder is not None:
            recorder.begin_session({
                "namespace": namespace, "k": int(k),
                "topology_check_every": int(max(1, topology_check_every)),
                "use_watch": bool(use_watch),
                "pipeline_depth": self.pipeline_depth,
                "use_columnar": (
                    columnar_enabled() if use_columnar is None
                    else bool(use_columnar)
                ),
                "explain": self._explain,
            })
            client = recorder.wrap_client(client)
        self.client = client
        self._inflight: "collections.deque" = collections.deque()
        self.pipeline_flushed = 0  # in-flight ticks dropped by degradation
        # incremental capture cache (busy polls re-derive only changed
        # rows; full sweeps refresh the cache — see features/extract.py)
        self._extractor = IncrementalExtractor()
        # columnar capture (ISSUE 10): when the client serves the
        # columnar feed, EVERY capture — sweep, resync, busy poll — goes
        # through the table mirror (row writes + vectorized assembly)
        # instead of the per-object dict scans; quiet polls still skip
        # capture entirely.  The state carries the mirror + cursor + log
        # text cache across polls so recordings log column DIFFS.
        self._use_columnar = (
            columnar_enabled() if use_columnar is None else bool(use_columnar)
        )
        self._colstate = None
        # persistent-compile-cache status for the health record: entries
        # counted at session start; the first post-tick health record adds
        # how many NEW entries this session compiled (0 = warm start)
        self._compile_cache = enable_compile_cache()
        # engine selection follows the analyze boundary (RCA_SHARD +
        # visible devices): a sharded engine gets the sharded streaming
        # session with its sp-sharded resident buffer (VERDICT r3 item 3)
        if engine is None:
            from rca_tpu.engine.sharded_runner import make_engine

            engine = make_engine()
        self.engine = engine
        if recorder is not None:
            # forensics only: replay may run ANY engine kind (the engines
            # are parity-locked), so the tag informs, never constrains
            recorder.begin_session({"engine": type(engine).__name__})
        self.topology_check_every = max(1, int(topology_check_every))
        self._polls = 0
        self.resyncs = -1  # first _resync is initialization, not a resync
        # resync cause split (chaos runs assert on WHY a session resynced):
        # feed expiry / lost-notification recovery vs. a real topology move
        self.resyncs_expired = 0
        self.resyncs_topology = 0
        # degradation ladder position (index into DEGRADATION_LADDER) and
        # the consecutive-tick-failure count that advances it
        self.degradation = 0
        self._tick_failures = 0
        self._retries_mark = retry_counter()
        self._last_ranked: List[dict] = []
        self._cursor: Optional[str] = None
        # set when a poll drained the feed but then failed to apply the
        # changes (sweep raised, or the capture came back partial): the
        # notifications are gone from the feed, so the next poll must
        # recover them with a full resync instead of serving stale rows
        # until the next periodic sweep (round-3 advisor finding)
        self._pending_resync = False
        # set by expiry recovery: the lost notifications may have included
        # topology/trace kinds the cheap recovery cannot verify, so the
        # NEXT poll runs the full topology check instead of waiting up to
        # ``topology_check_every`` polls
        self._force_topology_check = False
        # optimistic: _resync's _reopen_feed does the one real probe —
        # probing here too would open a second feed (on a live cluster,
        # a second pair of watch-pump threads) just to throw it away
        self._watch = bool(use_watch)
        self._resync()

    # -- capture (columnar fast path, ISSUE 10) -----------------------------
    def _columnar_active(self) -> bool:
        return self._use_columnar and callable(
            getattr(self.client, "get_columnar", None)
        )

    def _capture_full(self, traces_from=None) -> ClusterSnapshot:
        """One namespace capture through the preferred path: the columnar
        tables when the client serves them, else the dict sweep.  A
        degenerate world (columnar unsupported) falls back permanently —
        capture() already returned the dict-path snapshot in that case."""
        if self._columnar_active():
            if self._colstate is None:
                from rca_tpu.cluster.columnar import ColumnarClientState

                self._colstate = ColumnarClientState()
            snap = ClusterSnapshot.capture(
                self.client, self.namespace,
                columnar_state=self._colstate, traces_from=traces_from,
            )
            if snap.columnar is None:
                self._use_columnar = False
                self._colstate = None
            return snap
        return ClusterSnapshot.capture(
            self.client, self.namespace, columnar=False,
        )

    # -- topology (re)build -------------------------------------------------
    def _resync(self, snap=None, fs=None, edges=None,
                cause: str = "topology") -> None:
        """Rebuild from an ALREADY-captured snapshot when the caller has
        one (poll() detected the change on it) — re-capturing here would
        sweep the cluster twice per resync tick and rebuild from different
        state than the change-detection examined.

        ``cause`` feeds the split resync counters: ``"expired"`` for
        feed-expiry / lost-notification recovery, ``"topology"`` for a
        real service-graph move — chaos soaks assert on the cause."""
        if snap is None:
            # reopen the change feed BEFORE listing: changes that land
            # during the capture get re-reported next poll (a harmless
            # re-patch) instead of being lost
            self._reopen_feed()
            snap = self._capture_full()
        if fs is None:
            # full-mode extraction: a resync is the recovery path for
            # "we may have missed something", so it must not trust the
            # row cache — it refreshes it instead
            fs = self._extractor.extract(snap, incremental=False)
        src, dst = edges if edges is not None else service_dependency_edges(
            snap, fs
        )
        if self._watch and snap.errors:
            # a resync built from a PARTIAL capture has not actually
            # recovered: whatever the failing calls missed is still stale,
            # so keep the recovery flag set and try again next poll (the
            # flake clearing ends the loop; while it persists this is the
            # same capture-every-poll cost as sweep mode, degraded but
            # correct) — round-4 review finding
            self._pending_resync = True
        self._snap = snap if self._watch else None
        self._names = list(fs.service_names)
        self._edge_key = (src.tobytes(), dst.tobytes())
        # raw edges retained so the degradation ladder can rebuild the
        # session on a downgraded engine without re-capturing
        self._edges_raw = (np.asarray(src), np.asarray(dst))
        self._features = np.array(fs.service_features, np.float32)
        self.session = make_streaming_session(
            self._names, src, dst,
            num_features=self._features.shape[1],
            engine=self.engine, k=self.k, clock=self._clock,
        )
        self.session.set_all(self._features)
        is_init = self.resyncs < 0
        self.resyncs += 1
        if not is_init:
            if cause == "expired":
                self.resyncs_expired += 1
            else:
                self.resyncs_topology += 1
        self._last_resync_cause = None if is_init else cause

    def _reopen_feed(self) -> None:
        if self._watch:
            # release the superseded cursor first: an abandoned consumer
            # token would pin the shared journal's trim floor at its frozen
            # position, holding the window at its cap forever (round-4
            # review finding).  Optional surface — the mock's seq cursors
            # don't pin anything and define no watch_close.
            if self._cursor is not None:
                close = getattr(self.client, "watch_close", None)
                if close is not None:
                    with suppressed("live.watch_close"):
                        close(self.namespace, self._cursor)
            try:
                probe = self.client.watch_changes(self.namespace, None)
            except (AttributeError, TypeError):
                probe = {"supported": False}
            self._watch = bool(probe.get("supported"))
            self._cursor = probe.get("cursor")

    def _refetch_pod_logs(self, pod: dict, name: str) -> Dict[str, str]:
        """Per-container tail refetch — the ONE log-fetch policy shared by
        the busy-poll patch path and expiry recovery."""
        per_container: Dict[str, str] = {}
        for c in pod.get("spec", {}).get("containers", []) or []:
            try:
                per_container[c["name"]] = self.client.get_pod_logs(
                    self.namespace, name, container=c["name"],
                    tail_lines=200,
                )
            except Exception:
                per_container[c["name"]] = ""
        return per_container

    # -- expiry recovery ----------------------------------------------------
    def _recover_from_expiry(self, t0: float) -> Dict[str, Any]:
        """Graceful feed-expiry recovery (VERDICT r3 item 6): re-list the
        pods ONCE, value-diff against the retained snapshot, refetch logs
        only for pods that actually changed, and refresh the one-call
        event/metric/trace payloads.  Recovery cost scales with drift, not
        graph size — the previous behavior was a full resync (~726 ms
        capture at 10k, BENCH_r03) for what is usually a handful of stale
        rows.

        The lost notifications may also have included topology or
        trace-dependency kinds, which this cheap path cannot verify (the
        edge rebuild is the most expensive host step) — so recovery FORCES
        the full topology check on the very next poll instead of waiting
        out ``topology_check_every``: the stale-edge window is bounded at
        one tick regardless of the cadence setting."""
        from rca_tpu.cluster.sanitize import sanitize_objects

        snap = self._snap
        self._reopen_feed()
        if not self._watch:
            # feed gone for good (client reconnected without support):
            # fall back to the sweep strategy from here on
            return self._poll_sweep()
        can_check_errors = hasattr(self.client, "collect_errors")
        if can_check_errors:
            self.client.collect_errors()  # drain stale errors
        new_pods = sanitize_objects(self.client.get_pods(self.namespace))
        old_by_name = {
            p.get("metadata", {}).get("name"): p for p in snap.pods
        }
        new_by_name = {
            p.get("metadata", {}).get("name"): p for p in new_pods
        }
        changed = [
            n for n, p in new_by_name.items() if old_by_name.get(n) != p
        ]
        removed = [n for n in old_by_name if n not in new_by_name]
        logs = dict(snap.logs)
        for n in removed:
            logs.pop(n, None)
        for n in changed:
            logs[n] = self._refetch_pod_logs(new_by_name[n], n)
        try:
            traces = {
                "latency": self.client.get_service_latency_stats(
                    self.namespace),
                "error_rates": self.client.get_error_rate_by_service(
                    self.namespace),
                "dependencies": self.client.get_service_dependencies(
                    self.namespace),
                "slow_ops": self.client.find_slow_operations(self.namespace),
            }
        except Exception:
            traces = snap.traces
        events = sanitize_objects(self.client.get_events(self.namespace))
        metrics = self.client.get_pod_metrics(self.namespace) or {}
        if can_check_errors and self.client.collect_errors():
            # a fetch failed and was swallowed into the degraded channel:
            # an empty pod list here means API flake, NOT mass deletion —
            # interpreting it would wipe the ranking (every other path
            # guards this via snap.errors / collect_errors; round-4 review
            # finding).  Keep the retained state and retry with a full
            # resync next poll.
            self._pending_resync = True
            out = self._finish(t0, changed=0, resynced=False, quiet=False)
            out["recovered"] = False
            return out
        snap2 = dataclasses.replace(
            snap,
            captured_at=self.client.get_current_time(),
            pods=new_pods,
            logs=logs,
            events=events,
            pod_metrics=metrics,
            traces=traces,
            # this recovery's own (clean) fetch status, not the previous
            # capture's stale error list
            errors=[],
            # a columnar view describes exactly the capture that built
            # it; this grafted snapshot must extract through the dict path
            columnar=None,
        )
        self._force_topology_check = True
        # full-mode extraction: the notifications were LOST, so the drift
        # this recovery grafted in is exactly the un-journaled kind the
        # rv-keyed row cache cannot see (the log-scan and selector memos,
        # content-keyed, still apply — and get refreshed)
        fs = self._extractor.extract(snap2, incremental=False)
        if list(fs.service_names) != self._names:
            # the service set itself moved while we were blind: full rebuild
            self._resync(snap=snap2, fs=fs, cause="expired")
            return self._finish(
                t0, changed=len(self._names), resynced=True, quiet=False,
            )
        self._snap = snap2
        n_changed = self._upload_diff(fs)
        out = self._finish(t0, changed=n_changed, resynced=False, quiet=False)
        out["recovered"] = True
        out["drift_pods"] = len(changed) + len(removed)
        return out

    # -- snapshot patching --------------------------------------------------
    def _patch_snapshot(self, changes: List[Dict[str, str]]) -> ClusterSnapshot:
        """Re-fetch ONLY what changed and graft it onto the previous
        snapshot: changed pods (object + logs) by name, the event list and
        pod metrics wholesale when touched (each is one call).  Topology
        kinds never reach here (poll() resyncs on them)."""
        from rca_tpu.cluster.sanitize import sanitize_objects

        snap = self._snap
        pod_names = {c["name"] for c in changes if c["kind"] == "pod"}
        log_names = {c["name"] for c in changes if c["kind"] == "logs"}
        events_touched = any(c["kind"] == "event" for c in changes)
        metrics_touched = any(c["kind"] == "pod_metrics" for c in changes)
        traces_touched = any(c["kind"] == "traces" for c in changes)

        patch: Dict[str, Any] = {
            "captured_at": self.client.get_current_time(),
            # patched snapshots extract through the dict path (a columnar
            # view is only valid for the capture that assembled it)
            "columnar": None,
        }
        can_check_errors = hasattr(self.client, "collect_errors")
        if traces_touched:
            # error-rate/latency channels come straight from trace data —
            # a journaled trace update re-pulls the four payloads (each is
            # one call); UN-journaled trace drift is covered by the
            # periodic sweep like edge drift
            with suppressed("live.patch_traces"):
                patch["traces"] = {
                    "latency": self.client.get_service_latency_stats(
                        self.namespace),
                    "error_rates": self.client.get_error_rate_by_service(
                        self.namespace),
                    "dependencies": self.client.get_service_dependencies(
                        self.namespace),
                    "slow_ops": self.client.find_slow_operations(
                        self.namespace),
                }
        if pod_names:
            by_name_old = {
                p.get("metadata", {}).get("name"): p for p in snap.pods
            }
            kept = [
                p for p in snap.pods
                if p.get("metadata", {}).get("name") not in pod_names
            ]
            refetched = []
            for name in sorted(pod_names):
                if can_check_errors:
                    self.client.collect_errors()  # drain stale errors
                pod = self.client.get_pod(self.namespace, name)
                if pod is not None:
                    refetched.append(pod)
                elif can_check_errors and self.client.collect_errors():
                    # None + a recorded fetch error = transient failure,
                    # NOT deletion — keep the stale object rather than
                    # fabricating a pod removal the cluster never saw
                    # (round-3 review finding); the next change or sweep
                    # refreshes it
                    old = by_name_old.get(name)
                    if old is not None:
                        refetched.append(old)
            patch["pods"] = kept + sanitize_objects(refetched)
        if pod_names or log_names:
            logs = dict(snap.logs)
            by_name = {
                p.get("metadata", {}).get("name"): p
                for p in patch.get("pods", snap.pods)
            }
            for name in sorted(pod_names | log_names):
                pod = by_name.get(name)
                if pod is None:
                    logs.pop(name, None)
                    continue
                logs[name] = self._refetch_pod_logs(pod, name)
            patch["logs"] = logs
        if events_touched or pod_names:
            patch["events"] = sanitize_objects(
                self.client.get_events(self.namespace)
            )
        if metrics_touched or pod_names:
            patch["pod_metrics"] = (
                self.client.get_pod_metrics(self.namespace) or {}
            )
        return dataclasses.replace(snap, **patch)

    # -- one poll+tick ------------------------------------------------------
    def poll(self) -> Dict[str, Any]:
        """Drain changes (or sweep) → diff → delta upload → fused tick.

        Returns the tick result plus ``changed_rows`` (real changed
        services before padding), ``resynced`` (topology changed → full
        rebuild this poll), ``capture_ms`` (host-side capture/patch time),
        ``quiet`` (watch path, no changes: no capture ran at all),
        ``degraded`` + ``health`` (the resilience contract, below).

        Tick-loop contract (RESILIENCE.md): ``poll()`` NEVER raises on a
        fault — injected or real.  A failing capture/patch/tick returns
        the last known ranking with ``degraded: True`` and a per-tick
        health record (sanitized-row count, resync causes, retries spent,
        swallowed faults, injected chaos faults, ladder position); the
        next poll recovers with a full resync.  When no fault fires the
        output is bit-identical to the pre-resilience behavior (PARITY.md
        invariant)."""
        self._polls += 1
        t_poll0 = self._clock()
        if self.recorder is not None:
            self.recorder.begin_tick(self._polls)
        try:
            out = self._poll_inner()
            out["degraded"] = bool(out.pop("_tick_degraded", False))
        except Exception as exc:
            record_fault("live.poll", exc)
            # whatever the failing poll drained is gone from the feed —
            # recover it with a full resync next poll
            self._pending_resync = True
            out = {
                "ranked": list(self._last_ranked),
                "latency_ms": 0.0, "capture_ms": 0.0,
                "changed_rows": 0, "upload_rows": 0,
                "sanitized_rows": 0, "quiet": False, "resynced": False,
                "resyncs": self.resyncs, "tick": self._polls,
                "degraded": True,
            }
        self._last_ranked = list(out.get("ranked", []))
        if self._explain:
            self._explain_tick(out)
        if not self._warm_marked:
            # warmup ends after the first completed poll: the steady
            # state is what the zero-post-warmup-recompiles gate covers
            self.recompile_monitor.mark_warm()
            self._warm_marked = True
        out["health"] = self._health_record(out)
        self._trace_tick(out, t_poll0)
        if self.recorder is not None:
            self.recorder.end_tick(out, features=self._features)
        return out

    def _explain_tick(self, out: Dict[str, Any]) -> None:
        """Attribute this poll's DELIVERED ranking against the session's
        current host mirror (causelens, ISSUE 14).  Degraded ticks are
        attributed too — the last-known ranking over the retained state
        is exactly the answer the operator is looking at.  A failing
        attribution records a fault and stamps the error; it never takes
        down poll()."""
        try:
            from rca_tpu.engine.attribution import compute_attribution
            from rca_tpu.engine.runner import make_attribution_ctx
            from rca_tpu.observability.causelens import attribution_digest

            src, dst = self._edges_raw
            ctx = make_attribution_ctx(
                self._features, src, dst, self.engine.params, self._names,
                getattr(self.engine, "config", None).shape_buckets
                if getattr(self.engine, "config", None) is not None
                else None,
            )
            block = compute_attribution(ctx, out.get("ranked") or [])
            out["attribution"] = block
            out["attribution_digest"] = attribution_digest(block)
        except Exception as exc:  # noqa: BLE001 - explain never kills a tick
            record_fault("live.explain", exc)
            out["attribution_digest"] = None
            out["attribution_error"] = f"{type(exc).__name__}: {exc}"

    def _trace_tick(self, out: Dict[str, Any], t0: float) -> None:
        """Record this poll's spans and embed them in the health record.
        The phase children are laid end to end from the measured
        capture/dispatch/fetch durations — the same numbers PhaseStats
        aggregates, now attributable to ONE tick with its quiet/resync/
        degraded context and the per-shape kernel attribution attached."""
        if not self.tracer.enabled:
            return
        t_end = self._clock()
        tick_ctx = self.tracer.new_context(parent=self._trace_ctx)
        root = self.tracer.record(
            "tick", t0, t_end, context=tick_ctx,
            attrs={
                "tick": out.get("tick"),
                "quiet": bool(out.get("quiet", False)),
                "resynced": bool(out.get("resynced", False)),
                "degraded": bool(out.get("degraded", False)),
                "changed_rows": int(out.get("changed_rows", 0) or 0),
                "upload_rows": int(out.get("upload_rows", 0) or 0),
                "kernel_path": getattr(
                    self.session, "kernel_path", None
                ),
            },
        )
        spans = [root.to_dict()]
        t = t0
        for name, key in (("tick.capture", "capture_ms"),
                          ("tick.dispatch", "dispatch_ms"),
                          ("tick.fetch", "fetch_ms")):
            dur_s = float(out.get(key, 0.0) or 0.0) / 1e3
            child = self.tracer.record(
                name, t, t + dur_s, parent=tick_ctx,
                attrs={"ms": round(dur_s * 1e3, 3)},
            )
            t += dur_s
            spans.append(child.to_dict())
        out["health"]["spans"] = spans

    def _health_record(self, out: Dict[str, Any]) -> Dict[str, Any]:
        """Per-tick resilience health: what degraded, why, and how much
        recovery effort was spent."""
        injected: List[Dict[str, str]] = []
        drain = getattr(self.client, "drain_injected", None)
        if drain is not None:
            with suppressed("live.drain_injected"):
                injected = drain()
        retries_now = retry_counter()
        spent = retries_now - self._retries_mark
        self._retries_mark = retries_now
        if (self._compile_cache.get("enabled")
                and "new_entries" not in self._compile_cache
                and getattr(self.session, "ticks", 0) > 0):
            # first post-tick health record: how many executables this
            # session had to COMPILE (new cache files) — 0 means the
            # persistent cache served everything (a warm start)
            now = compile_cache_status().get("entries", 0)
            self._compile_cache["new_entries"] = (
                now - self._compile_cache.get("entries", 0)
            )
            self._compile_cache["warm"] = (
                self._compile_cache["new_entries"] == 0
            )
        # kernelscope channel (ISSUE 12): cumulative recompile counts +
        # the periodic device-memory sample.  NOT in the recorder's
        # _HEALTH_KEYS — compile/memory state is host-of-the-day, not
        # replayable incident state.
        scope = self.recompile_monitor.snapshot()
        kernelscope = {
            "recompiles": scope["recompiles"],
            "recompiles_post_warm": scope["recompiles_post_warm"],
            "compiles": scope["compiles"],
        }
        if scope["recompiled"]:
            kernelscope["recompiled"] = scope["recompiled"]
        mem = self.memory_accountant.maybe_sample(self._polls)
        if mem is not None:
            kernelscope["device_memory"] = mem
        return {
            "sanitized_rows": int(out.get("sanitized_rows", 0)),
            "kernelscope": kernelscope,
            "pipeline_depth": self.pipeline_depth,
            "result_lag": (
                0 if self.pipeline_depth == 1 or out.get("pipeline_fill")
                else self.pipeline_depth - 1
            ),
            "inflight": len(self._inflight),
            "pipeline_flushed": self.pipeline_flushed,
            "pipeline_fill": bool(out.get("pipeline_fill", False)),
            # the ENGAGED combine path for this session's padded shape
            # (autotune winner AND block-divisibility — ISSUE 11): a
            # pallas regression in a health stream names a shape.  The
            # retired process-level noisyor_path stamp (ISSUE 14
            # satellite) is subsumed by this per-shape attribution.
            "kernel_path": getattr(self.session, "kernel_path", None),
            "compile_cache": dict(self._compile_cache),
            "resyncs_expired": self.resyncs_expired,
            "resyncs_topology": self.resyncs_topology,
            "resync_cause": (
                self._last_resync_cause if out.get("resynced") else None
            ),
            "retries": int(spent),
            "faults": drain_faults(),
            "injected": injected,
            "degradation": self.degradation,
            "degradation_rung": DEGRADATION_LADDER[self.degradation],
        }

    # -- degradation ladder -------------------------------------------------
    def _degrade(self) -> None:
        """Step one rung down: sharded/full → single-device GraphEngine →
        interpret mode (jit disabled, op-by-op dispatch).  The rebuilt
        session re-uploads the retained feature matrix; the ladder is
        sticky for the session lifetime — a resync keeps the downgraded
        engine (repeated dispatch failure is an environment property, not
        a per-graph one)."""
        self.degradation = min(self.degradation + 1,
                               len(DEGRADATION_LADDER) - 1)
        self._tick_failures = 0
        # drain the pipeline: queued in-flight handles were dispatched on
        # the engine that just failed repeatedly — their results are
        # suspect and their buffers belong to the session being replaced.
        # Dropping (counted, surfaced in health) is the clean drain; the
        # retained feature matrix re-uploads below, so no DATA is lost,
        # only up to depth-1 stale rankings.
        self.pipeline_flushed += len(self._inflight)
        self._inflight.clear()
        if self.degradation == 1:
            self.engine = GraphEngine()
            src, dst = self._edges_raw
            self.session = make_streaming_session(
                self._names, src, dst,
                num_features=self._features.shape[1],
                engine=self.engine, k=self.k, clock=self._clock,
            )
            self.session.set_all(self._features)
        # rung 2 ("interpret") keeps the single-device session and runs
        # its tick under jax.disable_jit() — see _guarded_tick

    def _guarded_tick(self) -> Dict[str, Any]:
        """session.tick() under the degradation ladder: a dispatch failure
        records the fault, steps the ladder after repeated failure, and
        retries — poll() never sees the exception unless every rung fails.
        """
        import jax

        last_exc: Optional[Exception] = None
        for _ in range(len(DEGRADATION_LADDER) + 1):
            try:
                if self.degradation >= 2:
                    with jax.disable_jit():
                        out = self.session.tick()
                else:
                    out = self.session.tick()
                self._tick_failures = 0
                if last_exc is not None or self.degradation > 0:
                    out["_tick_degraded"] = True
                return out
            except Exception as exc:
                last_exc = exc
                record_fault(
                    f"live.tick[{DEGRADATION_LADDER[self.degradation]}]", exc
                )
                self._tick_failures += 1
                if self.degradation >= len(DEGRADATION_LADDER) - 1:
                    break
                if self._tick_failures >= _TICK_FAILURES_TO_DEGRADE:
                    self._degrade()
        # every rung failed (or the bottom rung keeps failing): degraded
        # no-result tick — the ranking is stale but poll() stays alive
        return {
            "ranked": list(self._last_ranked), "latency_ms": 0.0,
            "tick": self._polls, "upload_rows": 0, "sanitized_rows": 0,
            "_tick_degraded": True,
        }

    # -- pipelined tick (pipeline_depth >= 2) --------------------------------
    def _guarded_dispatch(self):
        """session.dispatch() under the degradation ladder (the dispatch
        half of :meth:`_guarded_tick`'s contract): a failure records the
        fault, steps the ladder after repeated failure, and retries on the
        rebuilt session.  Returns None only when every rung failed."""
        import jax

        for _ in range(len(DEGRADATION_LADDER) + 1):
            try:
                if self.degradation >= 2:
                    with jax.disable_jit():
                        handle = self.session.dispatch()
                else:
                    handle = self.session.dispatch()
                self._tick_failures = 0
                return handle
            except Exception as exc:
                record_fault(
                    "live.dispatch"
                    f"[{DEGRADATION_LADDER[self.degradation]}]", exc
                )
                self._tick_failures += 1
                if self.degradation >= len(DEGRADATION_LADDER) - 1:
                    break
                if self._tick_failures >= _TICK_FAILURES_TO_DEGRADE:
                    self._degrade()
        return None

    def _guarded_fetch(self, handle) -> Optional[Dict[str, Any]]:
        """Fetch one in-flight tick; an execution fault surfacing at the
        fetch (that is where async dispatch errors land) is absorbed like
        a serial tick failure: record, count toward the ladder, return
        None — the caller serves the last known ranking degraded."""
        try:
            out = handle.session.fetch(handle)
            self._tick_failures = 0
            return out
        except Exception as exc:
            record_fault(
                f"live.fetch[{DEGRADATION_LADDER[self.degradation]}]", exc
            )
            self._tick_failures += 1
            if (self._tick_failures >= _TICK_FAILURES_TO_DEGRADE
                    and self.degradation < len(DEGRADATION_LADDER) - 1):
                self._degrade()
            return None

    def _tick_pipelined(self) -> Dict[str, Any]:
        """One pipelined tick: dispatch THIS capture's work, then return
        the tick issued ``pipeline_depth - 1`` polls ago — its device
        round trip ran while the intervening captures did host work.
        While the pipeline fills (and after a flush) the poll returns the
        last known ranking with ``pipeline_fill``; rankings are otherwise
        exactly the serial sequence, one poll late per depth step
        (parity-tested in tests/test_tick_pipeline.py)."""
        handle = self._guarded_dispatch()
        degraded = handle is None or self.degradation > 0
        if handle is not None:
            self._inflight.append(handle)
        out: Optional[Dict[str, Any]] = None
        fill = False
        if len(self._inflight) > self.pipeline_depth - 1 or (
            handle is None and self._inflight
        ):
            # queue full (steady state) — or dispatch is broken, in which
            # case drain rather than sit on results that already exist
            out = self._guarded_fetch(self._inflight.popleft())
            if out is None:
                degraded = True
        elif handle is not None and not degraded:
            fill = True  # healthy, pipeline still filling
        if out is None:
            out = {
                "ranked": list(self._last_ranked), "latency_ms": 0.0,
                "tick": self._polls,
                "upload_rows": handle.upload_rows if handle else 0,
                "sanitized_rows": 0,
                "dispatch_ms": (
                    round(handle.dispatch_ms, 3) if handle else 0.0
                ),
            }
        if fill:
            out["pipeline_fill"] = True
        if degraded:
            out["_tick_degraded"] = True
        return out

    def _poll_inner(self) -> Dict[str, Any]:
        if not self._watch:
            return self._poll_sweep()
        t0 = self._clock()
        if self._pending_resync:
            # the previous poll drained notifications it could not apply;
            # a fresh full capture re-covers whatever they described
            self._pending_resync = False
            self._resync(cause="expired")
            return self._finish(
                t0, changed=len(self._names), resynced=True, quiet=False,
            )
        if self._force_topology_check or (
            self._polls % self.topology_check_every == 0
        ):
            # periodic full check: trace data (edges AND error-rate/latency
            # features) can drift invisibly to the feed; drain it first so
            # the cursor stays current — and if the feed expired, reopen
            # it NOW (a sticky pump expiry would otherwise force a full
            # resync on the very next poll, right after this sweep).
            # ``_force_topology_check`` is expiry recovery pulling this
            # check forward: lost notifications may have been topology.
            self._force_topology_check = False
            resp = self.client.watch_changes(self.namespace, self._cursor)
            self._cursor = resp.get("cursor")
            if resp.get("expired"):
                self._reopen_feed()
            try:
                return self._poll_sweep(check_edges=True)
            except Exception:
                # the drained changes are gone from the feed and the sweep
                # that superseded them never landed
                self._pending_resync = True
                raise
        resp = self.client.watch_changes(self.namespace, self._cursor)
        if not resp.get("supported"):
            self._watch = False
            return self._poll_sweep()
        self._cursor = resp.get("cursor")
        if resp.get("expired"):
            try:
                return self._recover_from_expiry(t0)
            except Exception:
                # recovery itself failed mid-flight: fall back to the full
                # resync next poll (same contract as a failed sweep)
                self._pending_resync = True
                raise
        changes = resp.get("changes", [])
        if not changes:
            return self._finish(t0, changed=0, resynced=False, quiet=True)
        try:
            if any(c["kind"] in _TOPOLOGY_KINDS for c in changes):
                self._resync()
                return self._finish(
                    t0, changed=len(self._names), resynced=True, quiet=False,
                )
            if self._columnar_active():
                # busy-poll columnar capture: the get_columnar diff IS the
                # patch (row writes for exactly the journaled names);
                # trace payloads carry forward unless journaled, the same
                # contract _patch_snapshot has
                traces_touched = any(
                    c["kind"] == "traces" for c in changes
                )
                snap = self._capture_full(
                    traces_from=(
                        None if traces_touched else self._snap.traces
                    ),
                )
            else:
                snap = self._patch_snapshot(changes)
            # busy-poll capture: every mutation reaching here is
            # journal-mediated (the API server — or the mock's touch —
            # bumped resourceVersion), so the incremental extractor
            # re-derives ONLY the changed rows (columnar snapshots skip
            # the row cache entirely — their rows pre-assembled)
            fs = self._extractor.extract(snap)
            if list(fs.service_names) != self._names:
                self._resync(snap=snap, fs=fs)
                return self._finish(
                    t0, changed=len(self._names), resynced=True, quiet=False,
                )
            if any(c["kind"] == "traces" for c in changes):
                # trace dependencies shape the session's device-pinned
                # edges: a journaled trace change must re-derive them and
                # resync on drift (feature-only trace changes fall through
                # to the diff)
                edges = service_dependency_edges(snap, fs)
                if (edges[0].tobytes(), edges[1].tobytes()) != self._edge_key:
                    self._resync(snap=snap, fs=fs, edges=edges)
                    return self._finish(
                        t0, changed=len(self._names), resynced=True,
                        quiet=False,
                    )
        except Exception:
            # changes were drained but never applied — recover next poll
            self._pending_resync = True
            raise
        self._snap = snap
        changed = self._upload_diff(fs)
        return self._finish(t0, changed=changed, resynced=False, quiet=False)

    def _upload_diff(self, fs) -> int:
        new = np.asarray(fs.service_features, np.float32)
        changed = np.flatnonzero(np.any(new != self._features, axis=1))
        if len(changed):
            # dirty-row slice straight into the delta-scatter staging
            # (ISSUE 10): one [U] index vector + one [U, C] block, no
            # per-row dict hop
            self.session.update_rows(changed, new[changed])
            self._features[changed] = new[changed]
        return int(len(changed))

    def _finish(self, t0: float, changed: int, resynced: bool,
                quiet: bool) -> Dict[str, Any]:
        capture_ms = (self._clock() - t0) * 1e3
        out = (
            self._tick_pipelined() if self.pipeline_depth > 1
            else self._guarded_tick()
        )
        out.update(
            changed_rows=changed, resynced=resynced, quiet=quiet,
            capture_ms=round(capture_ms, 2), resyncs=self.resyncs,
            # session-lifetime counter: the inner StreamingSession is
            # replaced on resync, so its "tick" restarts at 1 and the
            # CLI/UI sequence would go non-monotonic
            tick=self._polls,
        )
        return out

    def _poll_sweep(self, check_edges: bool = False) -> Dict[str, Any]:
        """Full list + extract + diff (the only strategy without a change
        feed; the watch path's periodic topology check also lands here).
        With columnar capture the "full list" is the table mirror — the
        sweep's out-of-band-drift net narrows to what the journal carries
        plus the trace payloads it refetches, which is the same
        journal-mediated visibility contract the watch feed already has."""
        t0 = self._clock()
        snap = self._capture_full()
        # full mode: sweeps exist to catch OUT-OF-BAND drift (trace-derived
        # edges, un-journaled mutations), which the rv-keyed row cache by
        # definition cannot see — recompute rows, refresh the cache
        fs = self._extractor.extract(snap, incremental=False)
        resynced = False
        edges = None
        if list(fs.service_names) != self._names:
            resynced = True
        elif check_edges or (
            not self._watch
            and self._polls % self.topology_check_every == 0
        ):
            edges = service_dependency_edges(snap, fs)
            if (edges[0].tobytes(), edges[1].tobytes()) != self._edge_key:
                resynced = True
        if resynced:
            if self._watch:
                # reopen-THEN-capture, not the reverse: jumping the cursor
                # to head after this (already minutes-old) capture would
                # orphan every change that landed during it.  _resync with
                # no snapshot does the ordering right (reopen, re-list) at
                # the cost of one extra sweep — resyncs are rare
                self._resync()
            else:
                self._resync(snap=snap, fs=fs, edges=edges)
            return self._finish(
                t0, changed=len(self._names), resynced=True, quiet=False,
            )
        if self._watch:
            # only the watch path's _patch_snapshot ever reads _snap;
            # retaining a 10k-service snapshot in pure-sweep mode would
            # pin pods+logs+events for the session lifetime for nothing
            self._snap = snap
            if snap.errors:
                # PARTIAL capture standing in for drained (and therefore
                # discarded) notifications: the objects the capture missed
                # may be exactly the ones that changed — schedule a
                # recovery resync rather than serving stale rows until the
                # next periodic sweep (round-3 advisor finding)
                self._pending_resync = True
        changed = self._upload_diff(fs)
        return self._finish(t0, changed=changed, resynced=False, quiet=False)
