"""Log-depth operator doubling: the 8-step serial chain in ~3 applications.

ROADMAP item 4(b), SNIPPETS.md retrieval goal ("cut the 50k
scatter-bound propagation via log-depth operator doubling").  The
propagation's two scans are SERIAL recursions of depth ``steps`` (8 by
default): every step pays one E-sized gather and one E-sized scatter,
and on tunneled TPUs the chain's latency is 8 round trips of exactly the
traffic the edge-layout study measured as the bottleneck.  Both
recursions admit doubling:

- **up-scan (max semiring)** — ``u_K[s] = max over paths s->..->d of
  length l<=K of y^(l-1) h[d]``.  With the EXACT-k-hop frontier ``A^k``
  precomputed host-side, ``u_2k[s] = max(u_k[s], (y*)^k max over
  A^k(s) of u_k)``, where ``(y*)^k`` is k SEQUENTIAL multiplies by the
  decay.  Because fp32 max is order-invariant and every candidate value
  is ``h`` left-multiplied by y exactly (l-1) times — the same operation
  sequence the serial chain performs — the doubled up-scan is
  **bit-identical** to the serial scan for ANY decay (property-tested).
- **down-scan (affine map)** — one impact step is ``f(m) = y*W m + W
  a_ex`` with ``W = D^-1 A^T``.  Doubling the affine map needs the
  operator POWERS: with host-precomputed weighted frontier layouts for
  ``W^(2^k)`` (edge lists whose weights aggregate the inv-degree
  products over parallel paths), ``v_{k+1} = y^(2^k) * (W^(2^k) v_k) +
  v_k`` reaches ``m_8`` in base + 3 applications.  Sums reassociate, so
  this direction is allclose (~1e-6, same class as the segscan layout),
  not bitwise — the parity tests assert exact up, tight-tolerance down,
  and identical ranking.

Cost model (why this is an eligibility hook, not a default): reaching
depth 8 needs the 2/4-hop frontiers, whose size is graph-dependent —
13.9x the edges at the 50k generator tier (tools/downscan_bench.py
measured), but near-E on deep sparse chains, which is exactly where 8
serial round trips hurt most.  The builder enforces ``MAX_FRONTIER_MULT``
and declines (returns None) past it; the registry row records the
reason, and the dispatch seam falls back to the serial path.

Interpret/hermetic path: pure jax.numpy (gathers + scatters), so the
CPU-host parity tests run the exact production math; forcing is
``RCA_KERNEL=doubling``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

#: frontier blowup cap: a doubling layout whose total edge count exceeds
#: this multiple of the padded edge tier is declined (hub-heavy graphs
#: square into dense frontiers; the serial chain is cheaper there)
MAX_FRONTIER_MULT = 16


class DoublingLayout(NamedTuple):
    """Device arrays for one padded graph's doubled operators.  Tuples of
    per-level arrays (a static pytree structure, so the executable is
    cached per level count like any other shape-bucket static):

    - ``up_src/up_dst[k]``: the exact ``2^k``-hop dependency frontier
      (pairs (s, e): e reachable from s in exactly ``2^k`` hops);
    - ``dn_src/dn_dst/dn_w[k]``: the weighted edge list of ``W^(2^k)``
      (down-scan operator power; weights aggregate inv-degree products
      over parallel paths).

    Level arrays are padded to power-of-two tiers with dummy self-loops
    (weight 0), the same stable-shape discipline as every other layout.
    """

    up_src: Tuple[jnp.ndarray, ...]
    up_dst: Tuple[jnp.ndarray, ...]
    dn_src: Tuple[jnp.ndarray, ...]
    dn_dst: Tuple[jnp.ndarray, ...]
    dn_w: Tuple[jnp.ndarray, ...]


def doubling_eligible(steps: int) -> bool:
    """Structural gate: the doubled ladder reaches exactly ``steps``
    only when it is a power of two (>= 2)."""
    return steps >= 2 and (steps & (steps - 1)) == 0


def _compose_pairs(src1, dst1, src2, dst2, n_pad: int, cap: int,
                   w1=None, w2=None):
    """Relational composition of two edge lists: pairs (s, e) with
    s->x in (src1, dst1) and x->e in (src2, dst2), deduplicated; with
    weights, parallel paths aggregate by sum (operator product).
    Returns None when the pre-dedup join exceeds ``cap``."""
    order = np.argsort(src2, kind="stable")
    s2, d2 = src2[order], dst2[order]
    w2s = w2[order] if w2 is not None else None
    left = np.searchsorted(s2, dst1, "left")
    right = np.searchsorted(s2, dst1, "right")
    counts = right - left
    total = int(counts.sum())
    if total > cap:
        return None
    if total == 0:
        empty = np.zeros(0, np.int32)
        return (empty, empty, np.zeros(0, np.float32)) \
            if w1 is not None else (empty, empty, None)
    rep = np.repeat(np.arange(len(src1)), counts)
    offs = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    idx2 = np.repeat(left, counts) + offs
    out_s = src1[rep].astype(np.int64)
    out_e = d2[idx2].astype(np.int64)
    key = out_s * n_pad + out_e
    if w1 is None:
        uniq = np.unique(key)
        return ((uniq // n_pad).astype(np.int32),
                (uniq % n_pad).astype(np.int32), None)
    uniq, inv = np.unique(key, return_inverse=True)
    agg = np.zeros(len(uniq), np.float64)
    np.add.at(agg, inv, (w1[rep] * w2s[idx2]).astype(np.float64))
    return ((uniq // n_pad).astype(np.int32),
            (uniq % n_pad).astype(np.int32), agg.astype(np.float32))


def _pad_level(src, dst, n_pad: int, w=None):
    """Pad one level to a power-of-two tier with dummy self-loops
    (weight 0): stable shapes per tier, harmless contributions (max of a
    zeroed row / add of 0)."""
    e = max(1, len(src))
    e_pad = 1 << (e - 1).bit_length()
    dummy = n_pad - 1
    s = np.full(e_pad, dummy, np.int32)
    d = np.full(e_pad, dummy, np.int32)
    s[: len(src)] = src
    d[: len(dst)] = dst
    if w is None:
        return jnp.asarray(s), jnp.asarray(d), None
    wv = np.zeros(e_pad, np.float32)
    wv[: len(w)] = w
    return jnp.asarray(s), jnp.asarray(d), jnp.asarray(wv)


def build_doubling(n_pad: int, e_pad: int, dep_src, dep_dst,
                   steps: int) -> Optional[DoublingLayout]:
    """Host-side frontier construction for one padded graph, or None
    when ineligible (non-power-of-two depth) or past the frontier cap.
    Operates on the RAW edges; padded slots would only add dummy
    self-loops that dedup away."""
    if not doubling_eligible(steps):
        return None
    src = np.asarray(dep_src, np.int64)
    dst = np.asarray(dep_dst, np.int64)
    cap = MAX_FRONTIER_MULT * max(int(e_pad), 1)
    # down-scan base weights: W[d, s] = inv_deg[d] per edge (s, d), with
    # the degree counted exactly like the device path (real edges only —
    # padded slots land on the dummy row the scoring ignores)
    deg = np.bincount(dst, minlength=n_pad).astype(np.float32)
    inv_deg = 1.0 / np.maximum(deg, 1.0)
    levels = steps.bit_length() - 1        # steps = 2 ** levels
    up_s, up_d = [src.astype(np.int32)], [dst.astype(np.int32)]
    dn_s = [src.astype(np.int32)]
    dn_d = [dst.astype(np.int32)]
    dn_w = [inv_deg[dst].astype(np.float32)]
    total = len(src)
    for _ in range(1, levels):
        nxt = _compose_pairs(up_s[-1], up_d[-1], up_s[-1], up_d[-1],
                             n_pad, cap)
        if nxt is None:
            return None
        up_s.append(nxt[0])
        up_d.append(nxt[1])
        wnxt = _compose_pairs(dn_s[-1], dn_d[-1], dn_s[-1], dn_d[-1],
                              n_pad, cap, w1=dn_w[-1], w2=dn_w[-1])
        if wnxt is None:
            return None
        dn_s.append(wnxt[0])
        dn_d.append(wnxt[1])
        dn_w.append(wnxt[2])
        total += len(nxt[0]) + len(wnxt[0])
        if total > cap:
            return None
    ups, upd = [], []
    dns, dnd, dnw = [], [], []
    for k in range(levels):
        s, d, _ = _pad_level(up_s[k], up_d[k], n_pad)
        ups.append(s)
        upd.append(d)
        s, d, w = _pad_level(dn_s[k], dn_d[k], n_pad, dn_w[k])
        dns.append(s)
        dnd.append(d)
        dnw.append(w)
    return DoublingLayout(tuple(ups), tuple(upd),
                          tuple(dns), tuple(dnd), tuple(dnw))


def doubling_up(h, decay: float, dbl: DoublingLayout):
    """The full up-scan in log depth.  Base: one scatter-max of ``h``
    over the 1-hop edges (= serial step 1 from u=0).  Level k doubles
    the horizon over the exact ``2^k``-hop frontier with ``2^k``
    sequential decay multiplies — bit-identical to the serial chain
    (module docstring)."""
    u = jnp.zeros_like(h).at[dbl.up_src[0]].max(h[dbl.up_dst[0]])
    for k in range(len(dbl.up_src)):
        vals = u[dbl.up_dst[k]]
        for _ in range(1 << k):
            vals = decay * vals
        u = jnp.maximum(u, jnp.zeros_like(u).at[dbl.up_src[k]].max(vals))
    return u


def doubling_down(a_ex, decay: float, dbl: DoublingLayout, inv_deg):
    """The full impact scan in log depth: base ``v_0 = W a_ex`` (the
    serial step from m=0, same scatter-then-normalize association), then
    ``v_{k+1} = decay^(2^k) * (W^(2^k) v_k) + v_k`` per level."""
    v = jnp.zeros_like(a_ex).at[dbl.dn_dst[0]].add(
        a_ex[dbl.dn_src[0]]
    ) * inv_deg
    for k in range(len(dbl.dn_src)):
        applied = jnp.zeros_like(v).at[dbl.dn_dst[k]].add(
            dbl.dn_w[k] * v[dbl.dn_src[k]]
        )
        v = (decay ** (1 << k)) * applied + v
    return v


# -- per-graph layout cache (same digest discipline as segscan's) -------------

_DOUBLING_CACHE: dict = {}


def doubling_layouts_for(n_pad: int, e_pad: int, dep_src, dep_dst,
                         steps: int) -> Optional[DoublingLayout]:
    """Cached frontier build for one edge set (host argsort/join costs
    milliseconds at the 50k tier — paid once per pinned graph).  A
    cached None records "declined: frontier cap" so hub graphs don't
    re-pay the join on every request."""
    from rca_tpu.engine.segscan import arrays_digest, cache_insert

    src = np.asarray(dep_src)
    dst = np.asarray(dep_dst)
    key = arrays_digest((n_pad, e_pad, steps), (src, dst))
    if key in _DOUBLING_CACHE:
        return _DOUBLING_CACHE[key]
    layout = build_doubling(n_pad, e_pad, src, dst, steps)
    cache_insert(_DOUBLING_CACHE, key, layout)
    return layout
