"""The TPU causal-inference core.

Replaces the reference's evidence-fusion step — a serial flatten-and-prompt
LLM call (reference: agents/mcp_coordinator.py:666-760) and the legacy
group-by-component heuristic (reference: agents/coordinator.py:118-184) —
with a jit-compiled explain-away propagation over the service-dependency
graph:

1. per-service anomaly from fused features (noisy-OR over channels),
2. upstream hard-failure signal propagated dependency→dependent
   (``lax.scan`` of segment-max steps) — a service whose dependency is
   crashed has its own anomaly *explained away*,
3. downstream impact accumulated dependent→dependency (segment-sum steps) —
   a faulty service with many symptomatic dependents ranks higher,
4. root score = (anomaly + impact bonus) × (1 − explained-away), top-k ranked.

Everything is static-shaped (bucketed padding) and compiles once per bucket.
"""

from rca_tpu.engine.propagate import (
    PropagationParams,
    default_params,
    propagate,
    propagate_jit,
)
from rca_tpu.engine.live import LiveStreamingSession
from rca_tpu.engine.runner import EngineResult, GraphEngine
from rca_tpu.engine.sharded_runner import ShardedGraphEngine, make_engine
from rca_tpu.engine.streaming import StreamingSession

__all__ = [
    "PropagationParams",
    "default_params",
    "propagate",
    "propagate_jit",
    "EngineResult",
    "GraphEngine",
    "ShardedGraphEngine",
    "make_engine",
    "StreamingSession",
    "LiveStreamingSession",
]
