"""KernelRegistry: the per-shape kernel table and THE dispatch seam.

ROADMAP item 4's refactor unlock (ISSUE 12 tentpole): every bench round
since r02 reported ``pallas_engaged: false`` as one process-wide bit,
decided by a one-shot autotune at a single canonical shape and then
re-derived ad hoc at four call surfaces (one-shot analyze, streaming
flush, resident delta, serve dispatch) — so adding a kernel meant
editing every surface, and a per-shape regression had nowhere to show
up.  This module replaces that with a declarative registry:

- a **row per ``(variant, n_pad, backend)``** records the engaged
  combine kernel (``xla | pallas`` today; a segscan or quantized kernel
  is a new :data:`KERNELS` candidate + an eligibility/timing hook, not a
  rewrite), WHY it won (``forced``/``cpu-default``/``ineligible``/
  ``timed``/``cache``/``sharded``), the per-candidate autotune timings,
  and the winner executable's XLA cost analysis (FLOPs, bytes accessed,
  peak temp/output memory) captured at compile time;
- :func:`engaged_kernel` is the ONE place a propagation surface asks
  "which kernel does this padded shape run" — graftlint rule
  ``kernel-dispatch`` makes calling the kernel bodies (or the legacy
  autotune shims) outside this seam unlandable;
- timed winners persist to a **file cache** (``RCA_KERNEL_CACHE``,
  keyed by jax version + a kernel-set source hash) so restarts don't
  re-time; corrupt or stale entries re-time instead of crashing, and
  ``RCA_KERNEL_CACHE=0`` disables the cache entirely;
- the same rows feed bench's ``kernel_registry`` section AND its
  ``kernel_by_shape`` map (agreement by construction — ISSUE 12
  satellite), the ``/metrics`` per-shape gauges, dispatch-span
  attributes, tick health records, and the ``rca kernels`` CLI table.

CPU hosts (tests) short-circuit to XLA without timing, exactly like the
old autotune: the Pallas kernel only runs interpreted there, and timing
an interpreter proves nothing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from rca_tpu.config import kernel_cache_path
from rca_tpu.util.threads import make_lock

#: candidate propagation kernels, in table order (ISSUE 13 tentpole —
#: ROADMAP item 4 (a)-(c) landed).  Adding a kernel = appending here +
#: an eligibility entry in :func:`_eligibility` + a timing leg in
#: :func:`_time_candidates`:
#:
#: - ``xla``      — f32 evidence + hybrid/COO scans (the default);
#: - ``pallas``   — fused Pallas noisy-OR evidence, same scans;
#: - ``segscan``  — Pallas flagged segmented-scan up/down layouts
#:                  (engine/segscan.py; its old ``RCA_SEGSCAN`` side
#:                  gate now lives HERE, registry-resident);
#: - ``quantized``— bf16 evidence + per-row int8 message quantization
#:                  on the E-sized gather traffic (engine/quantized.py;
#:                  rank-parity-gated, not bitwise);
#: - ``doubling`` — log-depth operator doubling over precomputed
#:                  frontier layouts (engine/doubling.py; 8 serial steps
#:                  -> base + 3 applications).
KERNELS = ("xla", "pallas", "segscan", "quantized", "doubling")

#: kernels expressible on the sharded (shard_map) engine: the per-block
#: scatter kernel has a segscan twin (parallel/sharded.py), the rest
#: have none yet
SHARDED_KERNELS = ("xla", "segscan")

#: the canonical shape the process-level compat path times at (the old
#: ``noisyor_autotune`` measured one [8192, C] block and applied the
#: verdict everywhere; per-shape rows supersede it, the constant remains
#: for the back-compat shim)
CANONICAL_PAD = 8192

_CACHE_VERSION = 1


def _flag() -> str:
    """Composite env fingerprint for the row key: a test flipping ANY
    dispatch knob mid-process re-decides instead of serving a stale
    verdict (``RCA_KERNEL`` is the unified force added in ISSUE 13;
    ``RCA_PALLAS``/``RCA_SEGSCAN`` keep their documented semantics)."""
    from rca_tpu.config import env_int, env_str

    return ":".join((
        env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1")),
        env_str("RCA_KERNEL", "", choices=("",) + KERNELS, lower=True),
        env_str("RCA_SEGSCAN", "", choices=("0", "1")),
        env_str("SEGSCAN_INTERPRET", "", choices=("0", "1")),
        env_str("RCA_EDGE_LAYOUT", "hybrid", lower=True),
        str(env_int("RCA_SEGSCAN_MIN", 1024, 0, 2**31 - 1)),
    ))


def forced_kernel() -> Optional[str]:
    """The explicitly forced kernel, or None for autotune.  Precedence:
    the unified ``RCA_KERNEL`` knob, then the legacy per-kernel forces
    it unifies (``RCA_PALLAS=1``, ``RCA_SEGSCAN=1``, the hermetic-test
    ``SEGSCAN_INTERPRET=1``)."""
    from rca_tpu.config import env_str

    k = env_str("RCA_KERNEL", "", choices=("",) + KERNELS, lower=True)
    if k:
        return k
    if env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1")) == "1":
        return "pallas"
    if (env_str("RCA_SEGSCAN", "", choices=("0", "1")) == "1"
            or env_str("SEGSCAN_INTERPRET", "", choices=("0", "1")) == "1"):
        return "segscan"
    return None


def _backend() -> str:
    import jax

    return jax.default_backend()


def kernel_set_hash() -> str:
    """Source hash of the kernel set: the cache invalidation key.  A
    change to any kernel body, the propagation core, or this registry
    re-times every shape — a stale winner must never outlive the code
    that earned it (ISSUE 12 satellite)."""
    global _KERNEL_SET_HASH
    if _KERNEL_SET_HASH is None:
        h = hashlib.sha1(repr(KERNELS).encode())
        base = os.path.dirname(os.path.abspath(__file__))
        # the grown kernel set is part of the key by construction (repr
        # above) AND by source: a cache written by the 2-kernel registry
        # re-times under the 5-kernel one (ISSUE 13 acceptance)
        for fname in ("propagate.py", "pallas_kernels.py", "registry.py",
                      "segscan.py", "quantized.py", "doubling.py",
                      "ell.py"):
            try:
                with open(os.path.join(base, fname), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(fname.encode())
        _KERNEL_SET_HASH = h.hexdigest()[:16]
    return _KERNEL_SET_HASH


_KERNEL_SET_HASH: Optional[str] = None


@dataclasses.dataclass
class KernelRow:
    """One registry row: the engaged kernel for one padded shape.
    ``e_pad`` (the padded EDGE tier) joined the key in ISSUE 13: the
    segscan/doubling/quantized kernels are edge-layout kernels, so their
    eligibility and timings are per (node tier, edge tier), not per node
    tier alone.  ``e_pad is None`` marks a caller that could not name an
    edge tier (the legacy process-level shim): edge-dependent kernels
    are ineligible there and the row decides among xla/pallas only."""

    variant: str                  # dense | sharded | attribution
    n_pad: int
    backend: str                  # jax.default_backend() at resolve time
    winner: str                   # the engaged kernel (a KERNELS member)
    source: str                   # forced|cpu-default|unsupported|
    #                               ineligible|timed|cache|sharded
    e_pad: Optional[int] = None   # padded edge tier (None = unknown)
    steps: int = 8                # propagation depth the row decided for
    eligible: Dict[str, Any] = dataclasses.field(default_factory=dict)
    timings_ms: Dict[str, Optional[float]] = dataclasses.field(
        default_factory=dict
    )
    cost: Optional[Dict[str, Any]] = None   # winner-executable XLA cost

    def to_dict(self) -> Dict[str, Any]:
        return {
            "variant": self.variant,
            "n_pad": self.n_pad,
            "e_pad": self.e_pad,
            "steps": self.steps,
            "backend": self.backend,
            "winner": self.winner,
            "source": self.source,
            "eligible": dict(self.eligible),
            "timings_ms": dict(self.timings_ms),
            "cost": dict(self.cost) if self.cost else None,
        }


class KernelRegistry:
    """The per-shape kernel table (one per process via
    :func:`get_registry`).  Rows resolve lazily the first time a surface
    asks about a shape; the lock is a leaf (the timing/cost work runs
    outside it — two threads may race to time the same shape, last
    write wins with identical results)."""

    def __init__(self, cache_path: Optional[str] = "unset"):
        # "unset" sentinel: resolve RCA_KERNEL_CACHE lazily per lookup so
        # tests can monkeypatch the env without rebuilding the registry
        self._cache_path_override = cache_path
        self._lock = make_lock("KernelRegistry._lock")
        self._rows: Dict[Tuple[str, int, str, str], KernelRow] = {}

    # -- cache file ----------------------------------------------------------
    def _cache_file(self) -> Optional[str]:
        if self._cache_path_override != "unset":
            return self._cache_path_override
        return kernel_cache_path()

    @staticmethod
    def _read_cache_rows(path: Optional[str]) -> Optional[Dict[str, Any]]:
        """Validated rows from one cache file, or None.  A stale header
        (jax upgrade, kernel edit, or a shipped cache from a DIFFERENT
        platform whose filename key happens to match) re-times instead
        of poisoning — the header check is the guarantee."""
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            return None  # corrupt cache: re-time, never crash
        import jax

        if (
            not isinstance(data, dict)
            or data.get("version") != _CACHE_VERSION
            or data.get("jax") != jax.__version__
            or data.get("kernel_set") != kernel_set_hash()
        ):
            return None  # stale header (jax upgrade / kernel edit): re-time
        rows = data.get("rows")
        return rows if isinstance(rows, dict) else None

    def _load_cached(self, key: str) -> Optional[Dict[str, Any]]:
        row = None
        rows = self._read_cache_rows(self._cache_file())
        if rows is not None:
            row = rows.get(key)
        if row is None:
            # fleet cold-start (ROADMAP item 2): fall back to the
            # committed platform-keyed cache (engine/kernel_cache.
            # <platform>.json) so a fresh worker process skips autotune
            # for shapes the shipped cache already timed on this
            # platform.  Never written to — user cache overrides it.
            from rca_tpu.config import shipped_kernel_cache_path

            shipped = self._read_cache_rows(shipped_kernel_cache_path())
            if shipped is not None:
                row = shipped.get(key)
        if not isinstance(row, dict) or row.get("winner") not in KERNELS:
            return None
        return row

    def _store_cached(self, key: str, row: KernelRow) -> None:
        path = self._cache_file()
        if not path:
            return
        import jax

        header = {
            "version": _CACHE_VERSION,
            "jax": jax.__version__,
            "kernel_set": kernel_set_hash(),
        }
        try:
            existing: Dict[str, Any] = {}
            if os.path.exists(path):
                try:
                    with open(path, encoding="utf-8") as f:
                        data = json.load(f)
                    if (
                        isinstance(data, dict)
                        and data.get("version") == _CACHE_VERSION
                        and data.get("jax") == header["jax"]
                        and data.get("kernel_set") == header["kernel_set"]
                    ):
                        existing = data.get("rows") or {}
                except (json.JSONDecodeError, UnicodeDecodeError):
                    existing = {}  # corrupt file: rewrite from scratch
            existing[key] = {
                "winner": row.winner,
                "timings_ms": dict(row.timings_ms),
                "cost": dict(row.cost) if row.cost else None,
            }
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({**header, "rows": existing}, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            pass  # an unwritable cache must not fail the dispatch

    # -- resolution ----------------------------------------------------------
    def resolve(self, n_pad: int, e_pad: Optional[int] = None,
                sharded: bool = False, steps: int = 8,
                variant: Optional[str] = None) -> KernelRow:
        """The row for one padded shape, created on first ask.  Rows are
        keyed by the dispatch env knobs too (:func:`_flag`), so a test
        flipping the env mid-process re-decides instead of serving a
        stale verdict.  ``variant`` overrides the dense/sharded pair —
        ``"attribution"`` (ISSUE 14) is the causelens counterfactual/
        gradient sweep, which dispatches through this same seam so its
        rows show up in ``rca kernels``, bench, and ``/metrics``."""
        n_pad = int(n_pad)
        e_pad = int(e_pad) if e_pad is not None else None
        steps = int(steps)
        if variant is None:
            variant = "sharded" if sharded else "dense"
        flag = _flag()
        backend = _backend()
        key = (variant, n_pad, e_pad, steps, backend, flag)
        with self._lock:
            row = self._rows.get(key)
        if row is not None:
            return row
        row = self._decide(variant, n_pad, e_pad, steps, backend)
        with self._lock:
            self._rows[key] = row
        return row

    def note_timing(self, n_pad: int, e_pad: Optional[int], name: str,
                    ms: float, variant: str = "dense",
                    steps: int = 8) -> None:
        """Record one observed wall cost into a row's timings (keeps the
        MINIMUM — first calls carry compile time, the floor is the
        steady-state cost).  The attribution sweep stamps its per-shape
        cost here so bench's ``attribution`` section and ``rca kernels``
        report explain-on cost from the one registry table."""
        row = self.resolve(n_pad, e_pad=e_pad, steps=steps, variant=variant)
        with self._lock:
            prev = row.timings_ms.get(name)
            if prev is None or float(ms) < float(prev):
                row.timings_ms[name] = round(float(ms), 4)

    def _decide(self, variant: str, n_pad: int, e_pad: Optional[int],
                steps: int, backend: str) -> KernelRow:
        from rca_tpu.engine.pallas_kernels import pallas_supported

        eligible = _eligibility(variant, n_pad, e_pad, steps)
        row = KernelRow(
            variant=variant, n_pad=n_pad, e_pad=e_pad, steps=steps,
            backend=backend, winner="xla", source="default",
            eligible=eligible,
        )
        if variant == "attribution":
            # the causelens sweep (ISSUE 14): re-propagates through the
            # differentiable xla body (vmap over counterfactual masks +
            # one backward pass) — the other kernels record WHY they sit
            # out in the eligibility map; the observed per-shape cost
            # lands in timings via note_timing
            row.source = "attribution"
            return row
        if variant == "sharded":
            # the sharded per-block propagation has a segscan twin
            # (parallel/sharded.py) but no shard_map twin of the other
            # kernels; its gate mirrors the dense auto gate (forced, or
            # TPU at or above RCA_SEGSCAN_MIN)
            row.source = "sharded"
            if eligible.get("segscan") is True and (
                forced_kernel() == "segscan"
                or (backend == "tpu" and n_pad >= _segscan_min())
            ):
                row.winner = "segscan"
            return row
        forced = forced_kernel()
        if forced is not None:
            if forced == "pallas":
                # forced: pallas_supported raises loudly on compile fail
                pallas_supported()
            if eligible.get(forced) is True:
                row.winner = forced
                row.source = "forced"
            else:
                row.source = "ineligible"
            return row
        if backend == "cpu":
            # non-accelerator: every non-XLA kernel runs interpreted (or
            # emulated) here — timing an interpreter burns seconds to
            # confirm the obvious; forcing still works for tests
            row.source = "cpu-default"
            return row
        candidates = [k for k in KERNELS if eligible.get(k) is True]
        if "pallas" in candidates and not pallas_supported():
            eligible["pallas"] = "pallas compile probe failed"
            candidates.remove("pallas")
        if "segscan" in candidates and n_pad < _segscan_min():
            eligible["segscan"] = (
                f"n_pad {n_pad} below RCA_SEGSCAN_MIN {_segscan_min()}"
            )
            candidates.remove("segscan")
        if candidates == ["xla"]:
            row.source = "ineligible"
            return row
        cache_key = f"{variant}:{n_pad}:{e_pad}:{steps}:{backend}"
        cached = self._load_cached(cache_key)
        if cached is not None:
            row.winner = cached["winner"]
            row.source = "cache"
            row.timings_ms = dict(cached.get("timings_ms") or {})
            if cached.get("cost"):
                row.cost = dict(cached["cost"])
            return row
        row.timings_ms = _time_candidates(n_pad, e_pad, steps, candidates)
        row.winner = _pick_winner(row.timings_ms)
        row.source = "timed"
        self._store_cached(cache_key, row)
        return row

    # -- cost analysis -------------------------------------------------------
    def ensure_cost(self, row: KernelRow) -> KernelRow:
        """Capture the winner executable's XLA cost analysis for a row
        that lacks it (one AOT compile of the canonical propagation body
        at this shape).  Explicit, not automatic: a ``/metrics`` scrape
        must never trigger a compile — ``rca kernels`` and bench call
        this, serve surfaces export whatever is already captured."""
        if row.cost is None:
            row.cost = _capture_cost(
                row.n_pad, row.e_pad, row.winner, row.steps
            )
            if row.source in ("timed", "cache"):
                cache_key = (f"{row.variant}:{row.n_pad}:{row.e_pad}:"
                             f"{row.steps}:{row.backend}")
                self._store_cached(cache_key, row)
        return row

    # -- reading -------------------------------------------------------------
    def table(self, ensure_cost: bool = False,
              cost_max_pad: int = 4096) -> List[Dict[str, Any]]:
        """Every resolved row, smallest shape first.  ``ensure_cost``
        captures missing cost analysis for rows with ``n_pad <=
        cost_max_pad`` (the cap bounds how much compile time a table
        dump may spend — a 50k-pad canonical compile is tens of seconds
        on a CPU host; its row still shows winner + timings)."""
        with self._lock:
            rows = sorted(
                self._rows.values(),
                key=lambda r: (r.variant, r.n_pad, r.e_pad or -1,
                               r.backend),
            )
        out = []
        for row in rows:
            if ensure_cost and row.cost is None and row.n_pad <= cost_max_pad:
                self.ensure_cost(row)
            out.append(row.to_dict())
        return out

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()


def _segscan_min() -> int:
    from rca_tpu.config import env_int

    return env_int("RCA_SEGSCAN_MIN", 1024, 0, 2**31 - 1)


def _eligibility(variant: str, n_pad: int, e_pad: Optional[int],
                 steps: int) -> Dict[str, Any]:
    """Per-kernel structural eligibility at one shape: ``True`` or a
    human-readable decline reason.  THE hook a new kernel registers
    with (ISSUE 13): segscan's old ``RCA_SEGSCAN`` side gate, the
    quantized row-width rule, and doubling's power-of-two depth rule all
    live here, so ``rca kernels --explain`` can say WHY a candidate was
    never in the race."""
    from rca_tpu.config import env_str
    from rca_tpu.engine.pallas_kernels import BLOCK_S
    from rca_tpu.engine.doubling import doubling_eligible
    from rca_tpu.engine.segscan import segscan_eligibility

    layout = env_str("RCA_EDGE_LAYOUT", "hybrid",
                     choices=("hybrid", "coo", "ell"), lower=True)
    out: Dict[str, Any] = {"xla": True}
    if variant == "attribution":
        # causelens (ISSUE 14): the counterfactual vmap + gradient
        # saliency need a differentiable, maskable body — only the xla
        # path qualifies today; the reasons below are what `rca kernels
        # --explain` prints for the attribution rows
        out["pallas"] = "no gradient rule for the fused evidence kernel"
        out["segscan"] = "no gradient twin for the flagged segment scan"
        out["quantized"] = "int8 messages are not differentiable"
        out["doubling"] = (
            "frontier layouts have no per-row counterfactual twin"
        )
        return out
    # pallas: the fused evidence kernel (dense only, block-divisible)
    if variant == "sharded":
        out["pallas"] = "no shard_map twin"
    elif env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1")) == "0":
        out["pallas"] = "RCA_PALLAS=0"
    elif n_pad % min(n_pad, BLOCK_S) != 0:
        out["pallas"] = f"n_pad {n_pad} not divisible into {BLOCK_S} blocks"
    else:
        out["pallas"] = True
    # segscan: structural gate shared by dense and sharded (the sharded
    # engine ships the per-block twin); dense additionally requires the
    # hybrid layout (RCA_EDGE_LAYOUT=coo/ell pin the layout-study paths)
    if variant == "dense" and layout != "hybrid":
        out["segscan"] = f"RCA_EDGE_LAYOUT={layout} pins the scan layout"
    else:
        out["segscan"] = segscan_eligibility(n_pad, e_pad)
    # quantized / doubling: dense-only edge-layout kernels
    for name, extra in (("quantized", None), ("doubling", None)):
        if variant == "sharded":
            out[name] = "no shard_map twin"
        elif layout == "ell":
            out[name] = "RCA_EDGE_LAYOUT=ell pins the gather-table layout"
        elif e_pad is None:
            out[name] = "edge tier unknown (caller passed no e_pad)"
        else:
            out[name] = True
    if out.get("doubling") is True and not doubling_eligible(steps):
        out["doubling"] = (
            f"steps {steps} not a power of two (doubled ladder cannot "
            f"land exactly)"
        )
    return out


def _pick_winner(timings: Dict[str, Optional[float]]) -> str:
    """Ties (and unmeasurable candidates) go to XLA — the simpler,
    default-tested path, same policy the one-shot autotune had; a
    challenger must beat XLA by >5% to take a row."""
    t_xla = timings.get("xla")
    if t_xla is None:
        return "xla"
    best, best_t = "xla", t_xla
    for k, t in timings.items():
        if k != "xla" and t is not None and t < best_t:
            best, best_t = k, t
    return best if best_t < 0.95 * t_xla else "xla"


def _timing_harness(n_pad: int, e_pad: Optional[int], steps: int):
    """The synthetic graph + per-kernel layout builder the timing and
    cost hooks share: a ring over ``n_pad - 1`` live nodes padded to
    ``e_pad`` edges — canonical per shape (the registry key), not per
    graph, so rows stay comparable across rounds."""
    import numpy as np

    n_pad = int(n_pad)
    e_pad = int(e_pad) if e_pad is not None else n_pad
    n = max(1, n_pad - 1)
    dummy = n_pad - 1
    src = np.full(e_pad, dummy, np.int32)
    dst = np.full(e_pad, dummy, np.int32)
    ring = np.arange(min(n, e_pad), dtype=np.int32)
    src[: len(ring)] = ring
    dst[: len(ring)] = (ring + 1) % n
    return n, e_pad, src, dst, ring


def _layouts_for_winner(kernel: str, n_pad: int, e_pad: int,
                        src, dst, steps: int):
    """(down_seg, up_seg, up_ell, dbl) for one candidate over the
    canonical harness graph — the same layout assembly the dispatch
    surfaces run (runner.kernel_plan), minus the registry ask."""
    down_seg = up_seg = up_ell = dbl = None
    raw = src[src != n_pad - 1], dst[src != n_pad - 1]
    if kernel == "segscan":
        from rca_tpu.engine.segscan import build_down_seg, build_up_seg

        down_seg = build_down_seg(n_pad, e_pad, raw[0], raw[1])
        up_seg = build_up_seg(n_pad, e_pad, raw[0], raw[1])
    elif kernel == "doubling":
        from rca_tpu.engine.doubling import build_doubling

        dbl = build_doubling(n_pad, e_pad, raw[0], raw[1], steps)
        if dbl is None:
            raise ValueError("doubling frontier declined the harness graph")
    elif kernel in ("xla", "pallas", "quantized"):
        from rca_tpu.engine.runner import up_ell_for

        up_ell = up_ell_for(n_pad, raw[0], raw[1])
    return down_seg, up_seg, up_ell, dbl


def _time_candidates(n_pad: int, e_pad: Optional[int], steps: int,
                     candidates) -> Dict[str, Optional[float]]:
    """Amortized in-jit timing of each candidate's FULL propagation
    chain (evidence + both scans) at THIS padded shape: rep count folds
    a salt so no transport cache can replay, sync is by FETCHING a slice
    — never ``block_until_ready`` (PERF.md round-1 correction).  A
    candidate that cannot even time records ``None`` (and cannot win)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    n, e_pad, src, dst, _ = _timing_harness(n_pad, e_pad, steps)
    rng = np.random.default_rng(0)
    f = jnp.asarray(
        rng.uniform(0, 1, (n_pad, NUM_SERVICE_FEATURES)).astype(np.float32)
    )
    edges = jnp.asarray(np.stack([src, dst]))
    w = jnp.asarray(
        rng.uniform(0.2, 0.9, NUM_SERVICE_FEATURES).astype(np.float32)
    )

    def timed(kernel: str, reps: int = 20) -> Optional[float]:
        from rca_tpu.engine.runner import propagate_auto

        try:
            layouts = _layouts_for_winner(
                kernel, n_pad, e_pad, src, dst, steps
            )
            down_seg, up_seg, up_ell, dbl = layouts

            @jax.jit
            def many(x, salt):
                def body(i, acc):
                    out = propagate_auto(
                        x * (1.0 + salt + i * 1e-7), edges, w, w,
                        steps, 0.7, 0.85, 1.6,
                        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
                        kernel=kernel, dbl=dbl,
                    )
                    return acc + out[4]
                return jax.lax.fori_loop(0, reps, body, jnp.zeros(n_pad))

            jax.device_get(many(f, jnp.float32(1e-7))[:4])  # compile
            outs = []
            for j in range(3):
                t0 = time.perf_counter()
                jax.device_get(many(f, jnp.float32((j + 2) * 1e-7))[:4])
                outs.append(time.perf_counter() - t0)
            return float(min(outs)) * 1e3 / reps
        except Exception:
            return None  # a path that cannot even time cannot win

    return {k: timed(k) for k in candidates}


def _capture_cost(n_pad: int, e_pad: Optional[int], winner: str,
                  steps: int = 8) -> Dict[str, Any]:
    """XLA cost + memory analysis of the canonical propagation
    executable at this padded shape: the one-shot fused body
    (``_propagate_ranked`` — sanitize + evidence + propagation + top-k)
    AOT-lowered over a ring graph at the row's (node, edge) tiers with
    the WINNER's layouts.  Canonical, not per-session: the figures scale
    with the shape (the registry key), not with one graph's edge list,
    so rows stay comparable across rounds.  Backends without cost
    analysis record why instead of crashing."""
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.engine.propagate import default_params
    from rca_tpu.engine.runner import _propagate_ranked
    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    n_pad = int(n_pad)
    n, e_pad, src, dst, _ = _timing_harness(n_pad, e_pad, steps)
    p = default_params(steps)
    aw, hw = p.weight_arrays()
    features = jnp.zeros((n_pad, NUM_SERVICE_FEATURES), jnp.float32)
    edges = jnp.asarray(np.stack([src, dst]))
    kk = min(13, n_pad)
    try:
        down_seg, up_seg, up_ell, dbl = _layouts_for_winner(
            winner, n_pad, e_pad, src, dst, steps
        )
        compiled = _propagate_ranked.lower(
            features, edges, aw, hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
            winner, jnp.asarray(n, jnp.int32), up_ell, down_seg,
            up_seg, dbl, error_contrast=p.error_contrast,
        ).compile()
    except Exception as exc:
        return {"unavailable": f"compile: {type(exc).__name__}: {exc}"}
    out: Dict[str, Any] = {"kernel": winner, "k": kk}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        if "transcendentals" in ca:
            out["transcendentals"] = float(ca["transcendentals"])
    except Exception as exc:
        out["cost_unavailable"] = f"{type(exc).__name__}: {exc}"
    try:
        ma = compiled.memory_analysis()
        for attr, key in (
            ("temp_size_in_bytes", "peak_temp_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("argument_size_in_bytes", "argument_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            value = getattr(ma, attr, None)
            if value is not None:
                out[key] = int(value)
    except Exception as exc:
        out["memory_unavailable"] = f"{type(exc).__name__}: {exc}"
    return out


# -- the process registry + module-level seam ---------------------------------

_REGISTRY: Optional[KernelRegistry] = None
_REGISTRY_LOCK = make_lock("registry._REGISTRY_LOCK")


def get_registry() -> KernelRegistry:
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = KernelRegistry()
        return _REGISTRY


def reset_registry() -> None:
    """Drop every resolved row (tests flipping env knobs)."""
    get_registry().clear()


def engaged_kernel(n_pad: int, e_pad: Optional[int] = None,
                   sharded: bool = False, steps: int = 8,
                   variant: Optional[str] = None) -> str:
    """THE dispatch seam: which propagation kernel an
    ``(n_pad, e_pad)``-padded graph engages.  Every call surface
    (one-shot analyze, streaming flush, resident delta, serve dispatch,
    sharded tick, and the causelens attribution sweep via
    ``variant="attribution"``) asks HERE — graftlint rule
    ``kernel-dispatch`` keeps it that way.  Callers that cannot name an
    edge tier get the xla/pallas-only decision (edge-layout kernels
    need ``e_pad``)."""
    return get_registry().resolve(
        n_pad, e_pad=e_pad, sharded=sharded, steps=steps, variant=variant,
    ).winner


def autotune_path(refresh: bool = False) -> str:
    """Process-level compat for the retired one-shot autotune: the
    winner at the canonical shape (``xla``/``pallas``).  Sessions stamp
    this as ``noisyor_path`` next to the per-shape ``kernel_path``."""
    global _PROCESS_PATH
    if refresh:
        reset_registry()
        _PROCESS_PATH = None
    if _PROCESS_PATH is None:
        _PROCESS_PATH = get_registry().resolve(CANONICAL_PAD).winner
    return _PROCESS_PATH


def autotuned_path() -> Optional[str]:
    """The cached process-level choice, or None when nothing autotuned
    yet (the old ``noisyor_path()`` contract)."""
    return _PROCESS_PATH


_PROCESS_PATH: Optional[str] = None


def kernel_table(ensure_cost: bool = False,
                 cost_max_pad: int = 4096) -> List[Dict[str, Any]]:
    """Every resolved row as dicts — bench ``kernel_registry``,
    ``/metrics`` gauges, and ``rca kernels`` all read THIS, so they
    agree by construction."""
    return get_registry().table(
        ensure_cost=ensure_cost, cost_max_pad=cost_max_pad,
    )
