"""KernelRegistry: the per-shape kernel table and THE dispatch seam.

ROADMAP item 4's refactor unlock (ISSUE 12 tentpole): every bench round
since r02 reported ``pallas_engaged: false`` as one process-wide bit,
decided by a one-shot autotune at a single canonical shape and then
re-derived ad hoc at four call surfaces (one-shot analyze, streaming
flush, resident delta, serve dispatch) — so adding a kernel meant
editing every surface, and a per-shape regression had nowhere to show
up.  This module replaces that with a declarative registry:

- a **row per ``(variant, n_pad, backend)``** records the engaged
  combine kernel (``xla | pallas`` today; a segscan or quantized kernel
  is a new :data:`KERNELS` candidate + an eligibility/timing hook, not a
  rewrite), WHY it won (``forced``/``cpu-default``/``ineligible``/
  ``timed``/``cache``/``sharded``), the per-candidate autotune timings,
  and the winner executable's XLA cost analysis (FLOPs, bytes accessed,
  peak temp/output memory) captured at compile time;
- :func:`engaged_kernel` is the ONE place a propagation surface asks
  "which kernel does this padded shape run" — graftlint rule
  ``kernel-dispatch`` makes calling the kernel bodies (or the legacy
  autotune shims) outside this seam unlandable;
- timed winners persist to a **file cache** (``RCA_KERNEL_CACHE``,
  keyed by jax version + a kernel-set source hash) so restarts don't
  re-time; corrupt or stale entries re-time instead of crashing, and
  ``RCA_KERNEL_CACHE=0`` disables the cache entirely;
- the same rows feed bench's ``kernel_registry`` section AND its
  ``kernel_by_shape`` map (agreement by construction — ISSUE 12
  satellite), the ``/metrics`` per-shape gauges, dispatch-span
  attributes, tick health records, and the ``rca kernels`` CLI table.

CPU hosts (tests) short-circuit to XLA without timing, exactly like the
old autotune: the Pallas kernel only runs interpreted there, and timing
an interpreter proves nothing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from rca_tpu.config import kernel_cache_path
from rca_tpu.util.threads import make_lock

#: candidate combine kernels, in table order.  Adding a kernel =
#: appending here + teaching :func:`_eligible` / :func:`_time_candidates`
#: about it (ROADMAP item 4 names ``segscan`` and ``quantized`` next).
KERNELS = ("xla", "pallas")

#: the canonical shape the process-level compat path times at (the old
#: ``noisyor_autotune`` measured one [8192, C] block and applied the
#: verdict everywhere; per-shape rows supersede it, the constant remains
#: for the back-compat shim)
CANONICAL_PAD = 8192

_CACHE_VERSION = 1


def _flag() -> str:
    from rca_tpu.config import env_str

    return env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1"))


def _backend() -> str:
    import jax

    return jax.default_backend()


def kernel_set_hash() -> str:
    """Source hash of the kernel set: the cache invalidation key.  A
    change to any kernel body, the propagation core, or this registry
    re-times every shape — a stale winner must never outlive the code
    that earned it (ISSUE 12 satellite)."""
    global _KERNEL_SET_HASH
    if _KERNEL_SET_HASH is None:
        h = hashlib.sha1(repr(KERNELS).encode())
        base = os.path.dirname(os.path.abspath(__file__))
        for fname in ("propagate.py", "pallas_kernels.py", "registry.py"):
            try:
                with open(os.path.join(base, fname), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(fname.encode())
        _KERNEL_SET_HASH = h.hexdigest()[:16]
    return _KERNEL_SET_HASH


_KERNEL_SET_HASH: Optional[str] = None


@dataclasses.dataclass
class KernelRow:
    """One registry row: the engaged kernel for one padded shape."""

    variant: str                  # dense | sharded
    n_pad: int
    backend: str                  # jax.default_backend() at resolve time
    winner: str                   # the engaged kernel (a KERNELS member)
    source: str                   # forced|cpu-default|unsupported|
    #                               ineligible|timed|cache|sharded
    eligible: Dict[str, Any] = dataclasses.field(default_factory=dict)
    timings_ms: Dict[str, Optional[float]] = dataclasses.field(
        default_factory=dict
    )
    cost: Optional[Dict[str, Any]] = None   # winner-executable XLA cost

    def to_dict(self) -> Dict[str, Any]:
        return {
            "variant": self.variant,
            "n_pad": self.n_pad,
            "backend": self.backend,
            "winner": self.winner,
            "source": self.source,
            "eligible": dict(self.eligible),
            "timings_ms": dict(self.timings_ms),
            "cost": dict(self.cost) if self.cost else None,
        }


class KernelRegistry:
    """The per-shape kernel table (one per process via
    :func:`get_registry`).  Rows resolve lazily the first time a surface
    asks about a shape; the lock is a leaf (the timing/cost work runs
    outside it — two threads may race to time the same shape, last
    write wins with identical results)."""

    def __init__(self, cache_path: Optional[str] = "unset"):
        # "unset" sentinel: resolve RCA_KERNEL_CACHE lazily per lookup so
        # tests can monkeypatch the env without rebuilding the registry
        self._cache_path_override = cache_path
        self._lock = make_lock("KernelRegistry._lock")
        self._rows: Dict[Tuple[str, int, str, str], KernelRow] = {}

    # -- cache file ----------------------------------------------------------
    def _cache_file(self) -> Optional[str]:
        if self._cache_path_override != "unset":
            return self._cache_path_override
        return kernel_cache_path()

    def _load_cached(self, key: str) -> Optional[Dict[str, Any]]:
        path = self._cache_file()
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            return None  # corrupt cache: re-time, never crash
        import jax

        if (
            not isinstance(data, dict)
            or data.get("version") != _CACHE_VERSION
            or data.get("jax") != jax.__version__
            or data.get("kernel_set") != kernel_set_hash()
        ):
            return None  # stale header (jax upgrade / kernel edit): re-time
        row = (data.get("rows") or {}).get(key)
        if not isinstance(row, dict) or row.get("winner") not in KERNELS:
            return None
        return row

    def _store_cached(self, key: str, row: KernelRow) -> None:
        path = self._cache_file()
        if not path:
            return
        import jax

        header = {
            "version": _CACHE_VERSION,
            "jax": jax.__version__,
            "kernel_set": kernel_set_hash(),
        }
        try:
            existing: Dict[str, Any] = {}
            if os.path.exists(path):
                try:
                    with open(path, encoding="utf-8") as f:
                        data = json.load(f)
                    if (
                        isinstance(data, dict)
                        and data.get("version") == _CACHE_VERSION
                        and data.get("jax") == header["jax"]
                        and data.get("kernel_set") == header["kernel_set"]
                    ):
                        existing = data.get("rows") or {}
                except (json.JSONDecodeError, UnicodeDecodeError):
                    existing = {}  # corrupt file: rewrite from scratch
            existing[key] = {
                "winner": row.winner,
                "timings_ms": dict(row.timings_ms),
                "cost": dict(row.cost) if row.cost else None,
            }
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({**header, "rows": existing}, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            pass  # an unwritable cache must not fail the dispatch

    # -- resolution ----------------------------------------------------------
    def resolve(self, n_pad: int, sharded: bool = False) -> KernelRow:
        """The row for one padded shape, created on first ask.  Rows are
        keyed by the ``RCA_PALLAS`` flag too, so a test flipping the env
        mid-process re-decides instead of serving a stale verdict."""
        n_pad = int(n_pad)
        variant = "sharded" if sharded else "dense"
        flag = _flag()
        backend = _backend()
        key = (variant, n_pad, backend, flag)
        with self._lock:
            row = self._rows.get(key)
        if row is not None:
            return row
        row = self._decide(variant, n_pad, backend, flag)
        with self._lock:
            self._rows[key] = row
        return row

    def _decide(self, variant: str, n_pad: int, backend: str,
                flag: str) -> KernelRow:
        from rca_tpu.engine.pallas_kernels import (
            BLOCK_S,
            pallas_supported,
        )

        divisible = n_pad % min(n_pad, BLOCK_S) == 0
        eligible: Dict[str, Any] = {
            "xla": True,
            "pallas": (
                True if divisible
                else f"n_pad {n_pad} not divisible into {BLOCK_S} blocks"
            ),
        }
        row = KernelRow(
            variant=variant, n_pad=n_pad, backend=backend,
            winner="xla", source="default", eligible=eligible,
        )
        if variant == "sharded":
            # the sharded per-block kernel keeps XLA's fused noisy-OR —
            # the Pallas pair has no shard_map twin (SERVING.md)
            row.source = "sharded"
            row.eligible["pallas"] = "no shard_map twin"
            return row
        if flag == "1":
            # forced: pallas_supported raises loudly if the compile fails
            pallas_supported()
            row.winner = "pallas" if divisible else "xla"
            row.source = "forced" if divisible else "ineligible"
            return row
        if flag == "0":
            row.source = "forced"
            return row
        if backend == "cpu":
            # non-accelerator: the kernel only runs interpreted here —
            # timing an interpreter burns seconds to confirm the obvious
            row.source = "cpu-default"
            return row
        if not pallas_supported():
            row.source = "unsupported"
            return row
        if not divisible:
            row.source = "ineligible"
            return row
        cache_key = f"{variant}:{n_pad}:{backend}"
        cached = self._load_cached(cache_key)
        if cached is not None:
            row.winner = cached["winner"]
            row.source = "cache"
            row.timings_ms = dict(cached.get("timings_ms") or {})
            if cached.get("cost"):
                row.cost = dict(cached["cost"])
            return row
        row.timings_ms = _time_candidates(n_pad)
        t_xla = row.timings_ms.get("xla")
        t_pallas = row.timings_ms.get("pallas")
        # ties (and unmeasurable candidates) go to XLA — the simpler,
        # default-tested path, same policy the one-shot autotune had
        row.winner = (
            "pallas"
            if t_xla is not None and t_pallas is not None
            and t_pallas < 0.95 * t_xla
            else "xla"
        )
        row.source = "timed"
        self._store_cached(cache_key, row)
        return row

    # -- cost analysis -------------------------------------------------------
    def ensure_cost(self, row: KernelRow) -> KernelRow:
        """Capture the winner executable's XLA cost analysis for a row
        that lacks it (one AOT compile of the canonical propagation body
        at this shape).  Explicit, not automatic: a ``/metrics`` scrape
        must never trigger a compile — ``rca kernels`` and bench call
        this, serve surfaces export whatever is already captured."""
        if row.cost is None:
            row.cost = _capture_cost(row.n_pad, row.winner)
            if row.source in ("timed", "cache"):
                cache_key = f"{row.variant}:{row.n_pad}:{row.backend}"
                self._store_cached(cache_key, row)
        return row

    # -- reading -------------------------------------------------------------
    def table(self, ensure_cost: bool = False,
              cost_max_pad: int = 4096) -> List[Dict[str, Any]]:
        """Every resolved row, smallest shape first.  ``ensure_cost``
        captures missing cost analysis for rows with ``n_pad <=
        cost_max_pad`` (the cap bounds how much compile time a table
        dump may spend — a 50k-pad canonical compile is tens of seconds
        on a CPU host; its row still shows winner + timings)."""
        with self._lock:
            rows = sorted(
                self._rows.values(),
                key=lambda r: (r.variant, r.n_pad, r.backend),
            )
        out = []
        for row in rows:
            if ensure_cost and row.cost is None and row.n_pad <= cost_max_pad:
                self.ensure_cost(row)
            out.append(row.to_dict())
        return out

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()


def _time_candidates(n_pad: int, reps: int = 200) -> Dict[str, Optional[float]]:
    """Amortized in-jit timing of each candidate's evidence pass at THIS
    padded shape: rep count folds a salt so no transport cache can
    replay, sync is by FETCHING a slice — never ``block_until_ready``
    (PERF.md round-1 correction).  A candidate that cannot even time
    records ``None`` (and cannot win)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.engine.pallas_kernels import (
        noisy_or_pair_pallas,
        noisy_or_pair_xla,
    )
    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    rng = np.random.default_rng(0)
    f = jnp.asarray(
        rng.uniform(0, 1, (n_pad, NUM_SERVICE_FEATURES)).astype(np.float32)
    )
    ft = f.T
    w = jnp.asarray(
        rng.uniform(0.2, 0.9, NUM_SERVICE_FEATURES).astype(np.float32)
    )

    def timed(fn, arg) -> Optional[float]:
        @jax.jit
        def many(x, salt):
            def body(i, acc):
                a, h = fn(x * (1.0 + salt + i * 1e-7), w, w)
                return acc + a + h
            return jax.lax.fori_loop(0, reps, body, jnp.zeros(n_pad))

        try:
            jax.device_get(many(arg, jnp.float32(1e-7))[:4])  # compile
            outs = []
            for j in range(3):
                t0 = time.perf_counter()
                jax.device_get(many(arg, jnp.float32((j + 2) * 1e-7))[:4])
                outs.append(time.perf_counter() - t0)
            return float(min(outs)) * 1e3 / reps
        except Exception:
            return None  # a path that cannot even time cannot win

    return {
        "xla": timed(noisy_or_pair_xla, f),
        "pallas": timed(noisy_or_pair_pallas, ft),
    }


def _capture_cost(n_pad: int, winner: str) -> Dict[str, Any]:
    """XLA cost + memory analysis of the canonical propagation
    executable at this padded shape: the one-shot fused body
    (``_propagate_ranked`` — sanitize + evidence + propagation + top-k)
    AOT-lowered over a ring graph with ``n_pad`` padded edges in pure
    COO form.  Canonical, not per-session: the figures scale with the
    shape (the registry key), not with one graph's edge list, so rows
    stay comparable across rounds.  Backends without cost analysis
    record why instead of crashing."""
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.engine.propagate import default_params
    from rca_tpu.engine.runner import _propagate_ranked
    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    n_pad = int(n_pad)
    n = max(1, n_pad - 1)  # slot n_pad-1 is the engine's dummy row
    dummy = n_pad - 1
    src = np.full(n_pad, dummy, np.int32)
    dst = np.full(n_pad, dummy, np.int32)
    ring = np.arange(n, dtype=np.int32)
    src[:n] = ring
    dst[:n] = (ring + 1) % n
    p = default_params()
    aw, hw = p.weight_arrays()
    features = jnp.zeros((n_pad, NUM_SERVICE_FEATURES), jnp.float32)
    edges = jnp.asarray(np.stack([src, dst]))
    kk = min(13, n_pad)
    try:
        compiled = _propagate_ranked.lower(
            features, edges, aw, hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
            winner == "pallas", jnp.asarray(n, jnp.int32), None, None,
            None, error_contrast=p.error_contrast,
        ).compile()
    except Exception as exc:
        return {"unavailable": f"compile: {type(exc).__name__}: {exc}"}
    out: Dict[str, Any] = {"kernel": winner, "k": kk}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        if "transcendentals" in ca:
            out["transcendentals"] = float(ca["transcendentals"])
    except Exception as exc:
        out["cost_unavailable"] = f"{type(exc).__name__}: {exc}"
    try:
        ma = compiled.memory_analysis()
        for attr, key in (
            ("temp_size_in_bytes", "peak_temp_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("argument_size_in_bytes", "argument_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            value = getattr(ma, attr, None)
            if value is not None:
                out[key] = int(value)
    except Exception as exc:
        out["memory_unavailable"] = f"{type(exc).__name__}: {exc}"
    return out


# -- the process registry + module-level seam ---------------------------------

_REGISTRY: Optional[KernelRegistry] = None
_REGISTRY_LOCK = make_lock("registry._REGISTRY_LOCK")


def get_registry() -> KernelRegistry:
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = KernelRegistry()
        return _REGISTRY


def reset_registry() -> None:
    """Drop every resolved row (tests flipping env knobs)."""
    get_registry().clear()


def engaged_kernel(n_pad: int, sharded: bool = False) -> str:
    """THE dispatch seam: which combine kernel a propagation over an
    ``n_pad``-padded graph engages.  Every call surface (one-shot
    analyze, streaming flush, resident delta, serve dispatch, sharded
    tick) asks HERE — graftlint rule ``kernel-dispatch`` keeps it that
    way."""
    return get_registry().resolve(n_pad, sharded=sharded).winner


def autotune_path(refresh: bool = False) -> str:
    """Process-level compat for the retired one-shot autotune: the
    winner at the canonical shape (``xla``/``pallas``).  Sessions stamp
    this as ``noisyor_path`` next to the per-shape ``kernel_path``."""
    global _PROCESS_PATH
    if refresh:
        reset_registry()
        _PROCESS_PATH = None
    if _PROCESS_PATH is None:
        _PROCESS_PATH = get_registry().resolve(CANONICAL_PAD).winner
    return _PROCESS_PATH


def autotuned_path() -> Optional[str]:
    """The cached process-level choice, or None when nothing autotuned
    yet (the old ``noisyor_path()`` contract)."""
    return _PROCESS_PATH


_PROCESS_PATH: Optional[str] = None


def kernel_table(ensure_cost: bool = False,
                 cost_max_pad: int = 4096) -> List[Dict[str, Any]]:
    """Every resolved row as dicts — bench ``kernel_registry``,
    ``/metrics`` gauges, and ``rca kernels`` all read THIS, so they
    agree by construction."""
    return get_registry().table(
        ensure_cost=ensure_cost, cost_max_pad=cost_max_pad,
    )
