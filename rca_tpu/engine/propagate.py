"""jit-compiled explain-away propagation over the service graph.

Pure functional core: fixed shapes, ``lax.scan`` for the propagation steps
(no data-dependent Python control flow), segment scatter ops that XLA lowers
to efficient TPU scatters.  Padded slots carry zero features and self-edges
on the dummy node, so no masking is needed anywhere.

Math (S services, E dependency edges (s → d) meaning "s depends on d"):

    a  = 1 - ∏_c (1 - w_c f_c)            anomaly evidence (noisy-OR)
    h  = 1 - ∏_c (1 - v_c f_c)            hard "I am broken" evidence
    u_s = max_{(s,d)} max(h_d, γ·u_d)     upstream explanation (K steps)
    m_d = (1/deg_d) Σ_{(s,d)} (ā_s + γ·m_s)   downstream impact (K steps)
    score = a · (1 + β·tanh(m)) · (1 - μ·u·(1-h))

where ā is the anomaly excess over the cascade-wide background and deg_d
is d's dependent count.  The impact mean is DEGREE-NORMALIZED (formula v3):
"how symptomatic is my average dependent" is fan-in invariant, where the
raw sum grows with fan-in and let any hub service accumulate a saturating
impact bonus from correlated background alone (the round-2 adversarial
misses — every winner was an early-DAG hub with m in the tens; see
tools/accuracy_report.py and PERF.md).

A root cause is a service with strong hard evidence, no broken upstream
dependency, and many symptomatic dependents — exactly the ranking the
reference asked its LLM for ("identify causal relationships, rank root
causes", reference: mcp_coordinator.py:698-733), computed in microseconds.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.features.schema import NUM_SERVICE_FEATURES, SvcF


# Bumped whenever the scoring semantics change (weights fitted against one
# objective surface mis-rank under another): v2 = multiplicative impact
# bonus on background-excess accumulation (v1 was additive on raw anomaly);
# v3 = degree-normalized impact mean (v2's raw sum scaled with fan-in, so
# hub services saturated the bonus on correlated background alone).
SCORE_FORMULA_VERSION = 3


@dataclasses.dataclass(frozen=True)
class PropagationParams:
    anomaly_weights: tuple       # per-channel weights for a
    hard_weights: tuple          # per-channel weights for h
    steps: int = 8               # propagation iterations (graph diameter cap)
    decay: float = 0.7           # γ per-hop decay
    explain_strength: float = 0.85  # μ suppression by an anomalous upstream
    # β downstream-impact bonus.  v3 formula: m is a degree-normalized mean
    # (bounded), so β can be strong without hub risk — 1.6 picked by sweep
    # on tuning band 3000:3040, validated on disjoint bands 1000/2000:+60
    # (tools/accuracy_report.py; the v2 raw-sum formula capped β at 0.5)
    impact_bonus: float = 1.6
    # error-SOURCE contrast (round 5, VERDICT r4 item 3): weight on the
    # node's error rate IN EXCESS of its dependencies' max — errors flow
    # downstream-to-upstream-of-the-call (a service failing because its
    # dependency errors inherits that error rate, attenuated), so a node
    # whose error rate exceeds every dependency's is an error SOURCE.
    # This is the one channel that separated the round-4 adversarial_mixed
    # miss (a config root with CONFIG and NOT_READY dropped: error_rate
    # 0.58 vs its crashing hop-1 victim's 0.21 — PERF.md round-4 autopsy).
    # 0.7 picked by sweep on bands 1000/7000 (PERF.md round-5 study:
    # closes adversarial_mixed to 1.0, lifts every band-7000 archetype,
    # regresses nothing); folded into the anomaly noisy-OR, so it is
    # soft evidence amplified by impact and suppressed by explain-away
    # like any other anomaly channel.
    error_contrast: float = 0.7

    def weight_arrays(self):
        return (
            jnp.asarray(self.anomaly_weights, dtype=jnp.float32),
            jnp.asarray(self.hard_weights, dtype=jnp.float32),
        )


def default_params(steps: int = 8) -> PropagationParams:
    aw = np.zeros(NUM_SERVICE_FEATURES, dtype=np.float32)
    aw[SvcF.CRASH] = 1.0
    # soft symptoms (error rate, latency) are weak evidence of being the
    # ROOT — decoy services spike them without any downstream blast radius
    # (correlated_noise mode); held-out eval across all six cascade modes
    # picked 0.4/0.3 over the round-1 0.7/0.5 (PERF.md accuracy table)
    aw[SvcF.ERROR_RATE] = 0.4
    aw[SvcF.LATENCY] = 0.3
    aw[SvcF.RESTARTS] = 0.6
    aw[SvcF.EVENTS] = 0.4
    aw[SvcF.LOG_ERRORS] = 0.5
    aw[SvcF.NOT_READY] = 0.6
    aw[SvcF.RESOURCE] = 0.5
    aw[SvcF.IMAGE] = 0.9
    aw[SvcF.CONFIG] = 0.9
    aw[SvcF.PENDING] = 0.7
    aw[SvcF.OOM] = 0.95
    # absence evidence: down-but-silent (never started) is root evidence
    # comparable to the archetype channels it stands in for when dropout
    # hides them (VERDICT r3 item 4; tuned on band 3000, validated on the
    # disjoint band-7000 archetype study — see PERF.md)
    aw[SvcF.SILENT] = 0.6
    hw = np.zeros(NUM_SERVICE_FEATURES, dtype=np.float32)
    hw[SvcF.CRASH] = 1.0
    hw[SvcF.IMAGE] = 0.9
    hw[SvcF.CONFIG] = 0.9
    hw[SvcF.PENDING] = 0.6
    hw[SvcF.OOM] = 0.95
    hw[SvcF.RESTARTS] = 0.4
    # a not-ready service is observably broken: counting it as (moderate)
    # hard evidence keeps explain-away working when a root's crash channel
    # is dropped (missing_signals mode) — without it the root can't
    # suppress its blast radius and a high-impact victim outranks it
    hw[SvcF.NOT_READY] = 0.5
    hw[SvcF.SILENT] = 0.6
    return PropagationParams(
        anomaly_weights=tuple(float(x) for x in aw),
        hard_weights=tuple(float(x) for x in hw),
        steps=steps,
    )


def _noisy_or(features: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    clipped = jnp.clip(features, 0.0, 1.0)
    return 1.0 - jnp.prod(1.0 - clipped * weights[None, :], axis=1)


def finite_mask_rows(features: jnp.ndarray):
    """Zero every feature row carrying a NaN/Inf; return (clean, n_bad).

    The resilience guard in front of propagation: a collector feeding a
    poisoned metric channel (NaN usage, Inf latency) must degrade that ONE
    service's evidence to "no signal", not propagate NaN through the whole
    explain-away scan and wipe the ranking.  Runs fused inside the same
    dispatch as propagation (no extra host sync); on all-finite input
    ``jnp.where`` passes the original values through bit-identically, so
    the fault-free path keeps the CPU/TPU parity invariant (PARITY.md).

    Accepts [S, C] or batched [B, S, C]; ``n_bad`` is the total zeroed
    row count as a traced int32 scalar (fetched alongside top-k)."""
    ok = jnp.all(jnp.isfinite(features), axis=-1, keepdims=True)
    clean = jnp.where(ok, features, jnp.zeros_like(features))
    n_bad = jnp.sum(jnp.logical_not(ok)).astype(jnp.int32)
    return clean, n_bad


def background_excess(a: jnp.ndarray, n_live=None) -> jnp.ndarray:
    """Anomaly excess over the cascade-wide background level.  Correlated
    noise (scrape jitter, a hot node) lifts every service's evidence
    uniformly; impact must accumulate only the excess, otherwise any hub
    with enough dependents saturates its impact term on background alone.

    The background is the MEDIAN over live services — a robust location
    with a 50% breakdown point, so it tracks the quiet majority instead of
    being dragged up by the incident's own victims (a mean+σ cut zeroes the
    excess entirely on small graphs where most services are symptomatic).

    ``n_live`` is the number of REAL services: slots 0..n_live-1 are live
    (quiet services with a == 0 legitimately count toward the background),
    slots beyond are shape-bucket padding and are excluded.  ``None`` means
    every slot is live."""
    if n_live is None:
        return jnp.maximum(a - jnp.median(a), 0.0)
    live = jnp.arange(a.shape[0]) < n_live
    masked = jnp.where(live, a, jnp.nan)
    a_bg = jnp.nan_to_num(jnp.nanmedian(masked), nan=0.0)
    return jnp.where(live, jnp.maximum(a - a_bg, 0.0), 0.0)


def error_source_excess(features: jnp.ndarray, dep_src, dep_dst) -> jnp.ndarray:
    """Per-node error rate in excess of the node's dependencies' max
    (relu(e - max over edges (s,d) of e[d])), the round-5 error-SOURCE
    contrast.  One gather + one scatter-max, outside the step loop.
    Padded edges self-loop on the dummy slot whose error rate is 0, so
    they contribute the max identity; a service with no dependencies
    keeps its full error rate (a leaf that errors IS a source)."""
    e = jnp.clip(features[:, SvcF.ERROR_RATE], 0.0, 1.0)
    dep_max = jnp.zeros_like(e).at[dep_src].max(e[dep_dst])
    return jnp.maximum(e - dep_max, 0.0)


def fold_error_contrast(a, err_src, weight: float):
    """Noisy-OR the contrast into the anomaly evidence — identical math
    to a 14th feature channel with weight ``weight``, but computed where
    the edges live (the contrast needs the graph, which the row-local
    feature extractor never sees)."""
    return 1.0 - (1.0 - a) * (1.0 - weight * err_src)


def combine_score(a, h, u, m, explain_strength, impact_bonus):
    """Final root-cause score.  Explain-away suppresses *soft* symptoms
    (latency, error rates) that an anomalous upstream accounts for, damped
    by the node's own hard evidence: a crashed service is a cause in its own
    right even when a dependency is also broken (concurrent-root cascades).
    The impact bonus is MULTIPLICATIVE on the node's own evidence: a
    symptomatic blast radius amplifies existing evidence of being broken; it
    cannot make a healthy hub look like a root cause on fan-out alone.
    ``m`` arrives DEGREE-NORMALIZED (mean dependent symptom level, roughly
    0..1/(1-γ)), so tanh(m) uses its steep region where real cascades live
    — no /4 temper as in the v2 raw-sum formula."""
    return (
        a
        * (1.0 + impact_bonus * jnp.tanh(m))
        * (1.0 - explain_strength * u * (1.0 - h))
    )


def propagate(
    features: jnp.ndarray,  # [S, C] float32
    dep_src: jnp.ndarray,   # [E] int32 — the dependent
    dep_dst: jnp.ndarray,   # [E] int32 — the dependency
    anomaly_w: jnp.ndarray,  # [C]
    hard_w: jnp.ndarray,     # [C]
    steps: int,
    decay: float,
    explain_strength: float,
    impact_bonus: float,
    n_live=None,            # real-service count; slots beyond are padding
    up_ell=None,            # optional (idx, mask, ovf_seg, ovf_other)
    down_seg=None,          # optional engine.segscan.SegLayout
    up_seg=None,            # optional engine.segscan.SegLayout
    error_contrast: float = 0.0,
    dbl=None,               # optional engine.doubling.DoublingLayout
    quant: bool = False,    # int8 message quantization (engine.quantized)
):
    """Returns (anomaly, hard, upstream, impact, score), all [S]."""
    a = _noisy_or(features, anomaly_w)
    h = _noisy_or(features, hard_w)
    if error_contrast:
        a = fold_error_contrast(
            a, error_source_excess(features, dep_src, dep_dst),
            error_contrast,
        )
    return propagate_core(
        a, h, dep_src, dep_dst, steps, decay, explain_strength, impact_bonus,
        n_live=n_live, up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        dbl=dbl, quant=quant,
    )


def propagate_core(
    a: jnp.ndarray,         # [S] anomaly evidence
    h: jnp.ndarray,         # [S] hard evidence
    dep_src: jnp.ndarray,   # [E] int32 — the dependent
    dep_dst: jnp.ndarray,   # [E] int32 — the dependency
    steps: int,
    decay: float,
    explain_strength: float,
    impact_bonus: float,
    n_live=None,            # real-service count; slots beyond are padding
    up_ell=None,            # optional (idx, mask, ovf_seg, ovf_other)
    down_seg=None,          # optional engine.segscan.SegLayout
    up_seg=None,            # optional engine.segscan.SegLayout
    dbl=None,               # optional engine.doubling.DoublingLayout
    quant: bool = False,    # int8 message quantization (engine.quantized)
):
    """Propagation given precomputed evidence vectors (lets the fused
    Pallas noisy-OR feed the same core).

    ``up_ell`` is the hybrid layout's upstream table (see
    :func:`rca_tpu.engine.ell.build_ell_segments`): dependencies-per-service
    grouped into a narrow [S, D] gather table.  Services depend on FEW
    things (D is 3-8 in practice) while hubs are depended on by THOUSANDS,
    so the up-scan turns into dense gathers + a row max — measured 2.4x
    faster per step than the COO scatter-max on v5e, and bit-identical
    because fp32 max is order-invariant — while the down-scan keeps the COO
    scatter-add (a width-capped table there measured 4x slower).  Overflow
    edges (dependents past the width cap) go through one small scatter-max.
    """

    if dbl is not None:
        # log-depth operator doubling (engine.doubling): the whole
        # serial ladder collapses into base + log2(steps) frontier
        # applications — no lax.scan, no per-step round trips
        from rca_tpu.engine.doubling import doubling_down, doubling_up

        u = doubling_up(h, decay, dbl)
        a_ex = background_excess(a, n_live)
        deg = jnp.zeros_like(a).at[dep_dst].add(1.0)
        inv_deg = 1.0 / jnp.maximum(deg, 1.0)
        m = doubling_down(a_ex, decay, dbl, inv_deg)
        score = combine_score(a, h, u, m, explain_strength, impact_bonus)
        return a, h, u, m, score

    if quant:
        # int8 per-row message quantization on the E-sized gather
        # traffic (engine.quantized): rank-parity-gated, not bitwise
        from rca_tpu.engine.quantized import quant_up_step

        def up_step(u, _):
            return quant_up_step(u, h, decay, dep_src, dep_dst), None
    elif up_seg is not None:
        # Pallas segmented-MAX layout (engine.segscan): one E-gather per
        # step vs the ELL table's [S, 8] gathers; bit-identical (fp32 max
        # is order-invariant)
        from rca_tpu.engine.segscan import up_seg_step as _up_seg_step

        def up_step(u, _):
            return _up_seg_step(u, h, decay, up_seg), None
    elif up_ell is not None:
        from rca_tpu.engine.ell import ell_up_step

        def up_step(u, _):
            up_idx, up_mask, up_ovf_seg, up_ovf_other = up_ell
            return ell_up_step(
                u, h, decay, up_idx, up_mask, up_ovf_seg, up_ovf_other
            ), None
    else:

        def up_step(u, _):
            vals = jnp.maximum(h[dep_dst], decay * u[dep_dst])
            u_new = jnp.zeros_like(u).at[dep_src].max(vals)
            return jnp.maximum(u, u_new), None

    u, _ = jax.lax.scan(up_step, jnp.zeros_like(a), None, length=steps)

    a_ex = background_excess(a, n_live)

    # dependent count per service for the impact MEAN (padded edges point
    # at the dummy slot, so live degrees come from real edges only)
    deg = jnp.zeros_like(a).at[dep_dst].add(1.0)
    inv_deg = 1.0 / jnp.maximum(deg, 1.0)

    if quant:
        from rca_tpu.engine.quantized import quant_imp_step

        def imp_step(m, _):
            return quant_imp_step(
                m, a_ex, decay, dep_src, dep_dst, inv_deg
            ), None
    elif down_seg is not None:
        # Pallas segmented-scan layout (engine.segscan): replaces the
        # per-edge-serialized scatter at large tiers — 12.5 -> 8.4 ms for
        # the 8-step chain at 50k on v5e
        from rca_tpu.engine.segscan import down_seg_step

        def imp_step(m, _):
            return down_seg_step(m, a_ex, decay, down_seg, inv_deg), None
    else:

        def imp_step(m, _):
            vals = a_ex[dep_src] + decay * m[dep_src]
            return jnp.zeros_like(m).at[dep_dst].add(vals) * inv_deg, None

    m, _ = jax.lax.scan(imp_step, jnp.zeros_like(a), None, length=steps)

    score = combine_score(a, h, u, m, explain_strength, impact_bonus)
    return a, h, u, m, score


@functools.partial(
    jax.jit, static_argnames=("steps", "decay", "explain_strength", "impact_bonus")
)
def propagate_jit(
    features, dep_src, dep_dst, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
):
    return propagate(
        features, dep_src, dep_dst, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus,
    )


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_scores(score: jnp.ndarray, k: int):
    return jax.lax.top_k(score, k)
