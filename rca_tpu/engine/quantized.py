"""Quantized propagation: bf16 evidence + int8 messages on the edge traffic.

ROADMAP item 4(c), PAPERS.md [5] (GNN acceleration survey): once the
combine kernels are tuned, large-graph message passing is bound by the
E-sized gather/scatter traffic — at the 50k tier the propagation chain
moves ~8 x E float32 message elements per direction through HBM (PERF.md
edge-layout study attributes ~6 ms of the 12.5 ms 8-step chain to the
gather alone).  The survey's low-precision message trick applies
directly: the per-step message vectors are smooth, bounded quantities
(``max(h, y*u)`` in [0, 1/(1-y)], ``a_ex + y*m`` likewise), so they
survive 8-bit quantization with rank-stable scores.

This kernel cuts the traffic two ways:

- **bf16 evidence**: the [S, C] noisy-OR evidence passes run on a
  bfloat16 cast of the feature matrix (same expression as
  ``propagate._noisy_or``, upcast to f32 after the product) — halves the
  feature-read bytes of the two evidence passes;
- **per-row int8 messages**: each propagation step quantizes the dense
  [S] per-node signal to int8 with one float32 scale per 128-lane row
  (``QUANT_ROW``), then the E-sized gather reads the int8 vector — 1
  byte per gathered element instead of 4 — and dequantizes with the
  row scale gathered from the 128x-smaller scale vector (which stays
  cache/VMEM-resident).  Accumulation (scatter-add / scatter-max) stays
  float32, so error does not compound through the reduction.

Parity contract: RANK parity, not bit parity (ISSUE 13 tentpole).  An
int8 message lane carries ~2 decimal digits; scores move in the 4th
decimal, which is invisible to hit@k but fatal to a bitwise replay gate.
The gates this kernel ships under are therefore hit@1/hit@3 equality
plus a Kendall-tau floor on the top-k order vs the f32 path
(:func:`rank_parity`), wired into bench ``accuracy_by_mode``, the chaos
soak, and a dedicated corpus replay leg — see tests/test_kernels.py.

Interpret/hermetic path: the kernel is pure jax.numpy (quantize /
gather / dequantize lower on every backend), so CPU-host tests exercise
EXACTLY the math the TPU runs — no interpreter shim needed; forcing is
``RCA_KERNEL=quantized`` (the unified knob, see engine/registry.py).
"""

from __future__ import annotations

import jax.numpy as jnp

#: per-row quantization granularity: one f32 scale per 128 message lanes
#: (a TPU vector register row).  Shapes are power-of-two buckets, so any
#: ``n_pad`` divides into ``min(n_pad, QUANT_ROW)`` rows exactly.
QUANT_ROW = 128


def quant_row(n_pad: int) -> int:
    """The effective row width for an ``n_pad``-padded vector."""
    return min(QUANT_ROW, int(n_pad))


def quantize_rows(x: jnp.ndarray):
    """Per-row symmetric int8 quantization of a dense [S] f32 vector:
    returns ``(q int8 [S], scale f32 [S // row])``.  An all-zero row
    keeps scale 1.0 so the dequant is exact-zero, never 0/0."""
    row = quant_row(x.shape[0])
    r = x.reshape(-1, row)
    amax = jnp.max(jnp.abs(r), axis=1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(r / scale[:, None]).astype(jnp.int8)
    return q.reshape(-1), scale


def dequant_gather(q: jnp.ndarray, scale: jnp.ndarray,
                   idx: jnp.ndarray) -> jnp.ndarray:
    """Gather ``x[idx]`` through the quantized representation: an int8
    gather (1 byte/element of E-sized traffic) plus a row-scale gather
    from the [S/128] scale vector."""
    row = quant_row(q.shape[0])
    return q[idx].astype(jnp.float32) * scale[idx // row]


def quant_up_step(u, h, decay: float, dep_src, dep_dst):
    """One explain-away step with the per-node signal ``max(h, y*u)``
    quantized before the E-sized gather.  Accumulation is the same f32
    scatter-max as the COO path."""
    q, scale = quantize_rows(jnp.maximum(h, decay * u))
    vals = dequant_gather(q, scale, dep_dst)
    u_new = jnp.zeros_like(u).at[dep_src].max(vals)
    return jnp.maximum(u, u_new)


def quant_imp_step(m, a_ex, decay: float, dep_src, dep_dst, inv_deg):
    """One impact step with ``a_ex + y*m`` quantized before the gather;
    the scatter-add and degree normalization stay f32."""
    q, scale = quantize_rows(a_ex + decay * m)
    vals = dequant_gather(q, scale, dep_src)
    return jnp.zeros_like(m).at[dep_dst].add(vals) * inv_deg


def noisy_or_pair_bf16(features, anomaly_w, hard_w):
    """The evidence pair over a bfloat16 cast of the feature matrix —
    same expression as ``propagate._noisy_or``, half the feature-read
    bytes, f32 out."""
    f = jnp.clip(features.astype(jnp.bfloat16), 0.0, 1.0)
    a = 1.0 - jnp.prod(1.0 - f * anomaly_w.astype(jnp.bfloat16)[None, :],
                       axis=1)
    h = 1.0 - jnp.prod(1.0 - f * hard_w.astype(jnp.bfloat16)[None, :],
                       axis=1)
    return a.astype(jnp.float32), h.astype(jnp.float32)


# -- the rank-parity gate (first-class gate mode, ISSUE 13) -------------------

def kendall_tau(order_a, order_b) -> float:
    """Kendall rank correlation between two orderings of the same item
    set (1.0 = identical order, -1.0 = reversed).  Host-side, O(k^2) on
    top-k-sized lists — the gate compares rankings, not score arrays."""
    items = [x for x in order_a if x in set(order_b)]
    k = len(items)
    if k < 2:
        return 1.0
    pos_b = {x: i for i, x in enumerate(order_b)}
    concordant = discordant = 0
    for i in range(k):
        for j in range(i + 1, k):
            d = pos_b[items[i]] - pos_b[items[j]]
            if d < 0:
                concordant += 1
            elif d > 0:
                discordant += 1
    return (concordant - discordant) / (k * (k - 1) / 2)


#: the score precision the kernel PROMISES: the symmetric int8 step is
#: ~1/254 of the per-row signal max per quantize, and the 8-step scans
#: accumulate it geometrically (sum decay^t ~ 1/(1-y) = 3.3x), so score
#: perturbations up to ~1e-2 are within spec (measured ~4e-3 typical).
#: Pairs the f32 path separates by LESS than this carry no rank signal.
SCORE_EPS = 1e-2


def topk_score_tau(scores_ref, scores_got, k: int = 25,
                   tie_eps: float = SCORE_EPS) -> float:
    """Tie-aware Kendall tau over the top-k of the REFERENCE score
    vector: pairs whose reference scores differ by <= ``tie_eps``
    (:data:`SCORE_EPS` — the kernel's documented score precision) are
    excluded; the deep tail of a cascade ranking is exactly such
    near-ties.  Pairs the f32 path DOES separate beyond the promised
    precision must keep their order: those count, and the bench/test
    gates hold this tau at >= 0.99."""
    import numpy as np

    ref = np.asarray(scores_ref, np.float64)
    got = np.asarray(scores_got, np.float64)
    top = np.argsort(-ref)[:k]
    concordant = discordant = 0
    for a in range(len(top)):
        for b in range(a + 1, len(top)):
            i, j = int(top[a]), int(top[b])
            if ref[i] - ref[j] <= tie_eps:
                continue
            if got[i] > got[j]:
                concordant += 1
            elif got[i] < got[j]:
                discordant += 1
    total = concordant + discordant
    return 1.0 if total == 0 else (concordant - discordant) / total


def rank_parity(ranked_ref, ranked_got, k: int = 3,
                tau_floor: float = 0.99) -> dict:
    """The quantized kernel's landing gate: hit@1 and hit@k equality
    (same leaders, as SETS for k>1 — order within the tail is judged by
    tau) plus a Kendall-tau floor over the common top-k.  ``ranked_*``
    are ranked dicts (``[{"component": ..., ...}]``) or plain name
    lists."""
    def names(r):
        return [x["component"] if isinstance(x, dict) else x for x in r]

    ref, got = names(ranked_ref), names(ranked_got)
    hit1 = bool(ref[:1] == got[:1])
    hitk = bool(set(ref[:k]) == set(got[:k]))
    tau = kendall_tau(ref, got)
    return {
        "hit1_equal": hit1,
        f"hit{k}_equal": hitk,
        "kendall_tau": round(float(tau), 4),
        "ok": hit1 and hitk and tau >= tau_floor,
    }
