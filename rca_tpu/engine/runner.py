"""GraphEngine: bucketing, device transfer, compile caching, ranking.

The host-side wrapper around :mod:`rca_tpu.engine.propagate`: pads node/edge
arrays to shape buckets (so jit compiles once per tier, not per graph —
recompilation control per SURVEY.md §7 "hard parts"), keeps arrays on device,
and renders ranked root causes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.propagate import (
    PropagationParams,
    default_params,
    propagate_jit,
    top_k_scores,
)
from rca_tpu.features.extract import FeatureSet, extract_features
from rca_tpu.graph.build import service_dependency_edges


@dataclasses.dataclass
class EngineResult:
    service_names: List[str]
    ranked: List[dict]            # [{component, score, anomaly, ...}] desc
    anomaly: np.ndarray           # [S]
    upstream: np.ndarray          # [S]
    impact: np.ndarray            # [S]
    score: np.ndarray             # [S]
    latency_ms: float             # device compute wall time (post-compile)
    n_services: int
    n_edges: int

    def top_components(self, k: Optional[int] = None) -> List[str]:
        items = self.ranked if k is None else self.ranked[:k]
        return [r["component"] for r in items]


class GraphEngine:
    """Bucketed, compile-cached causal propagation."""

    def __init__(
        self,
        config: Optional[RCAConfig] = None,
        params: Optional[PropagationParams] = None,
    ):
        self.config = config or RCAConfig()
        self.params = params or default_params(self.config.propagation_steps)
        self._aw, self._hw = self.params.weight_arrays()

    # -- shaping -----------------------------------------------------------
    def _pad(self, features: np.ndarray, src: np.ndarray, dst: np.ndarray):
        n = features.shape[0]
        # reserve one dummy slot so padded edges can self-loop harmlessly
        n_pad = bucket_for(n + 1, self.config.shape_buckets)
        e_pad = bucket_for(max(len(src), 1), self.config.shape_buckets)
        dummy = n_pad - 1
        f = np.zeros((n_pad, features.shape[1]), dtype=np.float32)
        f[:n] = features
        s = np.full(e_pad, dummy, dtype=np.int32)
        d = np.full(e_pad, dummy, dtype=np.int32)
        s[: len(src)] = src
        d[: len(dst)] = dst
        return f, s, d

    # -- core --------------------------------------------------------------
    def analyze_arrays(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        k: Optional[int] = None,
        timed: bool = False,
    ) -> EngineResult:
        n = features.shape[0]
        k = k or min(self.config.top_k_root_causes, n)
        f, s, d = self._pad(features, dep_src, dep_dst)
        fj, sj, dj = jnp.asarray(f), jnp.asarray(s), jnp.asarray(d)
        p = self.params

        def run():
            a, h, u, m, score = propagate_jit(
                fj, sj, dj, self._aw, self._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
            )
            vals, idx = top_k_scores(score, min(k + 8, f.shape[0]))
            return a, u, m, score, vals, idx

        if timed:
            run()[3].block_until_ready()  # warm the compile cache
            reps = []
            for _ in range(10):
                t0 = time.perf_counter()
                a, u, m, score, vals, idx = run()
                idx.block_until_ready()
                reps.append((time.perf_counter() - t0) * 1e3)
            latency_ms = float(np.median(reps))
        else:
            t0 = time.perf_counter()
            a, u, m, score, vals, idx = run()
            idx.block_until_ready()
            latency_ms = (time.perf_counter() - t0) * 1e3

        a, u, m, score = (np.asarray(x)[:n] for x in (a, u, m, score))
        idx = np.asarray(idx)
        vals = np.asarray(vals)
        names = list(names) if names is not None else [f"svc-{i}" for i in range(n)]
        ranked = []
        for j, i in enumerate(idx.tolist()):
            if i >= n or len(ranked) >= k:
                continue
            ranked.append(
                {
                    "component": names[i],
                    "score": float(vals[j]),
                    "anomaly": float(a[i]),
                    "explained_by_upstream": float(u[i]),
                    "downstream_impact": float(m[i]),
                }
            )
        return EngineResult(
            service_names=names,
            ranked=ranked,
            anomaly=a,
            upstream=u,
            impact=m,
            score=score,
            latency_ms=latency_ms,
            n_services=n,
            n_edges=int(len(dep_src)),
        )

    # -- convenience entry points ------------------------------------------
    def analyze_case(self, case, k: Optional[int] = None, timed: bool = False):
        """Analyze a :class:`rca_tpu.cluster.generator.CascadeArrays`."""
        return self.analyze_arrays(
            case.features, case.dep_src, case.dep_dst, case.names, k=k, timed=timed
        )

    def analyze_snapshot(self, snapshot, k: Optional[int] = None) -> EngineResult:
        fs = extract_features(snapshot)
        src, dst = service_dependency_edges(snapshot, fs)
        return self.analyze_features(fs, src, dst, k=k)

    def analyze_features(
        self, fs: FeatureSet, src: np.ndarray, dst: np.ndarray,
        k: Optional[int] = None,
    ) -> EngineResult:
        return self.analyze_arrays(
            fs.service_features, src, dst, fs.service_names, k=k
        )
