"""GraphEngine: bucketing, device transfer, compile caching, ranking.

The host-side wrapper around :mod:`rca_tpu.engine.propagate`: pads node/edge
arrays to shape buckets (so jit compiles once per tier, not per graph —
recompilation control per SURVEY.md §7 "hard parts"), keeps arrays on device,
and renders ranked root causes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, NamedTuple, Optional, Sequence

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for, env_raw, env_str
from rca_tpu.engine.ell import EllGraph, propagate_ell
from rca_tpu.engine.propagate import (
    PropagationParams,
    default_params,
    propagate,
)

UP_WIDTH_CAP = 8  # dependencies per service are few; hub FAN-IN is not


def finite_mask_rows_np(features: np.ndarray):
    """Host-side twin of :func:`rca_tpu.engine.propagate.finite_mask_rows`
    for paths whose features are staged from host anyway (the sharded
    engine's pre-upload pad, the sharded streaming session's delta rows):
    zero non-finite rows in a COPY, return (clean, n_bad).  Same zeroing
    semantics as the fused on-device pass so dense/sharded score parity
    holds under poisoned input too."""
    features = np.asarray(features, np.float32)
    ok = np.all(np.isfinite(features), axis=-1)
    if ok.all():
        return features, 0
    clean = features.copy()
    clean[~ok] = 0.0
    return clean, int(np.sum(~ok))


def build_up_ell(n_pad: int, dep_src, dep_dst):
    """Device arrays for the hybrid layout's upstream gather table:
    (idx, mask, ovf_seg, ovf_other), grouping each service's dependencies
    (edges src→dst keyed by src) into an [n_pad, 8] table.

    Contract: slot ``n_pad - 1`` is the engine's dummy row — callers pass
    the RAW (unpadded) edge arrays and an n_pad that reserves it (asserted),
    because the propagation step zeroes that slot each iteration.

    Shapes are STABLE per (n_pad, overflow-tier): the width is always
    ``UP_WIDTH_CAP`` (not the graph's max out-degree) and the overflow
    length is a power-of-two tier with a floor of 8 — otherwise a degree
    change inside the same node bucket would force a full XLA recompile in
    the latency path (the same reason ``n_live`` is a traced scalar)."""
    from rca_tpu.engine.ell import build_ell_segments

    src = np.asarray(dep_src)
    dst = np.asarray(dep_dst)
    if len(src) and (int(src.max()) >= n_pad - 1 or int(dst.max()) >= n_pad - 1):
        # ValueError, not assert: under `python -O` an assert vanishes and
        # an edge on the dummy slot silently corrupts the up-scan (the step
        # zeroes that slot every iteration)
        raise ValueError(
            "build_up_ell needs slot n_pad-1 free as the dummy row; pass "
            "raw edges with n_pad = bucket(n_services + 1)"
        )
    seg = build_ell_segments(src, dst, n_pad, width_cap=UP_WIDTH_CAP)
    dummy = n_pad - 1
    idx = np.full((n_pad, UP_WIDTH_CAP), dummy, np.int32)
    mask = np.zeros((n_pad, UP_WIDTH_CAP), np.float32)
    idx[:, : seg.idx.shape[1]] = seg.idx
    mask[:, : seg.mask.shape[1]] = seg.mask
    # explicit pow2 round-up: build_ell_segments pads to pow2 today, but
    # this table's shape stability must not hang on that producer — a
    # drifted overflow length here is a per-graph recompile, not an error
    o_pad = max(8, 1 << max(0, (len(seg.ovf_seg) - 1).bit_length()))
    ovf_seg = np.full(o_pad, dummy, np.int32)
    ovf_other = np.full(o_pad, dummy, np.int32)
    ovf_seg[: len(seg.ovf_seg)] = seg.ovf_seg
    ovf_other[: len(seg.ovf_other)] = seg.ovf_other
    return (
        jnp.asarray(idx), jnp.asarray(mask),
        jnp.asarray(ovf_seg), jnp.asarray(ovf_other),
    )


def up_ell_for(n_pad: int, dep_src, dep_dst):
    """The one place the layout flag gates the upstream table: returns the
    hybrid layout's table, or None when ``RCA_EDGE_LAYOUT`` selects a pure
    layout (callers pass the result straight to ``propagate``)."""
    if edge_layout() != "hybrid":
        return None
    return build_up_ell(n_pad, dep_src, dep_dst)


class KernelPlan(NamedTuple):
    """One shape's resolved dispatch: the engaged kernel plus the device
    layouts it runs over — what every staging surface pins per graph."""

    kernel: str                   # the engaged KERNELS member
    down_seg: object = None       # engine.segscan.SegLayout
    up_seg: object = None         # engine.segscan.SegLayout
    up_ell: object = None         # hybrid up-table (idx, mask, ovf, ovf)
    dbl: object = None            # engine.doubling.DoublingLayout


def kernel_plan(n_pad: int, e_pad: int, dep_src, dep_dst,
                steps: int = 8) -> KernelPlan:
    """THE per-graph dispatch step, shared by every caller that stages a
    padded graph (one-shot analyze, hypothesis batch, streaming session,
    resident session, serving dispatcher): ask the registry which kernel
    this ``(n_pad, e_pad)`` shape engages (ISSUE 13 — segscan's old
    ``RCA_SEGSCAN`` side gate, the quantized and doubling gates, the
    forcing knobs, and the per-shape timings all live THERE), then build
    that kernel's layouts.  One definition so a layout-gating change
    cannot land in one caller and silently break the cross-path score
    parity.

    The doubling kernel may decline a specific GRAPH (frontier blowup on
    hub-heavy topologies — engine/doubling.py cost model) even when the
    shape row elected it; the plan then falls back to the serial xla
    path and says so via ``plan.kernel``, so the stamped kernel is
    always the one that actually ran."""
    from rca_tpu.engine.registry import engaged_kernel

    kernel = engaged_kernel(n_pad, e_pad, steps=steps)
    down_seg = up_seg = up_ell = dbl = None
    if kernel == "segscan":
        from rca_tpu.engine.segscan import build_seg_layouts

        down_seg, up_seg = build_seg_layouts(n_pad, e_pad, dep_src, dep_dst)
    elif kernel == "doubling":
        from rca_tpu.engine.doubling import doubling_layouts_for

        dbl = doubling_layouts_for(n_pad, e_pad, dep_src, dep_dst, steps)
        if dbl is None:
            kernel = "xla"
    if kernel in ("xla", "pallas"):
        # the hybrid up-table serves the serial scans (quantized brings
        # its own int8 gather steps; segscan/doubling their layouts)
        up_ell = up_ell_for(n_pad, dep_src, dep_dst)
    return KernelPlan(kernel, down_seg, up_seg, up_ell, dbl)


def coo_layouts_for(n_pad: int, e_pad: int, dep_src, dep_dst):
    """Back-compat shim over :func:`kernel_plan` for callers that only
    want the serial-scan layouts: ``(down_seg, up_seg, up_ell)``."""
    plan = kernel_plan(n_pad, e_pad, dep_src, dep_dst)
    return plan.down_seg, plan.up_seg, plan.up_ell


def batch_kernel(kernel: str) -> str:
    """The batched (vmapped) executables' kernel for a shape whose solo
    winner is ``kernel``: the fused Pallas evidence pair keeps no vmap
    twin (the batch path has always run XLA's fusion — the any-width ==
    solo parity contract in SERVING.md predates it); every other kernel
    vmaps as-is."""
    return "xla" if kernel == "pallas" else kernel


def edge_layout() -> str:
    """Edge layout for the propagation scans, ``RCA_EDGE_LAYOUT``:

    - ``hybrid`` (default): up-scan over a narrow dependencies-per-service
      gather table, down-scan as COO scatter-add — each direction on the
      primitive that measured fastest for its degree distribution on v5e
      (25-32%% faster end-to-end than pure COO at 10k-50k services,
      bit-identical results);
    - ``coo``: both scans as COO scatter (the round-1 default);
    - ``ell``: both scans over width-capped gather tables + overflow
      (validated alternative for stacks where scatter lowers poorly;
      measured slower on v5e because hub fan-in forces a wide table)."""
    # empty env var conventionally means unset, not an error; a typo'd
    # layout fails loudly inside the choice-validated accessor
    return env_str(
        "RCA_EDGE_LAYOUT", "hybrid", choices=("hybrid", "coo", "ell"),
        lower=True,
    )


def propagate_auto(
    features, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    n_live=None, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0, kernel: str = "xla", dbl=None,
):
    """The shared traced propagation body behind every fused COO-family
    executable (one-shot, streaming flush, resident delta, hypothesis
    lanes): the per-kernel evidence branch lives HERE once, so the
    registry's engaged kernel cannot drift between the call surfaces.
    ``kernel`` is the registry winner (a static string in every jitted
    caller); segscan/doubling additionally arrive as layout pytrees.
    Returns ``(a, h, u, m, score)``."""
    from rca_tpu.engine.propagate import propagate

    if kernel in ("pallas", "quantized"):
        from rca_tpu.engine.propagate import (
            error_source_excess,
            fold_error_contrast,
            propagate_core,
        )

        if kernel == "pallas":
            from rca_tpu.engine.pallas_kernels import noisy_or_pair_pallas

            a, h = noisy_or_pair_pallas(features.T, anomaly_w, hard_w)
        else:
            from rca_tpu.engine.quantized import noisy_or_pair_bf16

            a, h = noisy_or_pair_bf16(features, anomaly_w, hard_w)
        if error_contrast:
            a = fold_error_contrast(
                a, error_source_excess(features, edges[0], edges[1]),
                error_contrast,
            )
        return propagate_core(
            a, h, edges[0], edges[1],
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
            up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
            dbl=dbl, quant=kernel == "quantized",
        )
    return propagate(
        features, edges[0], edges[1], anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        error_contrast=error_contrast, dbl=dbl,
    )


def topk_diag(stacked, idx):
    """On-device gather of the top-k rows of the [4, S] diagnostic stack:
    the ``[4, k]`` slice is everything the ranked rendering needs, so the
    fetch surfaces move THIS instead of the full stack (ISSUE 6: per-
    request fetch bytes are O(k), not O(n_pad))."""
    return stacked[:, idx]


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "kernel", "error_contrast",
    ),
)
def _propagate_ranked(
    features, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, kernel: str = "xla", n_live=None, up_ell=None,
    down_seg=None, up_seg=None, dbl=None, error_contrast: float = 0.0,
):
    """One dispatch, minimal transfers: edges arrive as one [2, E] buffer;
    the top-k pair leaves with a [4, k] gather of their diagnostic rows —
    the full stacked [4, S] buffer STAYS on device (fetched lazily only if
    a diagnostics consumer asks).  Matters on tunneled TPUs where every
    host<->device hop pays an RTT and transfer scales with bytes.

    ``kernel`` is the registry's engaged kernel for this shape (static):
    ``pallas`` runs the evidence passes as the fused Pallas kernel over
    the channel-major transpose, ``quantized`` runs bf16 evidence +
    int8-message scans, ``segscan``/``doubling`` arrive as layout
    pytrees; the propagation core is shared in every case.

    The finite-mask sanitize runs first, fused into this same dispatch:
    NaN/Inf rows (poisoned telemetry) zero out on device and the count
    rides back with the top-k fetch — no extra host sync, bit-identical
    pass-through on clean input."""
    from rca_tpu.engine.propagate import finite_mask_rows

    features, n_bad = finite_mask_rows(features)
    a, h, u, m, score = propagate_auto(
        features, edges, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        error_contrast=error_contrast, kernel=kernel, dbl=dbl,
    )
    vals, idx = jax.lax.top_k(score, k)
    stacked = jnp.stack([a, u, m, score])
    return stacked, topk_diag(stacked, idx), vals, idx, n_bad


def _ranked_lanes(
    features_b, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live, up_ell, down_seg, up_seg, error_contrast: float,
    kernel: str = "xla", dbl=None,
):
    """The traced per-lane body shared by the full and delta batched
    executables: vmap of the propagation + per-hypothesis top-k + the
    [4, k] diagnostic gather.  One definition so the serving dispatcher's
    delta path cannot drift from the full-staging executable it must stay
    bit-identical to."""

    def one(f):
        a, h, u, m, score = propagate_auto(
            f, edges, anomaly_w, hard_w,
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
            up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
            error_contrast=error_contrast, kernel=kernel, dbl=dbl,
        )
        vals, idx = jax.lax.top_k(score, k)
        stacked = jnp.stack([a, u, m, score])
        return stacked, topk_diag(stacked, idx), vals, idx

    return jax.vmap(one)(features_b)


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast", "kernel",
    ),
)
def _propagate_ranked_batch(
    features_b, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live=None, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0, kernel: str = "xla", dbl=None,
):
    """Hypothesis batch over ONE graph in ONE dispatch: vmap of the
    propagation + per-hypothesis top-k (BASELINE.json "pmap over fault
    candidates" — on a single device the batch rides vmap lanes; the
    sharded engine's dp axis covers multi-device batches)."""
    from rca_tpu.engine.propagate import finite_mask_rows

    features_b, n_bad = finite_mask_rows(features_b)
    stacked, diag, vals, idx = _ranked_lanes(
        features_b, edges, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, k,
        n_live, up_ell, down_seg, up_seg, error_contrast,
        kernel=kernel, dbl=dbl,
    )
    return stacked, diag, vals, idx, n_bad


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast", "kernel",
    ),
)
def _propagate_ranked_batch_delta(
    base, idx_b, rows_b, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live=None, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0, kernel: str = "xla", dbl=None,
):
    """Delta-staged hypothesis batch (ISSUE 6): each lane is the resident
    base feature buffer plus that request's changed rows, scattered on
    device — host→device traffic is the [B, U] index block and the
    [B, U, C] row block instead of the full [B, n_pad, C] stack.  ``base``
    is NOT donated (it serves every lane and the next dispatch).  Pad
    slots aim at the dummy row with zero rows, matching the zeros already
    there; the propagation body is the same `_ranked_lanes` as the full
    executable, so lane results are bit-identical to full staging."""
    from rca_tpu.engine.propagate import finite_mask_rows

    features_b = jax.vmap(lambda i, r: base.at[i].set(r))(idx_b, rows_b)
    features_b, n_bad = finite_mask_rows(features_b)
    stacked, diag, vals, idx = _ranked_lanes(
        features_b, edges, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, k,
        n_live, up_ell, down_seg, up_seg, error_contrast,
        kernel=kernel, dbl=dbl,
    )
    return stacked, diag, vals, idx, n_bad


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast",
    ),
)
def _propagate_ranked_ell(
    features, up_idx, up_mask, up_ovf, dn_idx, dn_mask, dn_ovf,
    anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live=None, error_contrast: float = 0.0,
):
    from rca_tpu.engine.propagate import finite_mask_rows

    features, n_bad = finite_mask_rows(features)
    a, h, u, m, score = propagate_ell(
        features, up_idx, up_mask, up_ovf[0], up_ovf[1],
        dn_idx, dn_mask, dn_ovf[0], dn_ovf[1],
        anomaly_w, hard_w, steps, decay, explain_strength, impact_bonus,
        n_live=n_live, error_contrast=error_contrast,
    )
    vals, idx = jax.lax.top_k(score, k)
    stacked = jnp.stack([a, u, m, score])
    return stacked, topk_diag(stacked, idx), vals, idx, n_bad
from rca_tpu.features.extract import FeatureSet, extract_features
from rca_tpu.graph.build import service_dependency_edges


class EngineResult:
    """One analysis result.  The ranked findings (top-k components with
    their diagnostic channels) are rendered eagerly from the [4, k] fetch;
    the FULL per-service vectors (``anomaly``/``upstream``/``impact``/
    ``score``) are LAZY — the analyze/serve hot path never moves the
    [4, n_pad] stack off device (ISSUE 6: fetch bytes are O(k)), and a
    diagnostics consumer's first attribute access triggers one deferred
    bulk fetch (``tools``, accuracy sweeps, tests — all off the latency
    path; the resident-fetch lint allowlists exactly this seam)."""

    def __init__(
        self,
        service_names: List[str],
        ranked: List[dict],           # [{component, score, anomaly, ...}]
        latency_ms: float,            # device compute wall (post-compile)
        n_services: int,
        n_edges: int,
        engine: str = "single",       # which engine ran: single|sharded(...)
        sanitized_rows: int = 0,      # finite-mask-zeroed rows (0 = clean)
        stacked: Optional[np.ndarray] = None,   # host [4, >=n], eager form
        stacked_dev: object = None,   # device [4, n_pad], deferred form
        attribution_ctx: object = None,  # lazy causelens inputs (ISSUE 14)
    ):
        self.service_names = service_names
        self.ranked = ranked
        self.latency_ms = latency_ms
        self.n_services = n_services
        self.n_edges = n_edges
        self.engine = engine
        self.sanitized_rows = int(sanitized_rows)
        self._stacked = stacked
        self._stacked_dev = stacked_dev
        # causelens (ISSUE 14): the raw inputs + resolved params this
        # result was computed from, retained so attribution() can run
        # lazily — like full_diagnostics(), strictly off the hot path
        self._attribution_ctx = attribution_ctx
        self._provenance: Optional[dict] = None

    def full_diagnostics(self) -> np.ndarray:
        """The [4, n] host diagnostic stack (a, u, m, score), fetching the
        device-parked stack on first use — THE deferred bulk fetch, off
        the hot path by construction."""
        if self._stacked is None:
            if self._stacked_dev is None:
                raise ValueError(
                    "EngineResult carries no diagnostic stack (degraded "
                    "render?)"
                )
            self._stacked = np.asarray(jax.device_get(self._stacked_dev))
            self._stacked_dev = None
        return self._stacked

    @property
    def anomaly(self) -> np.ndarray:       # [S]
        return np.asarray(self.full_diagnostics()[0][: self.n_services])

    @property
    def upstream(self) -> np.ndarray:      # [S]
        return np.asarray(self.full_diagnostics()[1][: self.n_services])

    @property
    def impact(self) -> np.ndarray:        # [S]
        return np.asarray(self.full_diagnostics()[2][: self.n_services])

    @property
    def score(self) -> np.ndarray:         # [S]
        return np.asarray(self.full_diagnostics()[3][: self.n_services])

    def attribution(self, paths: Optional[int] = None,
                    topm: Optional[int] = None) -> dict:
        """The causelens provenance block for THIS ranking (ISSUE 14):
        per-channel evidence contributions, counterfactual evidence
        rows, blame paths, and gradient saliency for every ranked
        candidate — lazy like :meth:`full_diagnostics` (one extra
        fused dispatch on first call, cached after; never on the
        analyze hot path).  Raises ``ValueError`` on results whose
        producer retained no attribution context (degraded renders)."""
        default_args = paths is None and topm is None
        if self._provenance is not None and default_args:
            return self._provenance
        if self._attribution_ctx is None:
            raise ValueError(
                "EngineResult carries no attribution context (degraded "
                "render, or a producer predating causelens)"
            )
        from rca_tpu.engine.attribution import compute_attribution
        from rca_tpu.observability.causelens import provenance_block

        block = compute_attribution(
            self._attribution_ctx, self.ranked, paths=paths, topm=topm,
        )
        out = provenance_block(block, engine=self.engine)
        if default_args:
            self._provenance = out
        return out

    def top_components(self, k: Optional[int] = None) -> List[str]:
        items = self.ranked if k is None else self.ranked[:k]
        return [r["component"] for r in items]


def render_result(
    diag: np.ndarray,             # [4, kk] host: a, u, m, score AT idx
    vals: np.ndarray,             # [kk] top-k values (may include pad slots)
    idx: np.ndarray,              # [kk] top-k indices
    names: Optional[Sequence[str]],
    n: int,
    k: int,
    latency_ms: float,
    n_edges: int,
    engine: str,
    sanitized_rows: int = 0,
    stacked_dev: object = None,   # device [4, n_pad] for lazy diagnostics
    attribution_ctx: object = None,  # lazy causelens inputs (ISSUE 14)
) -> EngineResult:
    """Shared host-side rendering: identical findings regardless of which
    engine (single-device or sharded) produced the device arrays.  Takes
    the [4, kk] top-k diagnostic gather, NOT the full stack — the full
    stack stays on device behind ``stacked_dev`` and only a diagnostics
    consumer's lazy access moves it."""
    diag = np.asarray(diag)
    names = list(names) if names is not None else [f"svc-{i}" for i in range(n)]
    ranked = []
    for j, i in enumerate(np.asarray(idx).tolist()):
        if i >= n or len(ranked) >= k:
            continue
        ranked.append(
            {
                "component": names[i],
                "score": float(vals[j]),
                "anomaly": float(diag[0, j]),
                "explained_by_upstream": float(diag[1, j]),
                "downstream_impact": float(diag[2, j]),
            }
        )
    return EngineResult(
        service_names=names,
        ranked=ranked,
        latency_ms=latency_ms,
        n_services=n,
        n_edges=n_edges,
        engine=engine,
        sanitized_rows=int(sanitized_rows),
        stacked_dev=stacked_dev,
        attribution_ctx=attribution_ctx,
    )


def make_attribution_ctx(features, dep_src, dep_dst, params, names,
                         shape_buckets=None):
    """The one constructor every render surface uses to retain causelens
    inputs (ISSUE 14) — a thin wrapper so the engines do not each import
    the attribution module at staging time."""
    from rca_tpu.engine.attribution import AttributionContext

    kwargs = {}
    if shape_buckets is not None:
        kwargs["shape_buckets"] = tuple(shape_buckets)
    return AttributionContext(
        features=np.asarray(features, np.float32),
        dep_src=np.asarray(dep_src, np.int32),
        dep_dst=np.asarray(dep_dst, np.int32),
        params=params,
        names=list(names) if names is not None else None,
        **kwargs,
    )


def resolve_params(
    config: RCAConfig, params: Optional[PropagationParams]
) -> PropagationParams:
    """Shared weight resolution for BOTH engines (single-device and
    sharded): explicit params > ``RCA_WEIGHTS`` checkpoint > the PACKAGED
    trained checkpoint > hand-set defaults.  One definition so a
    checkpoint-loading change cannot land in only one engine and silently
    break their score parity.

    The packaged artifact (``engine/default_weights.json``, gate-passing,
    committed with the repo) is the product default (VERDICT r3 item 2 —
    the trained weights beat the hand-set defaults OOD, so the default
    answer should be the stronger one).  ``RCA_WEIGHTS=off`` (also
    ``none``/``defaults``) opts back into the hand-set defaults;
    ``RCA_WEIGHTS=<path>`` loads that checkpoint instead.

    ``config.propagation_steps`` governs the propagation DEPTH in every
    case: steps is a runtime graph-diameter cap, not a fitted weight, so
    a checkpoint must not silently disable the documented config knob
    (its recorded steps value is training metadata)."""
    if params is None:
        ckpt = env_raw("RCA_WEIGHTS")
        if ckpt and ckpt.lower() in ("off", "none", "defaults"):
            return default_params(config.propagation_steps)
        from rca_tpu.engine.train import load_params, packaged_params

        params = load_params(ckpt) if ckpt else packaged_params()
        if params is not None and params.steps != config.propagation_steps:
            params = dataclasses.replace(
                params, steps=config.propagation_steps
            )
    return params or default_params(config.propagation_steps)


def timed_fetch(run, timed: bool, warm=None):
    """Shared fetch-synced execution for BOTH engines: ``run`` returns
    (stacked_diagnostics, topk_diag, topk_vals, topk_idx, sanitized_rows)
    device values (``sanitized_rows`` may be a host int for engines that
    sanitize host-side).  Only the TOP-K-SIZED values ever cross to host
    here — the full stack is returned as a device value for the result's
    lazy diagnostics (ISSUE 6: per-request fetch bytes are O(k)).

    ``warm`` (ISSUE 6 satellite): an AOT compile hook — when provided,
    the timed path warms the executable via ``jit(...).lower().compile()``
    instead of a throwaway dispatch+fetch, so compile warming moves ZERO
    result bytes through the host<->device tunnel.  Engines without an
    AOT form (the sharded shard_map closures) fall back to one untimed
    dispatch fetching only the top-k pair.

    Timing syncs through device_get of the top-k pair, NOT
    block_until_ready: on tunneled backends (axon) block_until_ready
    returns once the dispatch is enqueued, so dispatch-only timing
    under-measures by the whole device execution + fetch RTT.  The fetched
    top-k is tiny — the fetch cost is the tunnel round trip, which a real
    deployment pays per inference anyway."""
    if timed:
        if warm is not None:
            warm()  # AOT lower+compile: no result arrays move
        else:
            jax.device_get(run()[2:])  # warm via one top-k-sized fetch
        reps = []
        for _ in range(10):
            t0 = time.perf_counter()
            stacked, diag, vals, idx, n_bad = run()
            vals, idx = jax.device_get((vals, idx))
            reps.append((time.perf_counter() - t0) * 1e3)
        latency_ms = float(np.median(reps))
        diag, n_bad = jax.device_get((diag, n_bad))
    else:
        t0 = time.perf_counter()
        stacked, diag, vals, idx, n_bad = run()
        diag, vals, idx, n_bad = jax.device_get((diag, vals, idx, n_bad))
        latency_ms = (time.perf_counter() - t0) * 1e3
    return stacked, diag, vals, idx, int(n_bad), latency_ms


class EngineAPI:
    """The shared analyze call surface: every engine implements
    ``analyze_arrays``; these entry points exist ONCE so the two engines
    cannot drift apart (the drop-in contract the analyze boundary and the
    parity gates rely on)."""

    def analyze_arrays(self, features, dep_src, dep_dst, names=None,
                       k=None, timed=False) -> "EngineResult":
        raise NotImplementedError

    def analyze_batch(self, features_batch, dep_src, dep_dst, names=None,
                      k=None) -> List["EngineResult"]:
        """Score a batch of fault-hypothesis feature sets over ONE graph
        in one dispatch (the multi-hypothesis path; VERDICT r3 item 7).
        Default: loop analyze_arrays — engines override with a real
        batched executable."""
        return [
            self.analyze_arrays(f, dep_src, dep_dst, names, k=k)
            for f in features_batch
        ]

    def analyze_case(self, case, k: Optional[int] = None, timed: bool = False):
        """Analyze a :class:`rca_tpu.cluster.generator.CascadeArrays`."""
        return self.analyze_arrays(
            case.features, case.dep_src, case.dep_dst, case.names,
            k=k, timed=timed,
        )

    def analyze_snapshot(self, snapshot, k: Optional[int] = None) -> "EngineResult":
        fs = extract_features(snapshot)
        src, dst = service_dependency_edges(snapshot, fs)
        return self.analyze_features(fs, src, dst, k=k)

    def analyze_features(
        self, fs: "FeatureSet", src: np.ndarray, dst: np.ndarray,
        k: Optional[int] = None,
    ) -> "EngineResult":
        return self.analyze_arrays(
            fs.service_features, src, dst, fs.service_names, k=k
        )


class GraphEngine(EngineAPI):
    """Bucketed, compile-cached causal propagation."""

    def __init__(
        self,
        config: Optional[RCAConfig] = None,
        params: Optional[PropagationParams] = None,
        resident: Optional[bool] = None,
    ):
        # persistent XLA compile cache (RCA_COMPILE_CACHE, idempotent):
        # enabled before the first jit of the session so repeated engine
        # starts skip recompiling the tick executables
        from rca_tpu.config import enable_compile_cache, resident_enabled

        enable_compile_cache()
        self.config = config or RCAConfig()
        self.params = resolve_params(self.config, params)
        self._aw, self._hw = self.params.weight_arrays()
        # device-resident sessions (ISSUE 6): repeat analyze calls over a
        # known graph upload only their changed feature rows into a pinned
        # buffer (donated in-place scatter) instead of restaging the full
        # padded matrix.  ``resident=None`` follows RCA_RESIDENT (default
        # on — results are bit-identical either way, property-tested).
        self._resident_cache = None
        if resident if resident is not None else resident_enabled():
            from rca_tpu.engine.resident import ResidentCache

            self._resident_cache = ResidentCache(self)

    # -- shaping -----------------------------------------------------------
    def _pad(self, features: np.ndarray, src: np.ndarray, dst: np.ndarray):
        n = features.shape[0]
        # reserve one dummy slot so padded edges can self-loop harmlessly
        n_pad = bucket_for(n + 1, self.config.shape_buckets)
        e_pad = bucket_for(max(len(src), 1), self.config.shape_buckets)
        dummy = n_pad - 1
        f = np.zeros((n_pad, features.shape[1]), dtype=np.float32)
        f[:n] = features
        s = np.full(e_pad, dummy, dtype=np.int32)
        d = np.full(e_pad, dummy, dtype=np.int32)
        s[: len(src)] = src
        d[: len(dst)] = dst
        return f, s, d

    # -- core --------------------------------------------------------------
    def analyze_arrays(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        k: Optional[int] = None,
        timed: bool = False,
    ) -> EngineResult:
        n = features.shape[0]
        k = k or min(self.config.top_k_root_causes, n)
        layout = edge_layout()
        # resident fast path (ISSUE 6 tentpole): a repeat request over a
        # known graph digest applies its dirty rows to the device-pinned
        # buffer (donated scatter) and fetches only top-k-sized results —
        # bit-identical to full staging (property-tested).  The timed path
        # keeps the restaged methodology so the headline e2e metric stays
        # comparable across bench rounds; the pure-ELL layout has no fused
        # scatter twin and stays on the staging path.
        if (self._resident_cache is not None and not timed
                and layout != "ell"):
            return self._resident_cache.analyze(
                features, dep_src, dep_dst, names, k,
            )
        f, s, d = self._pad(features, dep_src, dep_dst)
        fj = jnp.asarray(f)
        p = self.params
        kk = min(k + 8, f.shape[0])
        # live-count as a traced scalar: same executable serves every graph
        # size within a shape bucket
        n_live = jnp.asarray(n, jnp.int32)

        if layout == "ell":
            # scatter-free layout for large graphs
            ell = EllGraph.build(f.shape[0], dep_src, dep_dst)
            up_idx = jnp.asarray(ell.up.idx)
            up_mask = jnp.asarray(ell.up.mask)
            up_ovf = jnp.asarray(np.stack([ell.up.ovf_seg, ell.up.ovf_other]))
            dn_idx = jnp.asarray(ell.down.idx)
            dn_mask = jnp.asarray(ell.down.mask)
            dn_ovf = jnp.asarray(
                np.stack([ell.down.ovf_seg, ell.down.ovf_other])
            )

            warm = None

            def run():
                return _propagate_ranked_ell(
                    fj, up_idx, up_mask, up_ovf, dn_idx, dn_mask, dn_ovf,
                    self._aw, self._hw,
                    p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                    n_live, error_contrast=p.error_contrast,
                )
        else:
            ej = jnp.asarray(np.stack([s, d]))  # one [2, E] upload
            # kernel + layouts from the per-shape registry (ISSUE 12/13):
            # the ONE dispatch seam shared with streaming, resident, and
            # serve staging — forcing knobs, the autotune, and every
            # eligibility gate (segscan's old side gate included) live
            # there
            plan = kernel_plan(
                f.shape[0], len(s), dep_src, dep_dst, steps=p.steps
            )
            up_ell, down_seg, up_seg = plan.up_ell, plan.down_seg, plan.up_seg

            # AOT compile warming (ISSUE 6 satellite): the timed path's
            # old warmup dispatched the executable and fetched its results
            # — dragging full arrays through the ~90 ms tunnel just to
            # populate a cache.  lower().compile() builds the executable
            # without dispatching; the timed reps then invoke the compiled
            # object directly (its dynamic-args-only call convention).
            aot: list = []

            def warm():
                aot.append(_propagate_ranked.lower(
                    fj, ej, self._aw, self._hw,
                    p.steps, p.decay, p.explain_strength, p.impact_bonus,
                    kk, plan.kernel, n_live, up_ell, down_seg, up_seg,
                    plan.dbl, error_contrast=p.error_contrast,
                ).compile())

            def run():
                if aot:
                    return aot[0](
                        fj, ej, self._aw, self._hw, n_live, up_ell,
                        down_seg, up_seg, plan.dbl,
                    )
                return _propagate_ranked(
                    fj, ej, self._aw, self._hw,
                    p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                    plan.kernel, n_live, up_ell, down_seg, up_seg,
                    plan.dbl, error_contrast=p.error_contrast,
                )

        stacked, diag, vals, idx, n_bad, latency_ms = timed_fetch(
            run, timed, warm=warm,
        )
        return render_result(
            diag, vals, idx, names, n, k, latency_ms,
            int(len(dep_src)), engine="single", sanitized_rows=n_bad,
            stacked_dev=stacked,
            attribution_ctx=make_attribution_ctx(
                features, dep_src, dep_dst, self.params, names,
                self.config.shape_buckets,
            ),
        )

    def analyze_batch(
        self,
        features_batch: np.ndarray,   # [B, S, C], one graph
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        k: Optional[int] = None,
    ) -> List[EngineResult]:
        import time as _time

        if edge_layout() == "ell":
            # the pure-ELL executable has no batched twin; the base-class
            # loop keeps batched scores identical to single analyses under
            # that (measurement-only) layout
            return super().analyze_batch(
                features_batch, dep_src, dep_dst, names, k=k
            )
        B, n = features_batch.shape[0], features_batch.shape[1]
        k = k or min(self.config.top_k_root_causes, n)
        f0, s, d = self._pad(features_batch[0], dep_src, dep_dst)
        fb = np.zeros((B, *f0.shape), np.float32)
        fb[:, :n] = features_batch
        ej = jnp.asarray(np.stack([s, d]))
        p = self.params
        # same registry plan as analyze_arrays (the one dispatch seam)
        plan = kernel_plan(
            f0.shape[0], len(s), dep_src, dep_dst, steps=p.steps
        )
        kk = min(k + 8, f0.shape[0])
        t0 = _time.perf_counter()
        stacked, diag, vals, idx, n_bad = _propagate_ranked_batch(
            jnp.asarray(fb), ej, self._aw, self._hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
            jnp.asarray(n, jnp.int32), plan.up_ell, plan.down_seg,
            plan.up_seg, error_contrast=p.error_contrast,
            kernel=batch_kernel(plan.kernel), dbl=plan.dbl,
        )
        # top-k-sized fetch only: the [B, 4, n_pad] stack stays on device
        # behind each lane's lazy diagnostics (ISSUE 6)
        diag, vals, idx, n_bad = jax.device_get((diag, vals, idx, n_bad))
        latency_ms = (_time.perf_counter() - t0) * 1e3
        # n_bad counts zeroed rows across the WHOLE batch (per-hypothesis
        # attribution is not worth a [B] fetch — a poisoned row poisons
        # every hypothesis built from the same snapshot)
        return [
            render_result(
                diag[b], vals[b], idx[b], names, n, k,
                latency_ms / B, int(len(dep_src)), engine="single-batch",
                sanitized_rows=int(n_bad), stacked_dev=stacked[b],
                attribution_ctx=make_attribution_ctx(
                    features_batch[b], dep_src, dep_dst, self.params,
                    names, self.config.shape_buckets,
                ),
            )
            for b in range(B)
        ]
