"""GraphEngine: bucketing, device transfer, compile caching, ranking.

The host-side wrapper around :mod:`rca_tpu.engine.propagate`: pads node/edge
arrays to shape buckets (so jit compiles once per tier, not per graph —
recompilation control per SURVEY.md §7 "hard parts"), keeps arrays on device,
and renders ranked root causes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.ell import EllGraph, propagate_ell
from rca_tpu.engine.propagate import (
    PropagationParams,
    default_params,
    propagate,
)

def _use_ell_layout() -> bool:
    """COO scatter is the default edge layout (XLA's TPU scatter measured
    sub-µs/step even at 65k nodes with duplicate-heavy indices); the
    scatter-free ELL layout is opt-in for stacks where scatter lowers
    poorly."""
    return os.environ.get("RCA_EDGE_LAYOUT", "coo").lower() == "ell"


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "use_pallas",
    ),
)
def _propagate_ranked(
    features, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, use_pallas: bool = False, n_live=None,
):
    """One dispatch, minimal transfers: edges arrive as one [2, E] buffer;
    diagnostics leave as one stacked [4, S] buffer plus the top-k pair.
    Matters on tunneled TPUs where every host<->device hop pays an RTT.

    With ``use_pallas`` the two noisy-OR evidence passes run as the fused
    Pallas kernel over the channel-major transpose (one feature read feeds
    both products); the propagation core is shared either way."""
    from rca_tpu.engine.propagate import propagate_core

    if use_pallas:
        from rca_tpu.engine.pallas_kernels import noisy_or_pair_pallas

        a, h = noisy_or_pair_pallas(features.T, anomaly_w, hard_w)
        out = propagate_core(
            a, h, edges[0], edges[1],
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
        )
        a, h, u, m, score = out
    else:
        a, h, u, m, score = propagate(
            features, edges[0], edges[1], anomaly_w, hard_w,
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
        )
    vals, idx = jax.lax.top_k(score, k)
    return jnp.stack([a, u, m, score]), vals, idx


@functools.partial(
    jax.jit,
    static_argnames=("steps", "decay", "explain_strength", "impact_bonus", "k"),
)
def _propagate_ranked_ell(
    features, up_idx, up_mask, up_ovf, dn_idx, dn_mask, dn_ovf,
    anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live=None,
):
    a, h, u, m, score = propagate_ell(
        features, up_idx, up_mask, up_ovf[0], up_ovf[1],
        dn_idx, dn_mask, dn_ovf[0], dn_ovf[1],
        anomaly_w, hard_w, steps, decay, explain_strength, impact_bonus,
        n_live=n_live,
    )
    vals, idx = jax.lax.top_k(score, k)
    return jnp.stack([a, u, m, score]), vals, idx
from rca_tpu.features.extract import FeatureSet, extract_features
from rca_tpu.graph.build import service_dependency_edges


@dataclasses.dataclass
class EngineResult:
    service_names: List[str]
    ranked: List[dict]            # [{component, score, anomaly, ...}] desc
    anomaly: np.ndarray           # [S]
    upstream: np.ndarray          # [S]
    impact: np.ndarray            # [S]
    score: np.ndarray             # [S]
    latency_ms: float             # device compute wall time (post-compile)
    n_services: int
    n_edges: int

    def top_components(self, k: Optional[int] = None) -> List[str]:
        items = self.ranked if k is None else self.ranked[:k]
        return [r["component"] for r in items]


class GraphEngine:
    """Bucketed, compile-cached causal propagation."""

    def __init__(
        self,
        config: Optional[RCAConfig] = None,
        params: Optional[PropagationParams] = None,
    ):
        self.config = config or RCAConfig()
        if params is None:
            ckpt = os.environ.get("RCA_WEIGHTS")
            if ckpt:
                from rca_tpu.engine.train import load_params

                params = load_params(ckpt)
        self.params = params or default_params(self.config.propagation_steps)
        self._aw, self._hw = self.params.weight_arrays()

    # -- shaping -----------------------------------------------------------
    def _pad(self, features: np.ndarray, src: np.ndarray, dst: np.ndarray):
        n = features.shape[0]
        # reserve one dummy slot so padded edges can self-loop harmlessly
        n_pad = bucket_for(n + 1, self.config.shape_buckets)
        e_pad = bucket_for(max(len(src), 1), self.config.shape_buckets)
        dummy = n_pad - 1
        f = np.zeros((n_pad, features.shape[1]), dtype=np.float32)
        f[:n] = features
        s = np.full(e_pad, dummy, dtype=np.int32)
        d = np.full(e_pad, dummy, dtype=np.int32)
        s[: len(src)] = src
        d[: len(dst)] = dst
        return f, s, d

    # -- core --------------------------------------------------------------
    def analyze_arrays(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        k: Optional[int] = None,
        timed: bool = False,
    ) -> EngineResult:
        n = features.shape[0]
        k = k or min(self.config.top_k_root_causes, n)
        f, s, d = self._pad(features, dep_src, dep_dst)
        fj = jnp.asarray(f)
        p = self.params
        kk = min(k + 8, f.shape[0])
        # live-count as a traced scalar: same executable serves every graph
        # size within a shape bucket
        n_live = jnp.asarray(n, jnp.int32)

        if _use_ell_layout():
            # scatter-free layout for large graphs
            ell = EllGraph.build(f.shape[0], dep_src, dep_dst)
            up_idx = jnp.asarray(ell.up.idx)
            up_mask = jnp.asarray(ell.up.mask)
            up_ovf = jnp.asarray(np.stack([ell.up.ovf_seg, ell.up.ovf_other]))
            dn_idx = jnp.asarray(ell.down.idx)
            dn_mask = jnp.asarray(ell.down.mask)
            dn_ovf = jnp.asarray(
                np.stack([ell.down.ovf_seg, ell.down.ovf_other])
            )

            def run():
                return _propagate_ranked_ell(
                    fj, up_idx, up_mask, up_ovf, dn_idx, dn_mask, dn_ovf,
                    self._aw, self._hw,
                    p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                    n_live,
                )
        else:
            ej = jnp.asarray(np.stack([s, d]))  # one [2, E] upload
            from rca_tpu.engine.pallas_kernels import (
                BLOCK_S,
                pallas_enabled,
            )

            # Pallas evidence pass is explicit opt-in (RCA_PALLAS=1): it
            # measures as a wash vs XLA on real TPU (pallas_kernels
            # docstring).  Kernel grid also needs the node pad to divide
            # into blocks (true for every power-of-two shape bucket).
            use_pallas = (
                f.shape[0] % min(f.shape[0], BLOCK_S) == 0
                and pallas_enabled()
            )

            def run():
                return _propagate_ranked(
                    fj, ej, self._aw, self._hw,
                    p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                    use_pallas, n_live,
                )

        if timed:
            run()[2].block_until_ready()  # warm the compile cache
            reps = []
            for _ in range(10):
                t0 = time.perf_counter()
                stacked, vals, idx = run()
                idx.block_until_ready()
                reps.append((time.perf_counter() - t0) * 1e3)
            latency_ms = float(np.median(reps))
        else:
            t0 = time.perf_counter()
            stacked, vals, idx = run()
            idx.block_until_ready()
            latency_ms = (time.perf_counter() - t0) * 1e3

        # one bulk fetch for the 3 result buffers
        stacked, vals, idx = jax.device_get((stacked, vals, idx))
        a, u, m, score = (stacked[i][:n] for i in range(4))
        names = list(names) if names is not None else [f"svc-{i}" for i in range(n)]
        ranked = []
        for j, i in enumerate(idx.tolist()):
            if i >= n or len(ranked) >= k:
                continue
            ranked.append(
                {
                    "component": names[i],
                    "score": float(vals[j]),
                    "anomaly": float(a[i]),
                    "explained_by_upstream": float(u[i]),
                    "downstream_impact": float(m[i]),
                }
            )
        return EngineResult(
            service_names=names,
            ranked=ranked,
            anomaly=a,
            upstream=u,
            impact=m,
            score=score,
            latency_ms=latency_ms,
            n_services=n,
            n_edges=int(len(dep_src)),
        )

    # -- convenience entry points ------------------------------------------
    def analyze_case(self, case, k: Optional[int] = None, timed: bool = False):
        """Analyze a :class:`rca_tpu.cluster.generator.CascadeArrays`."""
        return self.analyze_arrays(
            case.features, case.dep_src, case.dep_dst, case.names, k=k, timed=timed
        )

    def analyze_snapshot(self, snapshot, k: Optional[int] = None) -> EngineResult:
        fs = extract_features(snapshot)
        src, dst = service_dependency_edges(snapshot, fs)
        return self.analyze_features(fs, src, dst, k=k)

    def analyze_features(
        self, fs: FeatureSet, src: np.ndarray, dst: np.ndarray,
        k: Optional[int] = None,
    ) -> EngineResult:
        return self.analyze_arrays(
            fs.service_features, src, dst, fs.service_names, k=k
        )
