"""Pallas segmented-scan down-scan: the 50k impact chain off the scatter.

The propagation's impact recursion is 8 serial segment-sums over the
dependency edges (``m_new[d] = inv_deg[d] * sum_{(s,d)} (a_ex[s] + y*m[s])``).
XLA lowers the scatter-add at ~33 ns/edge (it serializes per edge), which
makes the chain the last real latency frontier at 50k services
(VERDICT r3 item 1; PERF.md edge-layout study).  Attribution measured on
v5e (tools/downscan_bench.py): of the 12.5 ms 8-step chain at 50k, ~6 ms
is the E-sized gather and ~6 ms the scatter.

This module replaces the scatter with a **flagged segmented scan** over
dst-sorted edges, run as ONE Pallas kernel pass over a VMEM-resident
[R, 128] layout (the 50k edge tier is ~0.5 MB — far under VMEM):

- lane-level flagged Hillis-Steele (7 shift-add passes): a value never
  absorbs across a segment boundary at or before it;
- row-level carry via the same flagged scan over full-lane row-aggregate
  broadcasts (Mosaic cannot shift 1-lane vectors along sublanes);
- each segment's total is its run's LAST element — no global cumsum, no
  boundary subtraction, so float error is bounded by the longest segment
  (the max-in-degree hub), not the whole edge array.  The global-cumsum
  alternative (rejected in round 3 for latency, re-measured in round 4)
  accumulates 5e-3 of error over 8 chained steps at 50k; this kernel
  holds ~4e-7 against the scatter chain.

Measured 8-step chain at 50k: 12.5 ms (COO scatter) -> 8.4 ms (segscan);
the residual is the per-step gather, which is shared by every layout.

Engagement (ISSUE 13): registry-resident.  This module ships the kernel
and its structural eligibility (:func:`segscan_eligibility` — edge tier
divisible by 128, under the VMEM cap); :mod:`rca_tpu.engine.registry`
owns the decision — forcing (``RCA_KERNEL=segscan`` or the legacy
``RCA_SEGSCAN=1``; ``RCA_SEGSCAN=0`` disables), the TPU +
``RCA_SEGSCAN_MIN`` auto gate (default 1024: the same-session A/B
showed segscan winning at EVERY measured tier — 0.63 vs 0.88 ms at 2k,
1.6 vs 3.5 ms at 5k, 4.3 vs 9.3 ms at 10k, 18.6 vs 47.3 ms at 50k — so
the floor only spares sub-millisecond micro-graphs the extra kernel
compile), the per-shape timings, and the persisted winner cache.  Tests
exercise the kernel hermetically on CPU via ``SEGSCAN_INTERPRET=1``.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import env_str

LANES = 128
# beyond this edge tier the [R, 128] working set stops fitting VMEM
# comfortably (4 live copies of e_pad * 4 bytes)
MAX_EPAD = 1 << 19


def _make_segscan_kernel(op: str):
    """Kernel factory: flagged segmented scan with ``sum`` or ``max``
    combine.  Both rely on every input being NONNEGATIVE, so a
    boundary-masked contribution of ``v_s * (1 - f)`` is the combine's
    identity (0) on both sides — sum adds 0, max keeps v."""

    def combine(v, v_s, f):
        if op == "sum":
            return v + v_s * (1.0 - f)
        return jnp.maximum(v, v_s * (1.0 - f))

    def kernel(x_ref, f_ref, out_ref):
        v = x_ref[...]                   # [R, 128] f32, all >= 0
        f = f_ref[...]                   # [R, 128] f32, 1 = segment start
        R = v.shape[0]

        for k in (1, 2, 4, 8, 16, 32, 64):
            # zero-pad BOTH: the virtual prefix carries no boundary (a
            # padded flag would poison the final (1 - f) carry gate at
            # every row start) and no value (nothing absorbs across the
            # row edge)
            v_s = jnp.pad(v, ((0, 0), (k, 0)))[:, :-k]
            f_s = jnp.pad(f, ((0, 0), (k, 0)))[:, :-k]
            v = combine(v, v_s, f)
            f = jnp.maximum(f, f_s)

        # row-level flagged scan on FULL-LANE broadcasts (see module
        # docstring)
        zero_row = jnp.zeros((1, LANES), dtype=v.dtype)
        cv = v[:, -1:] + zero_row        # [R, 128], all lanes equal
        cf = f[:, -1:] + zero_row
        k = 1
        while k < R:
            v_s = jnp.pad(cv, ((k, 0), (0, 0)))[:-k, :]
            f_s = jnp.pad(cf, ((k, 0), (0, 0)))[:-k, :]
            cv = combine(cv, v_s, cf)
            cf = jnp.maximum(cf, f_s)
            k *= 2
        # inclusive row carry, shifted down a row = carry ENTERING each row
        carry_in = jnp.pad(cv, ((1, 0), (0, 0)))[:-1, :]
        out_ref[...] = combine(v, carry_in, f)

    kernel.__name__ = f"_segscan_{op}_kernel"
    return kernel


_KERNELS = {"sum": _make_segscan_kernel("sum"), "max": _make_segscan_kernel("max")}


def interpret_mode() -> bool:
    """Pallas interpret-mode decision, made host-side at trace time: an
    explicit ``SEGSCAN_INTERPRET`` wins (1 forces on, 0 forces off); unset,
    interpret engages automatically when the default backend is not TPU, so
    a forced ``RCA_SEGSCAN=1`` on CPU/GPU runs the kernel through the
    interpreter instead of crashing at Mosaic dispatch (ADVICE r4)."""
    env = env_str("SEGSCAN_INTERPRET", "", choices=("0", "1"))
    if env:
        return env == "1"
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _segscan(x_flat, flags_flat, op: str):
    from jax.experimental import pallas as pl

    N = x_flat.shape[0]
    R = N // LANES
    out = pl.pallas_call(
        _KERNELS[op],
        out_shape=jax.ShapeDtypeStruct((R, LANES), jnp.float32),
        interpret=interpret_mode(),
    )(x_flat.reshape(R, LANES), flags_flat.reshape(R, LANES))
    return out.reshape(N)


def pallas_segscan(x_flat: jnp.ndarray, flags_flat: jnp.ndarray) -> jnp.ndarray:
    """Segmented inclusive SUM scan of a flat [N] array (N % 128 == 0)."""
    return _segscan(x_flat, flags_flat, "sum")


def pallas_segscan_max(x_flat: jnp.ndarray, flags_flat: jnp.ndarray) -> jnp.ndarray:
    """Segmented inclusive MAX scan (nonnegative inputs)."""
    return _segscan(x_flat, flags_flat, "max")


class SegLayout(NamedTuple):
    """Device arrays for one segmented-scan direction over a padded graph:
    edges sorted by their SEGMENT index (dst for the down-scan, src for
    the up-scan), the OTHER endpoint per sorted edge, segment-start flags,
    each segment's last edge position, and a has-edges mask (segments with
    no edges keep their reduction identity, exactly like the scatter
    path).  A NamedTuple so it crosses jit boundaries as a pytree."""

    other_sorted: jnp.ndarray  # int32 [e_pad] — other endpoint, seg-sorted
    flags: jnp.ndarray         # float32 [e_pad], 1 = first edge of its run
    ends: jnp.ndarray          # int32 [n_pad] — last edge pos per segment
    has_edges: jnp.ndarray     # float32 [n_pad]


def build_seg_layout(n_pad: int, e_pad: int, seg_idx, other_idx) -> SegLayout:
    """Host-side metadata for one scan direction.  Padded edge slots
    self-loop on the dummy node (slot ``n_pad - 1``) exactly like the COO
    path, so they sort into the dummy's run and contribute only to a row
    the propagation zeroes."""
    dummy = n_pad - 1
    seg = np.full(e_pad, dummy, np.int32)
    other = np.full(e_pad, dummy, np.int32)
    seg[: len(seg_idx)] = seg_idx
    other[: len(other_idx)] = other_idx
    order = np.argsort(seg, kind="stable")
    seg_sorted = seg[order]
    counts = np.bincount(seg_sorted, minlength=n_pad)
    ends = np.cumsum(counts)
    starts = ends - counts
    flags = np.zeros(e_pad, np.float32)
    flags[starts[counts > 0]] = 1.0
    return SegLayout(
        other_sorted=jnp.asarray(other[order]),
        flags=jnp.asarray(flags),
        ends=jnp.asarray((ends - 1).clip(0).astype(np.int32)),
        has_edges=jnp.asarray((counts > 0).astype(np.float32)),
    )


def build_down_seg(n_pad: int, e_pad: int, dep_src, dep_dst) -> SegLayout:
    """Down-scan (impact): segments are DESTINATIONS, values come from
    sources."""
    return build_seg_layout(n_pad, e_pad, dep_dst, dep_src)


def build_up_seg(n_pad: int, e_pad: int, dep_src, dep_dst) -> SegLayout:
    """Up-scan (explain-away): segments are SOURCES (the dependents),
    values come from their dependencies."""
    return build_seg_layout(n_pad, e_pad, dep_src, dep_dst)


def down_seg_step(m, a_ex, decay: float, seg: SegLayout, inv_deg):
    """One impact step over the segscan layout — same semantics as the COO
    ``imp_step`` (float association differs within a segment; parity is
    allclose at ~1e-6, asserted by tests/test_engine_layouts.py)."""
    vals = a_ex[seg.other_sorted] + decay * m[seg.other_sorted]
    s = pallas_segscan(vals, seg.flags)
    return jnp.where(seg.has_edges > 0, s[seg.ends], 0.0) * inv_deg


def up_seg_step(u, h, decay: float, seg: SegLayout):
    """One explain-away step as a segmented MAX over src-sorted edges.
    The per-node signal ``max(h, decay * u)`` is computed DENSE once
    ([S] elementwise), so the step pays ONE E-sized gather — the ELL
    table's [S, 8] form gathers ~4x more elements per step at 50k.
    fp32 max is order-invariant, so this direction stays bit-identical
    to the scatter-max and table forms."""
    w = jnp.maximum(h, decay * u)
    vals = w[seg.other_sorted]
    s = pallas_segscan_max(vals, seg.flags)
    upd = jnp.where(seg.has_edges > 0, s[seg.ends], 0.0)
    return jnp.maximum(u, upd)


# Built layouts keyed by an edge-set digest: the host-side argsort+bincount
# over the padded edge tier costs milliseconds at 50k/512k edges — paid on
# every one-shot analyze exactly in the latency path the kernel optimizes
# (ADVICE r4), while the streaming sessions build once per pinned edge set.
# Digest keys (16 bytes) instead of the raw tobytes() so the cache does not
# pin 4 MB of key material per 50k entry.  Insertion-ordered dict as FIFO.
_LAYOUT_CACHE: dict = {}
_LAYOUT_CACHE_MAX = 32


def arrays_digest(ints, arrays) -> bytes:
    """16-byte blake2b over shape scalars + array contents: the shared
    layout-cache key (also used by the sharded layout cache, so the digest
    inputs cannot drift between the two)."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    h.update(np.asarray(list(ints), np.int64).tobytes())
    for arr in arrays:
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.digest()


def cache_insert(cache: dict, key, value, maxsize: int = _LAYOUT_CACHE_MAX):
    """Bounded insertion-ordered (FIFO) cache insert, shared with the
    sharded layout cache."""
    while len(cache) >= maxsize:
        cache.pop(next(iter(cache)))
    cache[key] = value


def seg_layouts_for(n_pad: int, e_pad: int, dep_src, dep_dst):
    """(down_seg, up_seg) when the REGISTRY engages segscan for this
    shape, else (None, None).  ISSUE 13 folded the old ``RCA_SEGSCAN``
    side gate into the per-shape kernel registry: eligibility (edge-tier
    divisibility, VMEM cap, ``RCA_SEGSCAN_MIN``), forcing, and the
    per-shape timing all live in :mod:`rca_tpu.engine.registry` now, so
    the winner cache, cost analysis, bench ``kernel_registry`` section,
    and ``rca kernels`` finally see this kernel like any other.  Layouts
    are cached on the edge-set digest, so repeated analyses of the same
    graph (the common live/bench pattern) pay the host-side sort once."""
    from rca_tpu.engine.registry import engaged_kernel

    if engaged_kernel(n_pad, e_pad) != "segscan":
        return None, None
    return build_seg_layouts(n_pad, e_pad, dep_src, dep_dst)


def build_seg_layouts(n_pad: int, e_pad: int, dep_src, dep_dst):
    """Digest-cached (down_seg, up_seg) build with NO engagement gate —
    the assembly half :func:`seg_layouts_for` and the registry's timing
    harness share."""
    src = np.asarray(dep_src)
    dst = np.asarray(dep_dst)
    key = arrays_digest((n_pad, e_pad), (src, dst))
    hit = _LAYOUT_CACHE.get(key)
    if hit is None:
        hit = (
            build_down_seg(n_pad, e_pad, src, dst),
            build_up_seg(n_pad, e_pad, src, dst),
        )
        cache_insert(_LAYOUT_CACHE, key, hit)
    return hit


def segscan_eligibility(n_pad: int, e_pad):
    """Structural eligibility at one shape: ``True`` or the decline
    reason — the registry's segscan hook (:mod:`rca_tpu.engine.registry`
    owns forcing, the TPU/``RCA_SEGSCAN_MIN`` auto gate, and the
    decision itself).  A forced segscan is safe on any backend: off-TPU
    the kernel runs in interpret mode automatically
    (:func:`interpret_mode`)."""
    if env_str("RCA_SEGSCAN", "", choices=("0", "1")) == "0":
        return "RCA_SEGSCAN=0"
    if e_pad is None:
        return "edge tier unknown (caller passed no e_pad)"
    if e_pad % LANES:
        return f"e_pad {e_pad} not divisible into {LANES}-lane rows"
    if e_pad > MAX_EPAD:
        return f"e_pad {e_pad} past the VMEM cap {MAX_EPAD}"
    return True
