"""Learned propagation weights: optax fitting + orbax checkpointing.

The engine's evidence weights (noisy-OR channel weights, decay, explain
strength, impact bonus — :mod:`rca_tpu.engine.propagate`) default to
hand-set values.  This module fits them on synthetic cascades with known
roots: batched forward passes (vmap over cases), a listwise softmax
cross-entropy on the root-cause ranking, adam on unconstrained raw values
(sigmoid keeps the (0,1) weights in range; softplus keeps the impact bonus
positive but unbounded — its v3 default is 1.6).  Checkpoints persist via
orbax
(SURVEY.md §5 checkpoint row: model-weight checkpointing appears exactly
when the engine gains learned weights).

This is new capability relative to the reference (it never trains anything);
the acceptance bar is the parity gate plus hit@1 on held-out cascade seeds.
"""

from __future__ import annotations

import dataclasses
import functools
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.engine.propagate import (
    SCORE_FORMULA_VERSION,
    PropagationParams,
    default_params,
    propagate_core,
)
from rca_tpu.features.schema import NUM_SERVICE_FEATURES


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    n_services: int = 256
    n_roots_max: int = 3
    n_cases: int = 64
    steps: int = 8          # propagation steps (static)
    iters: int = 150
    lr: float = 0.05
    seed: int = 0
    # cascade modes sampled round-robin across the dataset (hard modes give
    # the learned weights something the hand-set defaults don't already ace)
    modes: Tuple[str, ...] = ("standard",)
    # Domain randomization (VERDICT r2 item 4): per-case generator
    # hyperparameters sampled uniformly from these ranges, so the fit
    # cannot exploit one fixed world (the round-2 failure: with every
    # knob pinned, training learned decay≈0.02 — no multi-hop propagation
    # — and dropped CRASH from hard evidence, artifacts usable only on the
    # distribution they overfit).  ``None`` disables (the old behavior,
    # kept for ablation).
    dr_decay: Optional[Tuple[float, float]] = (0.55, 0.9)
    dr_noise: Optional[Tuple[float, float]] = (0.02, 0.1)
    dr_max_deps: Optional[Tuple[int, int]] = (2, 4)        # inclusive
    dr_dropout_keep: Optional[Tuple[float, float]] = (0.5, 0.8)
    # root fault archetypes sampled per case: without "mixed" cascades the
    # fit zeroes the image/config/pending/oom channels that never fire in
    # crash-only worlds (observed in round 3) — weights that would silently
    # break on the fault classes the reference's test cluster injects
    dr_fault_mix: Optional[Tuple[str, ...]] = ("crash", "mixed", "mixed")
    # Physical-prior regularization strength (see _regularizer): anchors
    # decay and the CRASH hard weight inside physically-meaningful ranges.
    reg_strength: float = 1.0


def _logit(p: float) -> float:
    p = min(max(p, 1e-4), 1 - 1e-4)
    return float(np.log(p / (1 - p)))


def _softplus_inv(y: float) -> float:
    """Inverse of softplus; beta's domain is (0, ∞), NOT (0, 1) — the v3
    formula's default impact bonus is 1.6, which a sigmoid parameterization
    silently clamps to ~1.0 (round-3 review finding)."""
    y = max(y, 1e-4)
    return float(np.log(np.expm1(y)))


def params_to_pytree(p: PropagationParams) -> Dict[str, jnp.ndarray]:
    """Unconstrained raw values; sigmoid recovers the (0,1) weights and
    softplus recovers the positive-unbounded impact bonus."""
    return {
        "aw": jnp.asarray([_logit(x) for x in p.anomaly_weights]),
        "hw": jnp.asarray([_logit(x) for x in p.hard_weights]),
        "decay": jnp.asarray(_logit(p.decay)),
        "mu": jnp.asarray(_logit(p.explain_strength)),
        "beta": jnp.asarray(_softplus_inv(p.impact_bonus)),
    }


def pytree_to_params(tree: Dict, steps: int = 8) -> PropagationParams:
    sig = lambda x: jax.nn.sigmoid(jnp.asarray(x))  # noqa: E731
    return PropagationParams(
        anomaly_weights=tuple(float(x) for x in np.asarray(sig(tree["aw"]))),
        hard_weights=tuple(float(x) for x in np.asarray(sig(tree["hw"]))),
        steps=steps,
        decay=float(sig(tree["decay"])),
        explain_strength=float(sig(tree["mu"])),
        impact_bonus=float(jax.nn.softplus(jnp.asarray(tree["beta"]))),
    )


def sample_generator_kwargs(cfg: TrainConfig, rng: np.random.Generator) -> Dict:
    """One draw of the domain-randomized generator hyperparameters."""
    kw: Dict = {}
    if cfg.dr_decay is not None:
        kw["decay"] = float(rng.uniform(*cfg.dr_decay))
    if cfg.dr_noise is not None:
        kw["noise"] = float(rng.uniform(*cfg.dr_noise))
    if cfg.dr_max_deps is not None:
        kw["max_deps"] = int(rng.integers(cfg.dr_max_deps[0],
                                          cfg.dr_max_deps[1] + 1))
    if cfg.dr_dropout_keep is not None:
        kw["dropout_keep"] = float(rng.uniform(*cfg.dr_dropout_keep))
    if cfg.dr_fault_mix is not None:
        kw["fault_mix"] = str(
            cfg.dr_fault_mix[int(rng.integers(0, len(cfg.dr_fault_mix)))]
        )
    return kw


def make_dataset(
    cfg: TrainConfig, seed_offset: int = 0
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fixed-shape batch of cascades: features [B,S,C], edges [B,2,E],
    root multi-hot [B,S].  Each case draws its own generator
    hyperparameters (domain randomization) unless the ``dr_*`` ranges are
    disabled."""
    from rca_tpu.cluster.generator import synthetic_cascade_arrays

    S = cfg.n_services
    cases = []
    for b in range(cfg.n_cases):
        rng = np.random.default_rng(cfg.seed + seed_offset + b)
        cases.append(
            synthetic_cascade_arrays(
                S, n_roots=int(rng.integers(1, cfg.n_roots_max + 1)),
                seed=cfg.seed + seed_offset + b,
                mode=cfg.modes[b % len(cfg.modes)],
                **sample_generator_kwargs(cfg, rng),
            )
        )
    e_max = max(len(c.dep_src) for c in cases)
    # node S is a zero-feature dummy slot; padded edges self-loop on it
    B, C = cfg.n_cases, cases[0].features.shape[1]
    feats = np.zeros((B, S + 1, C), np.float32)
    edges = np.full((B, 2, e_max), S, np.int32)
    roots = np.zeros((B, S + 1), np.float32)
    for b, case in enumerate(cases):
        feats[b, :S] = case.features
        edges[b, 0, : len(case.dep_src)] = case.dep_src
        edges[b, 1, : len(case.dep_dst)] = case.dep_dst
        roots[b, case.roots] = 1.0
    return jnp.asarray(feats), jnp.asarray(edges), jnp.asarray(roots)


def _noisy_or_w(features, w):
    clipped = jnp.clip(features, 0.0, 1.0)
    return 1.0 - jnp.prod(1.0 - clipped * w[None, :], axis=1)


def _forward(tree, features, edges, steps: int):
    sig = jax.nn.sigmoid
    a = _noisy_or_w(features, sig(tree["aw"]))
    h = _noisy_or_w(features, sig(tree["hw"]))
    _, _, _, _, score = propagate_core(
        a, h, edges[0], edges[1], steps,
        sig(tree["decay"]), sig(tree["mu"]),
        jax.nn.softplus(tree["beta"]),
        n_live=features.shape[0] - 1,  # last slot is the edge-padding dummy
    )
    return score


def _regularizer(tree):
    """Physical prior on the fitted parameters (VERDICT r2 item 4): the
    round-2 fit exploited the fixed generator by collapsing decay to ~0.02
    (symptoms stop propagating, so the graph term degenerates) and zeroing
    CRASH out of hard evidence (explain-away dies).  Both are physically
    absurd for real cascades — symptoms demonstrably travel multiple hops
    and a crash-looping pod IS broken — so the loss hinges them into
    meaningful ranges instead of pinning exact values:

    - decay ≥ 0.4 (multi-hop propagation survives),
    - hard CRASH weight ≥ 0.7 (a crash stays hard evidence),
    - anomaly CRASH weight ≥ 0.6 (a crash stays root evidence),
    - every fault-archetype channel (OOM / IMAGE / CONFIG / PENDING)
      keeps anomaly ≥ 0.5 and hard ≥ 0.4 — a fit can pass synthetic
      cascades by leaning on the generator's correlated secondary signals
      (archetype roots always carry not_ready/events there), but a real
      ImagePullBackOff may surface nothing but its waiting reason; these
      floors mirror the shippability gate's direct channel check;
    - SOFT symptoms (error rate, latency, events, log errors, resource
      pressure) stay OUT of hard evidence (hw ≤ 0.55) — a fit that calls
      warning events "hard broken" works in the generator (its roots
      always emit events) but would treat every real cluster's background
      event churn as crashes (observed: hw[EVENTS] fitted to 0.99).

    Quadratic hinges: zero inside the allowed region, so a fit that beats
    the defaults WITHIN physical ranges pays nothing."""
    from rca_tpu.features.schema import SvcF

    sig = jax.nn.sigmoid
    decay = sig(tree["decay"])
    aw = sig(tree["aw"])
    hw = sig(tree["hw"])
    # SILENT rides the archetype floors: it is the absence-evidence twin of
    # these channels (what identifies their roots when dropout hides the
    # defining signal), and a fit on crash-heavy data would zero it for
    # exactly the same reason it zeroed them in round 3
    arch = jnp.asarray([int(SvcF.OOM), int(SvcF.IMAGE),
                        int(SvcF.CONFIG), int(SvcF.PENDING),
                        int(SvcF.SILENT)])
    soft = jnp.asarray([int(SvcF.ERROR_RATE), int(SvcF.LATENCY),
                        int(SvcF.EVENTS), int(SvcF.LOG_ERRORS),
                        int(SvcF.RESOURCE)])
    return (
        jnp.maximum(0.4 - decay, 0.0) ** 2
        + jnp.maximum(0.7 - hw[SvcF.CRASH], 0.0) ** 2
        + jnp.maximum(0.6 - aw[SvcF.CRASH], 0.0) ** 2
        # hinge floors sit a margin ABOVE the gate's 0.5/0.4 checks: a
        # hinge that is zero exactly at the gate floor lets the CE
        # gradient settle the weight epsilon BELOW it (observed: 0.498)
        + (jnp.maximum(0.55 - aw[arch], 0.0) ** 2).sum()
        + (jnp.maximum(0.45 - hw[arch], 0.0) ** 2).sum()
        # soft-channel CEILING sits a margin BELOW the gate's 0.6 check
        + (jnp.maximum(hw[soft] - 0.55, 0.0) ** 2).sum()
    )


@functools.partial(jax.jit, static_argnames=("steps",))
def _loss(tree, feats, edges, roots, steps: int, reg_strength: float = 0.0):
    """Listwise CE: every true root should sit atop the score softmax;
    plus the physical-prior hinge regularizer."""
    scores = jax.vmap(lambda f, e: _forward(tree, f, e, steps))(feats, edges)
    logp = jax.nn.log_softmax(scores * 8.0, axis=1)
    per_case = -(roots * logp).sum(axis=1) / jnp.maximum(
        roots.sum(axis=1), 1.0
    )
    return per_case.mean() + reg_strength * _regularizer(tree)


def hit_at_1(params: PropagationParams, cfg: TrainConfig,
             seed_offset: int = 10_000, mode: str = "standard") -> float:
    """Held-out top-1 accuracy (single-root cases for an unambiguous metric)."""
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine

    engine = GraphEngine(params=params)
    hits = 0
    trials = 20
    for t in range(trials):
        case = synthetic_cascade_arrays(
            cfg.n_services, n_roots=1, seed=cfg.seed + seed_offset + t,
            mode=mode,
        )
        r = engine.analyze_arrays(
            case.features, case.dep_src, case.dep_dst, k=1
        )
        hits += int(np.argmax(r.score)) == int(case.roots[0])
    return hits / trials


# held-out generator settings for the shippability gate: EVERY entry sits
# at or OUTSIDE the edges of the default training ranges (TrainConfig.dr_*
# — decay [0.55,0.9], noise [0.02,0.1], max_deps {2..4}, dropout_keep
# [0.5,0.8]), so a fit that merely memorized the training domain fails here
HOLDOUT_SETTINGS: Tuple[Dict, ...] = (
    {"decay": 0.5, "noise": 0.12, "max_deps": 5, "dropout_keep": 0.45,
     "fault_mix": "mixed"},
    {"decay": 0.95, "noise": 0.02, "max_deps": 2, "dropout_keep": 0.8},
    {"decay": 0.9, "noise": 0.12, "max_deps": 5, "dropout_keep": 0.5,
     "fault_mix": "mixed"},
)

# (baseline params, trials, seed_offset) -> holdout hit@1; PropagationParams
# is a frozen (hashable) dataclass
_BASELINE_HOLDOUT_CACHE: Dict = {}


def shippability_report(
    params: PropagationParams,
    baseline: Optional[PropagationParams] = None,
    trials_per_setting: int = 10,
    seed_offset: int = 50_000,
) -> Dict:
    """The gate trained weights must pass to ship (VERDICT r2 item 4):

    1. **physically sane** — decay > 0.3 and CRASH still counted as hard
       evidence (the round-2 fit violated both and worked only on the
       distribution it overfit);
    2. **no worse than the defaults on adversarial cascades under
       HELD-OUT generator settings** (:data:`HOLDOUT_SETTINGS` sit at or
       outside the training randomization edges);
    3. **fixtures don't regress** — the 5-service faulted world still
       ranks both injected roots top-2, and a 50-service cascade world
       still ranks its root first.

    Returns a dict with per-check results and an overall ``ships`` bool.
    """
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.engine import GraphEngine
    from rca_tpu.features.schema import SvcF

    baseline = baseline or default_params(params.steps)

    sane = {
        "decay": round(params.decay, 4),
        "decay_ok": params.decay > 0.3,
        "hard_crash": round(params.hard_weights[SvcF.CRASH], 4),
        "hard_crash_ok": params.hard_weights[SvcF.CRASH] >= 0.6,
        "anomaly_crash": round(params.anomaly_weights[SvcF.CRASH], 4),
        "anomaly_crash_ok": params.anomaly_weights[SvcF.CRASH] >= 0.5,
    }

    def holdout_hit1(p: PropagationParams) -> float:
        eng = GraphEngine(params=p)
        hits = trials = 0
        for si, setting in enumerate(HOLDOUT_SETTINGS):
            for t in range(trials_per_setting):
                case = synthetic_cascade_arrays(
                    300, n_roots=1,
                    seed=seed_offset + si * 1000 + t,
                    mode="adversarial", **setting,
                )
                r = eng.analyze_case(case, k=1)
                hits += int(np.argmax(r.score)) == int(case.roots[0])
                trials += 1
        return hits / trials

    trained_acc = holdout_hit1(params)
    # the defaults' holdout score is a deterministic constant per
    # (steps, trials, seed_offset): memoize so every gated train run
    # doesn't pay 30 redundant analyses re-measuring it
    base_key = (baseline, trials_per_setting, seed_offset)
    if base_key in _BASELINE_HOLDOUT_CACHE:
        default_acc = _BASELINE_HOLDOUT_CACHE[base_key]
    else:
        default_acc = holdout_hit1(baseline)
        _BASELINE_HOLDOUT_CACHE[base_key] = default_acc

    def fixtures_ok(p: PropagationParams) -> Dict:
        eng = GraphEngine(params=p)
        snap = ClusterSnapshot.capture(
            MockClusterClient(five_service_world()), NS
        )
        five = set(eng.analyze_snapshot(snap).top_components(2))
        case = synthetic_cascade_arrays(50, n_roots=1, seed=0)
        fifty = eng.analyze_case(case, k=1)
        # per-archetype smoke: each fault family checked alone on an easy
        # standard-mode cascade the defaults ace (end-to-end ranking)
        archetypes = {}
        for kind in ("oom", "image", "config", "pending"):
            hits = 0
            for t in range(3):
                c = synthetic_cascade_arrays(
                    200, n_roots=1, seed=60_000 + t, fault_mix=kind,
                )
                r = eng.analyze_case(c, k=1)
                hits += int(np.argmax(r.score)) == int(c.roots[0])
            archetypes[kind] = hits
        # direct channel check — the sharp instrument: a fit can pass the
        # cascade smoke by leaning on the generator's correlated secondary
        # signals (not_ready/events always accompany synthetic archetype
        # roots), but a REAL ImagePullBackOff may surface nothing else, so
        # each fault channel's weight must alone constitute root+hard
        # evidence (for a lone 1.0 channel the noisy-OR IS the weight —
        # this is exactly what the observed crash-only round-3 fit
        # violated: image/config/pending/oom all fitted to ~0.03)
        chans = (SvcF.OOM, SvcF.IMAGE, SvcF.CONFIG, SvcF.PENDING,
                 SvcF.SILENT)
        channel_floor = {
            ch.name.lower(): {
                "a": round(float(p.anomaly_weights[ch]), 3),
                "h": round(float(p.hard_weights[ch]), 3),
            }
            for ch in chans
        }
        # compare RAW floats: the report's 3-decimal rounding would pass a
        # 0.4996 weight as 0.5 — the exact epsilon-under-the-floor failure
        # this check exists to catch
        channels_ok = all(
            float(p.anomaly_weights[ch]) >= 0.5
            and float(p.hard_weights[ch]) >= 0.4
            for ch in chans
        )
        # ...and soft symptoms must stay OUT of hard evidence: a fit with
        # hw[EVENTS] ~ 1.0 calls every real cluster's background event
        # churn "hard broken" (works only inside the generator)
        soft_chans = (SvcF.ERROR_RATE, SvcF.LATENCY, SvcF.EVENTS,
                      SvcF.LOG_ERRORS, SvcF.RESOURCE)
        channels_ok = channels_ok and all(
            float(p.hard_weights[ch]) <= 0.6 for ch in soft_chans
        )
        soft_hard_max = round(
            max(float(p.hard_weights[ch]) for ch in soft_chans), 3
        )
        return {
            "five_svc_top2": sorted(five),
            "five_svc_ok": five == {"database", "api-gateway"},
            "fifty_svc_top1_ok": (
                fifty.ranked[0]["component"] == case.names[case.roots[0]]
            ),
            "archetype_hits": archetypes,
            "channel_floors": channel_floor,
            "soft_hard_max": soft_hard_max,
            "archetypes_ok": bool(
                all(v == 3 for v in archetypes.values()) and channels_ok
            ),
        }

    fx = fixtures_ok(params)
    report = {
        "sanity": sane,
        "holdout_adversarial_hit1": {
            "trained": round(trained_acc, 4),
            "defaults": round(default_acc, 4),
        },
        "fixtures": fx,
        "ships": bool(
            sane["decay_ok"] and sane["hard_crash_ok"]
            and sane["anomaly_crash_ok"]
            and trained_acc >= default_acc
            and fx["five_svc_ok"] and fx["fifty_svc_top1_ok"]
            and fx["archetypes_ok"]
        ),
    }
    return report


def train(
    cfg: Optional[TrainConfig] = None,
    init: Optional[PropagationParams] = None,
) -> Tuple[PropagationParams, List[float]]:
    """Fit the weights; returns (trained params, loss history)."""
    import optax

    cfg = cfg or TrainConfig()
    tree = params_to_pytree(init or default_params(cfg.steps))
    feats, edges, roots = make_dataset(cfg)
    opt = optax.adam(cfg.lr)
    opt_state = opt.init(tree)
    grad_fn = jax.jit(
        jax.value_and_grad(_loss), static_argnames=("steps",)
    )
    history: List[float] = []
    for _ in range(cfg.iters):
        loss, grads = grad_fn(
            tree, feats, edges, roots, cfg.steps, cfg.reg_strength
        )
        updates, opt_state = opt.update(grads, opt_state)
        tree = optax.apply_updates(tree, updates)
        history.append(float(loss))
    return pytree_to_params(tree, steps=cfg.steps), history


# -- checkpointing (orbax + packaged JSON) ----------------------------------

# The SHIPPED default checkpoint (VERDICT r3 item 2): a gate-passing
# artifact committed with the repo, loaded by GraphEngine construction
# unless RCA_WEIGHTS overrides it (see rca_tpu.engine.runner.resolve_params).
# JSON, not orbax: the artifact is ~30 floats — human-diffable in review,
# no checkpointer dependency at import time.
PACKAGED_WEIGHTS = Path(__file__).with_name("default_weights.json")


def _require_formula_version(version: int, path: str) -> None:
    if version != SCORE_FORMULA_VERSION:
        raise ValueError(
            f"checkpoint {path} was trained against score formula "
            f"v{version}, but this engine computes v{SCORE_FORMULA_VERSION} "
            "(rca_tpu.engine.propagate.SCORE_FORMULA_VERSION) — weights "
            "fitted to a different objective mis-rank silently; retrain "
            "with `rca train`."
        )


def save_params_json(
    params: PropagationParams, path: str, provenance: Optional[Dict] = None
) -> None:
    """Single-file JSON checkpoint (the packaged-artifact format).
    ``provenance`` (training config, gate report, dataset description) is
    stored verbatim so the shipped file documents how it was produced."""
    import json

    data = {
        "format": "rca-weights-v1",
        "formula_version": SCORE_FORMULA_VERSION,
        "anomaly_weights": [float(x) for x in params.anomaly_weights],
        "hard_weights": [float(x) for x in params.hard_weights],
        "steps": int(params.steps),
        "decay": float(params.decay),
        "explain_strength": float(params.explain_strength),
        "impact_bonus": float(params.impact_bonus),
        "provenance": provenance or {},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def load_params_json(path: str) -> PropagationParams:
    import json

    with open(path) as f:
        data = json.load(f)
    _require_formula_version(int(data.get("formula_version", 1)), path)
    n = NUM_SERVICE_FEATURES
    short = min(len(data["anomaly_weights"]), len(data["hard_weights"]))
    if short < n:
        raise ValueError(
            f"checkpoint {path} carries {short} weight channels but this "
            f"engine's feature schema has {n} "
            "(rca_tpu.features.schema.SvcF grew since it was trained) — "
            "retrain with `rca train`."
        )
    return PropagationParams(
        anomaly_weights=tuple(float(x) for x in data["anomaly_weights"][:n]),
        hard_weights=tuple(float(x) for x in data["hard_weights"][:n]),
        steps=int(data["steps"]),
        decay=float(data["decay"]),
        explain_strength=float(data["explain_strength"]),
        impact_bonus=float(data["impact_bonus"]),
    )


def packaged_params() -> Optional[PropagationParams]:
    """The committed default checkpoint, or None when absent (source
    checkouts before the artifact landed, or deliberately stripped)."""
    if PACKAGED_WEIGHTS.exists():
        return load_params_json(str(PACKAGED_WEIGHTS))
    return None


def save_params(params: PropagationParams, path: str) -> None:
    import orbax.checkpoint as ocp

    tree = {
        "anomaly_weights": np.asarray(params.anomaly_weights, np.float32),
        "hard_weights": np.asarray(params.hard_weights, np.float32),
        "steps": np.asarray(params.steps, np.int32),
        "decay": np.asarray(params.decay, np.float32),
        "explain_strength": np.asarray(params.explain_strength, np.float32),
        "impact_bonus": np.asarray(params.impact_bonus, np.float32),
        "formula_version": np.asarray(SCORE_FORMULA_VERSION, np.int32),
    }
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(Path(path).absolute(), tree, force=True)


def load_params(path: str) -> PropagationParams:
    """Load either checkpoint format: a JSON file (packaged artifact) or
    an orbax checkpoint directory (``rca train --out``)."""
    p = Path(path)
    if p.is_file():
        return load_params_json(path)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    tree = ckptr.restore(p.absolute())
    _require_formula_version(int(tree.get("formula_version", 1)), path)
    n = NUM_SERVICE_FEATURES
    short = min(len(np.asarray(tree["anomaly_weights"])),
                len(np.asarray(tree["hard_weights"])))
    if short < n:
        raise ValueError(
            f"checkpoint {path} carries {short} weight channels but this "
            f"engine's feature schema has {n} — retrain with `rca train`."
        )
    aw = tuple(float(x) for x in np.asarray(tree["anomaly_weights"])[:n])
    hw = tuple(float(x) for x in np.asarray(tree["hard_weights"])[:n])
    return PropagationParams(
        anomaly_weights=aw,
        hard_weights=hw,
        steps=int(tree["steps"]),
        decay=float(tree["decay"]),
        explain_strength=float(tree["explain_strength"]),
        impact_bonus=float(tree["impact_bonus"]),
    )
