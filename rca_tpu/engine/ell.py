"""Capped-ELL edge layout: scatter-free propagation (alternative layout).

Pads each node's edge list to a fixed width D (ELL/padded-CSR) so every
propagation step is a dense gather + row reduce instead of a COO scatter.
Real graphs have hub nodes (in-degree p99 ≈ 24 but max ≈ 2k at 50k
services), so the width is capped and the residue goes to a small COO
overflow list.

Measured on v5e via device_get-synced in-jit loop timing: a FULL-ELL
propagate (both directions through width-capped tables) loses to COO
scatter — 10.9 vs 1.4 ms at 2k services, 158 vs 34 ms at 50k — because hub
fan-in forces a wide (32-lane) down table.  But the UP direction's degree
distribution is the opposite (services depend on 3-8 things), and a narrow
up table beats the scatter-max 2.4x per step; the default engine layout is
therefore the HYBRID (``RCA_EDGE_LAYOUT=hybrid``): up-scan through
:func:`build_ell_segments`' table, down-scan through COO scatter-add.  Pure
``coo`` and pure ``ell`` remain selectable, and all three are verified
bit-compatible by tests/test_engine_layouts.py.  (Reference comparison: the
reference rebuilt an ``nx.DiGraph`` per analysis,
agents/topology_agent.py:94; no layout here materializes dense adjacency,
per SURVEY.md §7.)
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.engine.propagate import (
    _noisy_or,
    background_excess,
    combine_score,
    fold_error_contrast,
)

DEFAULT_WIDTH_CAP = 32


@dataclasses.dataclass(frozen=True)
class EllSegments:
    """Per-segment padded neighbor lists + COO overflow for one direction."""

    idx: np.ndarray        # int32 [S_pad, D] neighbor ids (dummy-padded)
    mask: np.ndarray       # float32 [S_pad, D] 1=real
    ovf_seg: np.ndarray    # int32 [O_pad] segment ids of overflow edges
    ovf_other: np.ndarray  # int32 [O_pad] neighbor ids of overflow edges
    n_overflow: int


def build_ell_segments(
    seg: np.ndarray,
    other: np.ndarray,
    n_pad: int,
    width_cap: int = DEFAULT_WIDTH_CAP,
) -> EllSegments:
    """Group ``other`` by ``seg`` into an [n_pad, D] table, D ≤ width_cap.

    Edges past the cap for a hub segment land in the overflow COO arrays
    (dummy-padded to a power-of-two so shapes bucket)."""
    dummy = n_pad - 1
    E = len(seg)
    if E == 0:
        return EllSegments(
            idx=np.full((n_pad, 1), dummy, np.int32),
            mask=np.zeros((n_pad, 1), np.float32),
            ovf_seg=np.full(1, dummy, np.int32),
            ovf_other=np.full(1, dummy, np.int32),
            n_overflow=0,
        )
    order = np.argsort(seg, kind="stable")
    s_sorted = seg[order].astype(np.int64)
    o_sorted = other[order].astype(np.int32)
    counts = np.bincount(s_sorted, minlength=n_pad)
    starts = np.zeros(n_pad + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    col = np.arange(E, dtype=np.int64) - starts[s_sorted]

    D = int(min(max(counts.max(), 1), width_cap))
    in_table = col < D
    idx = np.full((n_pad, D), dummy, np.int32)
    mask = np.zeros((n_pad, D), np.float32)
    idx[s_sorted[in_table], col[in_table]] = o_sorted[in_table]
    mask[s_sorted[in_table], col[in_table]] = 1.0

    ovf = ~in_table
    n_ovf = int(ovf.sum())
    o_pad = 1 << max(int(np.ceil(np.log2(max(n_ovf, 1)))), 0)
    ovf_seg = np.full(o_pad, dummy, np.int32)
    ovf_other = np.full(o_pad, dummy, np.int32)
    ovf_seg[:n_ovf] = s_sorted[ovf]
    ovf_other[:n_ovf] = o_sorted[ovf]
    return EllSegments(
        idx=idx, mask=mask, ovf_seg=ovf_seg, ovf_other=ovf_other,
        n_overflow=n_ovf,
    )


@dataclasses.dataclass(frozen=True)
class EllGraph:
    n_pad: int
    up: EllSegments    # segment = src (the dependent); neighbors = dsts
    down: EllSegments  # segment = dst (the dependency); neighbors = srcs

    @classmethod
    def build(
        cls, n_pad: int, src: np.ndarray, dst: np.ndarray,
        width_cap: int = DEFAULT_WIDTH_CAP,
    ) -> "EllGraph":
        return cls(
            n_pad=n_pad,
            up=build_ell_segments(src, dst, n_pad, width_cap),
            down=build_ell_segments(dst, src, n_pad, width_cap),
        )


def ell_up_step(u, h, decay, idx, mask, ovf_seg, ovf_other):
    """One upstream-explanation step over an ELL table: gather each node's
    dependencies, take the row max, fold hub overflow through a small
    scatter-max, and keep the dummy slot (last row) at 0.  Shared by the
    hybrid default (propagate_core) and the full-ELL layout so the
    bit-compatibility the layout tests assert cannot drift between copies."""
    vals = jnp.maximum(h[idx], decay * u[idx]) * mask
    u_new = vals.max(axis=1)
    ovf = jnp.maximum(h[ovf_other], decay * u[ovf_other])
    u_new = u_new.at[ovf_seg].max(ovf)
    # dummy slot may have been written by padded overflow lanes
    u_new = u_new.at[-1].set(0.0)
    return jnp.maximum(u, u_new)


@functools.partial(
    jax.jit,
    # error_contrast must be static: the kernel branches on it in Python
    # (`if error_contrast:`) — traced, that branch dies with
    # TracerBoolConversionError the first time the ELL path runs
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus",
        "error_contrast",
    ),
)
def propagate_ell(
    features,                    # [S_pad, C]
    up_idx, up_mask,             # [S_pad, Du], dsts per src
    up_ovf_seg, up_ovf_other,    # [Ou]
    dn_idx, dn_mask,             # [S_pad, Dd], srcs per dst
    dn_ovf_seg, dn_ovf_other,    # [Od]
    anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    n_live=None, error_contrast: float = 0.0,
):
    """Scatter-free variant of :func:`rca_tpu.engine.propagate.propagate`.

    Same math, same outputs (anomaly, hard, upstream, impact, score); hub
    residue handled by one small scatter per step.  The dummy slot (last
    row) carries zero features so padded lanes contribute the identity of
    each reduction (0 for max over nonnegatives, 0 for sum).
    """
    from rca_tpu.features.schema import SvcF

    a = _noisy_or(features, anomaly_w)
    h = _noisy_or(features, hard_w)
    if error_contrast:
        # error-source contrast over the up table (dependencies per src):
        # table lanes masked to the max identity 0, hub residue through
        # the overflow scatter — same result as the COO form
        e = jnp.clip(features[:, SvcF.ERROR_RATE], 0.0, 1.0)
        dep_max = (e[up_idx] * up_mask).max(axis=1)
        dep_max = dep_max.at[up_ovf_seg].max(e[up_ovf_other])
        a = fold_error_contrast(
            a, jnp.maximum(e - dep_max, 0.0), error_contrast
        )

    def up_step(u, _):
        return ell_up_step(
            u, h, decay, up_idx, up_mask, up_ovf_seg, up_ovf_other
        ), None

    u, _ = jax.lax.scan(up_step, jnp.zeros_like(a), None, length=steps)

    a_ex = background_excess(a, n_live)

    # dependent count for the degree-normalized impact mean: table lanes
    # from the mask, hub residue through the same overflow scatter (padded
    # overflow lanes point at the dummy node and only inflate its count)
    deg = dn_mask.sum(axis=1).at[dn_ovf_seg].add(1.0)
    inv_deg = 1.0 / jnp.maximum(deg, 1.0)

    def imp_step(m, _):
        vals = (a_ex[dn_idx] + decay * m[dn_idx]) * dn_mask
        m_new = vals.sum(axis=1)
        # padded overflow lanes point at the dummy node whose a=m=0
        ovf = a_ex[dn_ovf_other] + decay * m[dn_ovf_other]
        m_new = m_new.at[dn_ovf_seg].add(ovf)
        m_new = m_new * inv_deg
        m_new = m_new.at[-1].set(0.0)
        return m_new, None

    m, _ = jax.lax.scan(imp_step, jnp.zeros_like(a), None, length=steps)

    score = combine_score(a, h, u, m, explain_strength, impact_bonus)
    return a, h, u, m, score
