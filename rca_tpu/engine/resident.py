"""Device-resident analyze sessions: erase the per-request staging floor.

BENCH_r02–r05 put 2k-service device compute at 0.7–1.8 ms while one
end-to-end analysis pays ~90–125 ms — a ~100× host/staging/fetch floor
(ROADMAP item 1; the GNN-acceleration survey in PAPERS.md [5] names
host↔device data movement, not compute, as the dominant cost once kernels
are tuned).  The streaming session solved this for TICKS in round 2 by
pinning state on device and scattering deltas; this module generalizes
that pattern to the ONE-SHOT analyze path (``GraphEngine.analyze_arrays``
and everything behind it — the coordinator, the CLI, the serve solo
re-runs):

- a :class:`ResidentSession` per graph digest pins the padded edge
  buffers, the segscan/up-table layouts, AND the feature matrix on
  device for as long as the graph stays hot;
- a repeat request over the same graph uploads only its CHANGED rows
  (host diff against the raw mirror), applied with a donated-argument
  in-place scatter fused into the propagation dispatch — per-request
  host→device bytes are O(changed rows), not O(n_pad × C);
- every fetch moves only top-k-sized results (the ``[4, k]`` diagnostic
  gather + the top-k pair + the sanitized-row scalar); the full stack
  stays on device behind :meth:`rca_tpu.engine.runner.EngineResult.
  full_diagnostics`'s deferred bulk fetch;
- a :class:`ResidentCache` LRU (``RCA_RESIDENT_CACHE``) bounds the pinned
  device memory; ``RCA_RESIDENT=0`` restores the restage-everything path.

Bit-parity contract: the resident buffer always holds exactly the padded
RAW request features (the scatter writes raw rows; the finite-mask
sanitize runs fused inside each dispatch without persisting, unlike the
streaming session's persist-on-device variant), so every analyze computes
from the same values full staging would upload — scores, rankings, and
sanitized-row counts are bit-identical over arbitrary update/delete/NaN
sequences (property-tested in tests/test_resident.py).
"""

from __future__ import annotations

import collections
import functools
import hashlib
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import bucket_for, resident_cache_cap
from rca_tpu.util.threads import make_lock

GraphDigest = Tuple[int, int, int, str]


def graph_digest(
    n: int, num_features: int, dep_src: np.ndarray, dep_dst: np.ndarray,
) -> GraphDigest:
    """Identity of the computation graph an analyze call runs over:
    ``(n_services, n_channels, n_edges, edge-digest)`` — the same notion
    of identity the serving layer's ``graph_key`` uses, so "requests that
    coalesce" and "requests that share a resident session" agree."""
    digest = hashlib.sha1(
        np.asarray(dep_src, np.int32).tobytes() + b"|"
        + np.asarray(dep_dst, np.int32).tobytes()
    ).hexdigest()[:16]
    return (int(n), int(num_features), int(len(dep_src)), digest)


@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast", "kernel",
    ),
)
def _resident_delta_ranked(
    features, idx, rows, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0, kernel: str = "xla", dbl=None,
):
    """One request in ONE dispatch: scatter the delta rows into the
    donated resident buffer, sanitize, propagate, top-k, and gather the
    top-k diagnostic rows.  Returns the RAW post-scatter buffer (the next
    request's diff base) — the finite-mask pass feeds only the
    propagation, so the resident state is exactly what full staging would
    have uploaded and parity holds row-for-row, NaN rows included."""
    from rca_tpu.engine.propagate import finite_mask_rows
    from rca_tpu.engine.runner import propagate_auto, topk_diag

    features = features.at[idx].set(rows)
    clean, n_bad = finite_mask_rows(features)
    a, h, u, m, score = propagate_auto(
        clean, edges, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        error_contrast=error_contrast, kernel=kernel, dbl=dbl,
    )
    vals, topi = jax.lax.top_k(score, k)
    stacked = jnp.stack([a, u, m, score])
    return features, stacked, topk_diag(stacked, topi), vals, topi, n_bad


class ResidentSession:
    """One graph's device-resident analyze state.  Not thread-safe on its
    own — :class:`ResidentCache` serializes access (the donated buffer
    swap must not race)."""

    def __init__(
        self,
        engine,                      # GraphEngine (weights + config)
        key: GraphDigest,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
    ):
        from rca_tpu.engine.runner import kernel_plan

        self.engine = engine
        self.key = key
        n, num_features, n_edges, _ = key
        cfg = engine.config
        self._n = n
        self._num_features = num_features
        self._n_edges = n_edges
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        e_pad = bucket_for(max(n_edges, 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[:n_edges] = dep_src
        d[:n_edges] = dep_dst
        # edges + layouts + (lazily) the feature matrix live on device for
        # the session lifetime — same pinning the streaming session does
        self._edges = jnp.asarray(np.stack([s, d]))
        # raw edges retained for the lazy causelens context (ISSUE 14)
        self._dep_src = np.asarray(dep_src, np.int32)
        self._dep_dst = np.asarray(dep_dst, np.int32)
        # per-shape registry plan (ISSUE 12/13): the same dispatch seam
        # the one-shot and streaming surfaces ask, so the resident delta
        # path cannot drift to a different kernel
        self._plan = kernel_plan(
            self._n_pad, e_pad, dep_src, dep_dst,
            steps=engine.params.steps,
        )
        self._down_seg = self._plan.down_seg
        self._up_seg = self._plan.up_seg
        self._up_ell = self._plan.up_ell
        self._n_live = jnp.asarray(n, jnp.int32)
        # raw host mirror of the resident buffer's live rows (the diff
        # base); None until the first request stages the buffer
        self._mirror: Optional[np.ndarray] = None
        self._features = None        # device [n_pad, C]
        # accounting (bench sync_floor section + serve metrics read these)
        self.requests = 0
        self.delta_requests = 0      # served via the delta-scatter path
        self.last_upload_rows = 0    # padded rows the last request staged
        self.upload_bytes = 0        # cumulative host->device request bytes
        self.fetch_bytes = 0         # cumulative device->host result bytes

    # -- fetch surface -------------------------------------------------------
    def _fetch_topk(self, diag, vals, idx, n_bad):
        """THE session's device-sync point: moves only the [4, kk] gather,
        the top-k pair, and the sanitized-row scalar (resident-fetch lint:
        no full-[n_pad] fetch on this path)."""
        diag, vals, idx, n_bad = jax.device_get((diag, vals, idx, n_bad))
        self.fetch_bytes += (
            diag.nbytes + vals.nbytes + idx.nbytes + 4
        )
        return diag, vals, idx, int(n_bad)

    # -- one request ---------------------------------------------------------
    def analyze(self, features: np.ndarray, names, k: int):
        from rca_tpu.engine.runner import (
            _propagate_ranked,
            make_attribution_ctx,
            render_result,
        )

        t0 = time.perf_counter()
        eng = self.engine
        p = eng.params
        kk = min(k + 8, self._n_pad)
        features = np.asarray(features, np.float32)
        changed = (
            None if self._mirror is None
            else np.flatnonzero(np.any(features != self._mirror, axis=1))
        )
        # NaN rows always diff as changed (NaN != NaN), so a poisoned row
        # re-uploads raw every request — the fused sanitize re-zeroes it
        # on device and parity with full staging holds
        if changed is None or 2 * len(changed) >= self._n_pad:
            # first request for this graph — or the delta is no cheaper
            # than the matrix: stage the full padded buffer once, pin it
            f = np.zeros((self._n_pad, self._num_features), np.float32)
            f[: self._n] = features
            self._features = jnp.asarray(f)
            self._mirror = features.copy()
            self.last_upload_rows = self._n_pad
            self.upload_bytes += f.nbytes
            stacked, diag, vals, idx, n_bad = _propagate_ranked(
                self._features, self._edges, eng._aw, eng._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                self._plan.kernel, self._n_live, self._up_ell,
                self._down_seg, self._up_seg, self._plan.dbl,
                error_contrast=p.error_contrast,
            )
        elif len(changed) == 0:
            # identical request (retry, hypothesis re-rank): zero upload
            self.delta_requests += 1
            self.last_upload_rows = 0
            stacked, diag, vals, idx, n_bad = _propagate_ranked(
                self._features, self._edges, eng._aw, eng._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                self._plan.kernel, self._n_live, self._up_ell,
                self._down_seg, self._up_seg, self._plan.dbl,
                error_contrast=p.error_contrast,
            )
        else:
            # delta request: O(changed rows) up, fused donated scatter.
            # Pad slots aim at the dummy row with zero rows — it is zero
            # already, so the write is a no-op at any pad width
            u = len(changed)
            u_pad = 1 << max(0, (u - 1).bit_length())
            idx_h = np.full(u_pad, self._n_pad - 1, np.int32)
            rows_h = np.zeros((u_pad, self._num_features), np.float32)
            idx_h[:u] = changed
            rows_h[:u] = features[changed]
            (self._features, stacked, diag, vals, idx,
             n_bad) = _resident_delta_ranked(
                self._features, jnp.asarray(idx_h), jnp.asarray(rows_h),
                self._edges, eng._aw, eng._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus, kk,
                self._n_live, self._up_ell, self._down_seg, self._up_seg,
                error_contrast=p.error_contrast,
                kernel=self._plan.kernel, dbl=self._plan.dbl,
            )
            # mirror updates only once the dispatch is accepted — a raise
            # above (fresh-tier compile failure) leaves the old mirror, so
            # the next request re-diffs and recovers
            self._mirror[changed] = features[changed]
            self.delta_requests += 1
            self.last_upload_rows = u_pad
            self.upload_bytes += idx_h.nbytes + rows_h.nbytes
        self.requests += 1
        diag, vals, idx, n_bad = self._fetch_topk(diag, vals, idx, n_bad)
        latency_ms = (time.perf_counter() - t0) * 1e3
        return render_result(
            diag, vals, idx, names, self._n, k, latency_ms,
            self._n_edges, engine="single", sanitized_rows=n_bad,
            stacked_dev=stacked,
            attribution_ctx=make_attribution_ctx(
                features, self._dep_src, self._dep_dst, eng.params,
                names, eng.config.shape_buckets,
            ),
        )


class ResidentCache:
    """LRU of :class:`ResidentSession` per graph digest (the engine-side
    analog of the serving dispatcher's prepared-graph cache).  The lock
    serializes whole analyze calls: the donated-buffer swap inside a
    session must not interleave with another thread's dispatch over the
    same session.

    ``session_factory`` makes the cache engine-agnostic: the dense
    engine uses the default :class:`ResidentSession`; the sharded engine
    plugs :class:`rca_tpu.parallel.sharded.ShardedResidentSession` in
    (same ``(engine, key, dep_src, dep_dst)`` constructor, same
    ``analyze``/accounting surface), so one LRU + lock discipline serves
    both (ISSUE 8 satellite)."""

    def __init__(self, engine, cap: Optional[int] = None,
                 session_factory=None):
        self._engine = engine
        self._factory = session_factory or ResidentSession
        self._cap = int(cap) if cap is not None else resident_cache_cap()
        self._sessions: "collections.OrderedDict[GraphDigest, ResidentSession]" = (
            collections.OrderedDict()
        )
        self._lock = make_lock("ResidentCache._lock")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def analyze(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]],
        k: int,
    ):
        key = graph_digest(
            features.shape[0], features.shape[1], dep_src, dep_dst
        )
        with self._lock:
            sess = self._sessions.get(key)
            if sess is not None:
                self._sessions.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
                sess = self._factory(self._engine, key, dep_src, dep_dst)
                self._sessions[key] = sess
                while len(self._sessions) > self._cap:
                    self._sessions.popitem(last=False)
                    self.evictions += 1
            return sess.analyze(features, names, k)

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
            return {
                "sessions": len(sessions),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "requests": sum(s.requests for s in sessions),
                "delta_requests": sum(s.delta_requests for s in sessions),
                "upload_bytes": sum(s.upload_bytes for s in sessions),
                "fetch_bytes": sum(s.fetch_bytes for s in sessions),
            }
