"""ShardedGraphEngine: the multi-device engine behind the analyze boundary.

SURVEY.md §2.9 requires the node-sharded propagation to "live behind
``BaseAgent.analyze()``", not be a parallel API only tests can reach.  This
module closes that gap: :class:`ShardedGraphEngine` exposes the exact
:class:`rca_tpu.engine.runner.GraphEngine` interface (``analyze_arrays`` /
``analyze_features`` / ``analyze_snapshot`` / ``analyze_case``) but executes
through :mod:`rca_tpu.parallel.sharded` — nodes sharded over the mesh's
'sp' axis with all_gather / psum_scatter collectives riding ICI, the
cross-shard top-k merged on device.  :func:`make_engine` is the auto
selector the correlation path calls: sharded when ``RCA_SHARD`` asks for it
or more than one device is visible, single-device otherwise.

Shape discipline matches the dense engine: the node axis pads to the same
``RCAConfig.shape_buckets`` tier (then up to a multiple of sp) and the
per-shard edge rows pad to a bucketed length, so jit compiles once per
(mesh, tier) — not once per graph.

The reference has no analog (it is serial Python end to end, reference:
agents/mcp_coordinator.py:624-665); scores are parity-locked to the dense
engine by tests/test_parallel.py and the coordinator parity gates running
under ``RCA_SHARD`` on the virtual 8-device CPU mesh.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for, env_str
from rca_tpu.engine.propagate import PropagationParams
from rca_tpu.engine.runner import (
    EngineAPI,
    EngineResult,
    finite_mask_rows_np,
    render_result,
    resolve_params,
    timed_fetch,
)


class ShardConfigError(ValueError):
    """A misconfigured RCA_SHARD (malformed spec, impossible device
    count): an OPERATOR error the correlation path surfaces loudly, unlike
    runtime engine failures which degrade to the deterministic backend."""


def parse_shard_spec(spec: str, n_devices: int) -> Dict[str, int]:
    """``"sp=4,dp=2"`` → {"sp": 4, "dp": 2}; ``"auto"``/``"1"`` put every
    device on the node axis (dp=1 — the analyze path ranks ONE snapshot, so
    hypothesis parallelism would only tile redundant work)."""
    spec = (spec or "").strip().lower()
    if spec in ("", "auto", "1", "on", "true"):
        return {"sp": n_devices, "dp": 1}
    axes: Dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        # isdecimal, not isdigit: isdigit admits superscripts that int()
        # then rejects with a plain ValueError the fail-loudly handler
        # would not match; and either alone admits 0, which dies far away
        # (empty mesh / divide-by-sp) instead of here with a clear message
        if key not in ("sp", "dp") or not val.strip().isdecimal() \
                or int(val) < 1:
            raise ShardConfigError(
                f"RCA_SHARD={spec!r}: expected 'auto' or "
                "'sp=<positive n>[,dp=<positive n>]'"
            )
        axes[key] = int(val)
    axes.setdefault("sp", max(1, n_devices // axes.get("dp", 1)))
    axes.setdefault("dp", 1)
    return axes


class ShardedGraphEngine(EngineAPI):
    """Multi-device twin of :class:`GraphEngine` (same call surface)."""

    def __init__(
        self,
        config: Optional[RCAConfig] = None,
        params: Optional[PropagationParams] = None,
        mesh=None,
        spec: Optional[str] = None,
        resident: Optional[bool] = None,
    ):
        from rca_tpu.parallel.mesh import make_mesh

        # same persistent-compile-cache hook as the dense engine: the
        # sharded tick executables are the most expensive compiles in the
        # codebase (tens of seconds at 50k), exactly what a warm
        # RCA_COMPILE_CACHE dir turns into a disk read
        from rca_tpu.config import enable_compile_cache

        enable_compile_cache()
        self.config = config or RCAConfig()
        self.params = resolve_params(self.config, params)
        if mesh is None:
            devices = jax.devices()
            if spec is None:
                # single source for the env token semantics: off-tokens
                # (0/off/single/...) mean "the CALLER asked for sharding
                # anyway, use the auto layout" — constructing this class
                # IS the request, so they must not crash the parse
                _, env_spec = shard_requested()
                spec = env_spec or "auto"
            axes = parse_shard_spec(spec, len(devices))
            need = axes["sp"] * axes["dp"]
            if need > len(devices):
                raise ShardConfigError(
                    f"RCA_SHARD wants {need} devices "
                    f"(sp={axes['sp']},dp={axes['dp']}), have {len(devices)}"
                )
            # sp innermost so node-shard collectives ride ICI neighbors
            mesh = make_mesh(
                [("dp", axes["dp"]), ("sp", axes["sp"])], devices[:need]
            )
        self.mesh = mesh
        self.sp = int(self.mesh.shape["sp"])
        self.dp = int(self.mesh.shape["dp"])
        self.engine_tag = f"sharded(dp={self.dp},sp={self.sp})"
        # the analyze path ranks ONE snapshot — the dp axis is for batch
        # workloads (training, hypothesis sweeps) that a single snapshot
        # cannot fill.  Execute on a dp=1 sub-mesh (the first sp-row of
        # devices) instead of tiling dp redundant copies of the features
        # through the upload and the propagation lanes.
        if self.dp == 1:
            self._exec_mesh = self.mesh
        else:
            from rca_tpu.parallel.mesh import make_mesh as _mm

            self._exec_mesh = _mm(
                [("dp", 1), ("sp", self.sp)],
                list(np.asarray(self.mesh.devices).reshape(-1)[: self.sp]),
            )
        # device-resident one-shot sessions (ISSUE 8 satellite — PR 6's
        # named leftover): repeat analyze calls over a known graph scatter
        # only their changed rows into the mesh-pinned feature batch
        # instead of restaging it.  Same knob, cache, and bit-parity
        # contract as the dense engine's resident path.
        from rca_tpu.config import resident_enabled

        self._resident_cache = None
        if resident if resident is not None else resident_enabled():
            from rca_tpu.engine.resident import ResidentCache
            from rca_tpu.parallel.sharded import ShardedResidentSession

            self._resident_cache = ResidentCache(
                self, session_factory=ShardedResidentSession
            )

    # -- core --------------------------------------------------------------
    def _shard(self, n: int, dep_src: np.ndarray, dep_dst: np.ndarray):
        from rca_tpu.parallel.sharded import shard_graph

        buckets = self.config.shape_buckets
        # same node tier as the dense engine (dummy-slot convention
        # included, for identical bucket boundaries), then up to a
        # multiple of sp inside shard_graph
        n_pad_to = bucket_for(n + 1, buckets)
        return shard_graph(
            n, np.asarray(dep_src, np.int32), np.asarray(dep_dst, np.int32),
            self.sp, n_pad_to=n_pad_to,
            e_pad_fn=lambda e: bucket_for(e, buckets),
        )

    def analyze_arrays(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        k: Optional[int] = None,
        timed: bool = False,
    ) -> EngineResult:
        from rca_tpu.parallel.sharded import sharded_topk, stage_sharded

        n = features.shape[0]
        k = k or min(self.config.top_k_root_causes, n)
        # resident fast path (ISSUE 8 satellite): a repeat request over a
        # known graph digest scatters its dirty rows into the mesh-pinned
        # batch and restages nothing — bit-identical to the staging path
        # below (property-tested).  The timed path keeps the restaged
        # methodology so latency figures stay comparable across rounds.
        if self._resident_cache is not None and not timed:
            return self._resident_cache.analyze(
                features, dep_src, dep_dst, names, k,
            )
        # finite-mask guard: host-side here (the features are being staged
        # from host anyway), same zeroing semantics as the dense engine's
        # fused on-device pass — score parity holds under poisoned input
        features, n_bad = finite_mask_rows_np(features)
        graph = self._shard(n, dep_src, dep_dst)
        f = np.zeros((graph.n_pad, features.shape[1]), np.float32)
        f[:n] = features
        batch = f[None]  # B=1 on the dp=1 execution mesh
        kk = min(k + 8, graph.n_pad)
        # upload ONCE, outside the (possibly repeated) timed invocations —
        # same methodology as the dense engine, so the two latency_ms
        # figures stay comparable
        mesh = self._exec_mesh
        invoke = stage_sharded(mesh, batch, graph, self.params)

        from rca_tpu.parallel.sharded import batch_topk_diag

        def run():
            stack = invoke()
            vals, idx = sharded_topk(mesh, stack[:, 3], kk)
            diag = batch_topk_diag(stack, idx)
            # squeeze the B=1 axis on DEVICE so the fetch carries one copy
            return stack[0], diag[0], vals[0], idx[0], n_bad

        stack, diag, vals, idx, n_bad, latency_ms = timed_fetch(run, timed)
        from rca_tpu.engine.runner import make_attribution_ctx

        return render_result(
            diag, np.asarray(vals), np.asarray(idx),
            names, n, k, latency_ms, int(len(dep_src)),
            engine=self.engine_tag, sanitized_rows=n_bad,
            stacked_dev=stack,
            attribution_ctx=make_attribution_ctx(
                features, dep_src, dep_dst, self.params, names,
                self.config.shape_buckets,
            ),
        )

    def analyze_batch(
        self,
        features_batch: np.ndarray,   # [B, S, C], one graph
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names=None,
        k=None,
    ):
        """Hypothesis batch on the FULL mesh: hypotheses shard over 'dp'
        (BASELINE.json "pmap over fault candidates"), nodes over 'sp'.
        The batch pads up to a multiple of dp with zero hypotheses that
        are dropped from the result."""
        import time as _time

        from rca_tpu.parallel.sharded import stage_batch_ranked

        B, n = features_batch.shape[0], features_batch.shape[1]
        k = k or min(self.config.top_k_root_causes, n)
        features_batch, n_bad = finite_mask_rows_np(features_batch)
        graph = self._shard(n, dep_src, dep_dst)
        B_pad = -(-B // self.dp) * self.dp
        fb = np.zeros((B_pad, graph.n_pad, features_batch.shape[2]),
                      np.float32)
        fb[:B, :n] = features_batch
        kk = min(k + 8, graph.n_pad)
        t0 = _time.perf_counter()
        stack, diag, vals, idx = stage_batch_ranked(
            self.mesh, fb, graph, self.params, kk
        )
        # top-k-sized fetch only: the [B, 4, n_pad] stack stays sharded
        # on device behind each lane's lazy diagnostics (ISSUE 6)
        diag, vals, idx = jax.device_get((diag, vals, idx))
        latency_ms = (_time.perf_counter() - t0) * 1e3
        from rca_tpu.engine.runner import make_attribution_ctx

        return [
            render_result(
                diag[b], vals[b], idx[b], names, n, k,
                latency_ms / B, int(len(dep_src)),
                engine=self.engine_tag + "-batch", sanitized_rows=n_bad,
                stacked_dev=stack[b],
                attribution_ctx=make_attribution_ctx(
                    features_batch[b], dep_src, dep_dst, self.params,
                    names, self.config.shape_buckets,
                ),
            )
            for b in range(B)
        ]


def shard_requested() -> Tuple[bool, Optional[str]]:
    """(use sharded engine?, spec) from ``RCA_SHARD`` + visible devices.

    ``RCA_SHARD`` unset/empty: shard automatically when more than one
    device is visible (SURVEY §2.9: multi-device execution is the default
    posture on multi-chip hosts, behind the same analyze boundary).
    ``RCA_SHARD=0/off/single`` forces the single-device engine;
    anything else ("auto", "sp=4,dp=2") forces sharding with that layout.
    """
    spec = env_str("RCA_SHARD", "", lower=True)
    if spec in ("0", "off", "single", "none", "false"):
        return False, None
    if spec:
        return True, spec
    return len(jax.devices()) > 1, "auto"


def make_engine(
    config: Optional[RCAConfig] = None,
    params: Optional[PropagationParams] = None,
):
    """The engine the analyze path should use RIGHT NOW (env + devices)."""
    from rca_tpu.engine.runner import GraphEngine

    use_sharded, spec = shard_requested()
    if use_sharded:
        return ShardedGraphEngine(config=config, params=params, spec=spec)
    return GraphEngine(config=config, params=params)
