"""causelens core: batched on-device evidence attribution (ISSUE 14).

Every ranking the engine produces says "checkout, score 0.93" — this
module says WHY, decomposing :func:`rca_tpu.engine.propagate.
combine_score` for each top-k candidate into the terms that built it:

- **channel contributions**: the noisy-OR is a product of per-channel
  survival factors, so each channel's contribution ``w_c · clip(f_c)``
  (plus the round-5 error-contrast term, which folds in as a 14th
  channel) reconstructs the anomaly evidence EXACTLY — and
  ``a · impact_factor · suppression_factor`` reconstructs the combined
  score.  The completeness axiom (per-channel contributions reconstruct
  ``combine_score`` within 1e-5 for the float32 kernels) is
  property-tested in tests/test_causelens.py;
- **counterfactual evidence rows**: re-propagate with each of the top-M
  evidence rows masked (vectorized over the masks via vmap, one fused
  dispatch) and record each candidate's score drop — "which service's
  evidence is this ranking actually standing on";
- **blame paths**: per candidate, a greedy walk over the dependency
  edges following the up-scan's own term (``max(h_d, γ·u_d)``) — the
  exact quantity explain-away propagated, so the path names the edges
  that suppressed (or failed to suppress) the candidate;
- **gradient saliency**: ``∂(Σ top-k score)/∂features`` over the same
  traced propagation body, per-candidate channel gradients plus the
  top-M rows by gradient norm (a second opinion on the counterfactuals
  that costs one backward pass instead of M propagations).

Dispatch discipline: the sweep asks the :class:`rca_tpu.engine.registry.
KernelRegistry` for its kernel as a first-class ``attribution`` variant
(the counterfactual/gradient body re-propagates through the
differentiable xla path; quantized/pallas/doubling record WHY they are
ineligible), records its per-shape wall cost into the registry row, and
fetches only top-k/top-m-sized results — the full masked-score matrix
never leaves the device.  graftlint's ``kernel-dispatch`` rule guards
``attribution_sweep``/``attribution_saliency`` exactly like the kernel
bodies: callers go through :func:`compute_attribution`.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import (
    RCAConfig,
    bucket_for,
    explain_paths,
    explain_topm,
)
from rca_tpu.features.schema import SERVICE_FEATURE_NAMES, SvcF

#: provenance block schema (bumped whenever the block layout changes —
#: consumers check it before parsing; replay digests embed it)
ATTRIBUTION_SCHEMA = 1


@dataclasses.dataclass
class AttributionContext:
    """Everything a lazy ``EngineResult.attribution()`` needs to compute
    the provenance block after the fact: the RAW request arrays plus the
    engine's resolved params.  Arrays are the caller's own copies (the
    serve request already copied at construction; the engines pass the
    arrays they analyzed)."""

    features: np.ndarray             # [S, C] raw request features (host)
    dep_src: np.ndarray              # [E] int32
    dep_dst: np.ndarray              # [E] int32
    params: Any                      # engine.propagate.PropagationParams
    names: Optional[Sequence[str]] = None
    shape_buckets: tuple = RCAConfig.shape_buckets


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus",
        "error_contrast", "kernel", "path_len",
    ),
)
def attribution_sweep(
    features, edges, anomaly_w, hard_w, cand_idx, mask_rows,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    error_contrast: float = 0.0, kernel: str = "xla", path_len: int = 4,
    n_live=None, up_ell=None,
):
    """One fused attribution dispatch: the base propagation, the M-lane
    counterfactual vmap, and the per-candidate blame-path walk.  Returns
    top-k/top-m-sized device values only (ISSUE 6 discipline):

    - ``diag``       [5, K]  (a, h, u, m, score) at the candidates;
    - ``deltas``     [M, K]  base score minus the score with evidence
                             row ``mask_rows[j]`` zeroed;
    - ``path_edge``  [K, P]  edge index per hop (-1 = walk stopped);
    - ``path_term``  [K, P]  the up-term ``max(h_d, γ·u_d)`` that chose
                             the hop;
    - ``path_dst``   [K, P]  the blamed dependency per hop;
    - ``path_hard`` / ``path_up``  [K, P]  h / u at that dependency.
    """
    from rca_tpu.engine.propagate import finite_mask_rows
    from rca_tpu.engine.runner import propagate_auto

    features, _ = finite_mask_rows(features)

    def run(f):
        return propagate_auto(
            f, edges, anomaly_w, hard_w,
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
            up_ell=up_ell, error_contrast=error_contrast, kernel=kernel,
        )

    a, h, u, m, score = run(features)
    diag = jnp.stack([a, h, u, m, score])[:, cand_idx]

    def masked(row):
        # the counterfactual: this evidence row contributes nothing
        return run(features.at[row].set(0.0))[4][cand_idx]

    deltas = score[cand_idx][None, :] - jax.vmap(masked)(mask_rows)

    # blame-path walk: at each hop follow the dependency edge whose
    # up-term is largest — the same quantity the up-scan propagated, so
    # the path is the explain-away chain itself, not a heuristic
    n_edges = edges.shape[1]

    def walk(c0):
        def step(cur, _):
            term = jnp.where(
                edges[0] == cur,
                jnp.maximum(h[edges[1]], decay * u[edges[1]]),
                -jnp.inf,
            )
            j = jnp.argmax(term)
            t = term[j]
            live = t > 0.0
            return (
                jnp.where(live, edges[1][j], cur),
                (jnp.where(live, j, -1), jnp.where(live, t, 0.0)),
            )

        _, (ej, tv) = jax.lax.scan(step, c0, None, length=path_len)
        return ej, tv

    path_edge, path_term = jax.vmap(walk)(cand_idx)
    pe = jnp.clip(path_edge, 0, n_edges - 1)
    path_dst = edges[1][pe]
    return (diag, deltas, path_edge, path_term, path_dst,
            h[path_dst], u[path_dst])


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus",
        "error_contrast", "kernel", "m",
    ),
)
def attribution_saliency(
    features, edges, anomaly_w, hard_w, cand_idx,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    error_contrast: float = 0.0, kernel: str = "xla", m: int = 8,
    n_live=None, up_ell=None,
):
    """Gradient saliency over the propagation core: ``∂(Σ candidate
    scores)/∂features``, returning the candidates' own channel gradients
    [K, C] plus the top-``m`` rows by gradient L1 norm."""
    from rca_tpu.engine.propagate import finite_mask_rows
    from rca_tpu.engine.runner import propagate_auto

    def total(f):
        f, _ = finite_mask_rows(f)
        score = propagate_auto(
            f, edges, anomaly_w, hard_w,
            steps, decay, explain_strength, impact_bonus, n_live=n_live,
            up_ell=up_ell, error_contrast=error_contrast, kernel=kernel,
        )[4]
        return jnp.sum(score[cand_idx])

    sal = jax.grad(total)(features)
    row_norm = jnp.sum(jnp.abs(sal), axis=1)
    vals, idx = jax.lax.top_k(row_norm, m)
    return sal[cand_idx], vals, idx


def _error_source_excess_np(clipped: np.ndarray, dep_src, dep_dst):
    """Host twin of :func:`rca_tpu.engine.propagate.error_source_excess`
    over ALREADY-clipped features — the channel-decomposition mirror for
    the error-contrast pseudo-channel."""
    e = clipped[:, SvcF.ERROR_RATE].astype(np.float32)
    dep_max = np.zeros_like(e)
    src = np.asarray(dep_src, np.int64)
    dst = np.asarray(dep_dst, np.int64)
    if len(src):
        np.maximum.at(dep_max, src, e[dst])
    return np.maximum(e - dep_max, 0.0)


def _f32(x) -> float:
    return float(np.float32(x))


def compute_attribution(
    ctx: AttributionContext,
    ranked: List[dict],
    k: Optional[int] = None,
    paths: Optional[int] = None,
    topm: Optional[int] = None,
) -> Dict[str, Any]:
    """The host attribution entry point: pad like the engine, resolve
    the ``attribution`` registry variant, run the fused sweep + saliency,
    and assemble the schema-versioned provenance block.  ``ranked`` is
    the engine's rendered ranking (the candidates to explain); entries
    whose component is not a live service are skipped.

    The block is fully deterministic for a given (features, edges,
    params) on one platform — no wall times inside — which is what lets
    ``rca replay --explain`` parity-check digests against the tape."""
    from rca_tpu.engine.registry import engaged_kernel, get_registry
    from rca_tpu.engine.runner import finite_mask_rows_np, up_ell_for

    t0 = time.perf_counter()
    p = ctx.params
    feats = np.asarray(ctx.features, np.float32)
    n = int(feats.shape[0])
    names = (
        list(ctx.names) if ctx.names is not None
        else [f"svc-{i}" for i in range(n)]
    )
    paths = explain_paths() if paths is None else max(1, int(paths))
    topm = explain_topm() if topm is None else max(1, int(topm))
    index = {nm: i for i, nm in enumerate(names)}
    cand = [
        index[r["component"]] for r in ranked
        if r.get("component") in index
    ]
    if k is not None:
        cand = cand[: max(1, int(k))]
    block: Dict[str, Any] = {
        "schema": ATTRIBUTION_SCHEMA,
        "k": len(cand), "topm": int(topm), "paths": int(paths),
        "n_services": n, "n_edges": int(len(ctx.dep_src)),
        "candidates": [],
    }
    from rca_tpu.engine.propagate import SCORE_FORMULA_VERSION

    block["score_formula_version"] = SCORE_FORMULA_VERSION
    if not cand:
        block["kernel"] = None
        block["evidence_rows"] = []
        return block

    # pad exactly like GraphEngine._pad (same tiers, same dummy slot)
    n_pad = bucket_for(n + 1, ctx.shape_buckets)
    e_pad = bucket_for(max(len(ctx.dep_src), 1), ctx.shape_buckets)
    dummy = n_pad - 1
    f = np.zeros((n_pad, feats.shape[1]), np.float32)
    f[:n] = feats
    s = np.full(e_pad, dummy, np.int32)
    d = np.full(e_pad, dummy, np.int32)
    s[: len(ctx.dep_src)] = np.asarray(ctx.dep_src, np.int32)
    d[: len(ctx.dep_dst)] = np.asarray(ctx.dep_dst, np.int32)

    # THE dispatch seam, as its own registry variant (ISSUE 14): the row
    # names the engaged kernel (xla — the differentiable body) and WHY
    # every other kernel sat out; the wall cost lands in its timings
    kernel = engaged_kernel(
        n_pad, e_pad=e_pad, steps=p.steps, variant="attribution",
    )
    block["kernel"] = kernel
    up_ell = up_ell_for(
        n_pad, np.asarray(ctx.dep_src, np.int32),
        np.asarray(ctx.dep_dst, np.int32),
    )
    aw, hw = p.weight_arrays()
    aw_np = np.asarray(aw, np.float32)
    hw_np = np.asarray(hw, np.float32)

    # host channel decomposition over the SANITIZED features (mirrors
    # the fused finite-mask pass, so a poisoned row contributes zero on
    # both sides)
    clean, _ = finite_mask_rows_np(feats)
    clipped = np.clip(clean, 0.0, 1.0).astype(np.float32)
    err = _error_source_excess_np(clipped, ctx.dep_src, ctx.dep_dst)
    a0 = (1.0 - np.prod(
        np.float32(1.0) - clipped * aw_np[None, :], axis=1,
        dtype=np.float32,
    )).astype(np.float32)
    if p.error_contrast:
        a_host = (1.0 - (1.0 - a0)
                  * (1.0 - np.float32(p.error_contrast) * err)
                  ).astype(np.float32)
    else:
        a_host = a0

    # counterfactual mask set: the top-M evidence rows by anomaly (the
    # rows the ranking could be standing on), stable order for replay
    m_rows = int(min(topm, n))
    mask_rows = np.argsort(-a_host, kind="stable")[:m_rows].astype(np.int32)

    cand_arr = np.asarray(cand, np.int32)
    n_live = jnp.asarray(n, jnp.int32)
    edges_j = jnp.asarray(np.stack([s, d]))
    out = attribution_sweep(
        jnp.asarray(f), edges_j, aw, hw,
        jnp.asarray(cand_arr), jnp.asarray(mask_rows),
        p.steps, p.decay, p.explain_strength, p.impact_bonus,
        error_contrast=p.error_contrast, kernel=kernel,
        path_len=paths, n_live=n_live, up_ell=up_ell,
    )
    (diag, deltas, path_edge, path_term, path_dst, path_hard,
     path_up) = jax.device_get(out)

    sal_cand = sal_vals = sal_idx = None
    saliency_note = None
    try:
        sal_cand, sal_vals, sal_idx = jax.device_get(attribution_saliency(
            jnp.asarray(f), edges_j, aw, hw, jnp.asarray(cand_arr),
            p.steps, p.decay, p.explain_strength, p.impact_bonus,
            error_contrast=p.error_contrast, kernel=kernel,
            m=min(m_rows, n_pad), n_live=n_live, up_ell=up_ell,
        ))
    except Exception as exc:  # noqa: BLE001 - saliency is best-effort
        # a backend without the needed gradient rules still gets the
        # counterfactual/channel attribution; the block says why
        saliency_note = f"{type(exc).__name__}: {exc}"

    block["evidence_rows"] = [
        {"row": int(r), "component": names[int(r)],
         "anomaly": _f32(a_host[int(r)])}
        for r in mask_rows
    ]
    for rank, i in enumerate(cand):
        a_dev, h_v, u_v, m_v, score = (
            _f32(diag[0, rank]), _f32(diag[1, rank]),
            _f32(diag[2, rank]), _f32(diag[3, rank]),
            _f32(diag[4, rank]),
        )
        channels = []
        for c, cname in enumerate(SERVICE_FEATURE_NAMES):
            contrib = float(np.float32(aw_np[c] * clipped[i, c]))
            if contrib == 0.0 and clipped[i, c] == 0.0:
                continue
            channels.append({
                "channel": cname,
                "value": _f32(clipped[i, c]),
                "weight": _f32(aw_np[c]),
                "hard_weight": _f32(hw_np[c]),
                "contribution": contrib,
            })
        if p.error_contrast:
            channels.append({
                "channel": "error_contrast",
                "value": _f32(err[i]),
                "weight": _f32(p.error_contrast),
                "hard_weight": 0.0,
                "contribution": _f32(np.float32(p.error_contrast)
                                     * err[i]),
            })
        # the completeness axiom: the channel survival product rebuilds
        # a, and a · impact_factor · suppression_factor rebuilds score
        surv = np.float32(1.0)
        for ch in channels:
            surv = np.float32(surv * np.float32(1.0 - ch["contribution"]))
        a_rec = float(np.float32(1.0) - surv)
        impact_factor = 1.0 + float(p.impact_bonus) * float(np.tanh(m_v))
        suppression = 1.0 - (float(p.explain_strength) * u_v * (1.0 - h_v))
        reconstructed = a_rec * impact_factor * suppression
        counterfactuals = sorted(
            (
                {
                    "row": int(mask_rows[j]),
                    "component": names[int(mask_rows[j])],
                    "self": bool(int(mask_rows[j]) == i),
                    "score_drop": _f32(deltas[j, rank]),
                }
                for j in range(m_rows)
            ),
            key=lambda e: -e["score_drop"],
        )
        path = []
        for hop in range(paths):
            if int(path_edge[rank, hop]) < 0:
                break
            path.append({
                "to": names[int(path_dst[rank, hop])]
                if int(path_dst[rank, hop]) < n
                else f"row-{int(path_dst[rank, hop])}",
                "row": int(path_dst[rank, hop]),
                "term": _f32(path_term[rank, hop]),
                "hard": _f32(path_hard[rank, hop]),
                "upstream": _f32(path_up[rank, hop]),
            })
        entry: Dict[str, Any] = {
            "component": names[i], "row": int(i), "rank": rank + 1,
            "score": score,
            "anomaly": a_dev, "hard": h_v, "upstream": u_v,
            "impact_mean": m_v,
            "factors": {
                "evidence": a_rec,
                "impact": _f32(impact_factor),
                "suppression": _f32(suppression),
            },
            "channels": channels,
            "reconstructed_score": _f32(reconstructed),
            "reconstruction_error": _f32(abs(reconstructed - score)),
            "counterfactuals": counterfactuals,
            "blame_path": path,
        }
        if sal_cand is not None:
            grads = {
                SERVICE_FEATURE_NAMES[c]: _f32(sal_cand[rank, c])
                for c in range(sal_cand.shape[1])
                if float(sal_cand[rank, c]) != 0.0
            }
            entry["saliency"] = {"channels": grads}
        block["candidates"].append(entry)
    if sal_idx is not None:
        block["saliency_rows"] = [
            {"row": int(r), "component": names[int(r)]
             if int(r) < n else f"row-{int(r)}",
             "grad_l1": _f32(v)}
            for v, r in zip(sal_vals, sal_idx)
            if int(r) < n and float(v) != 0.0
        ]
    elif saliency_note is not None:
        block["saliency_unavailable"] = saliency_note
    # per-shape cost telemetry: the wall cost of THIS attribution lands
    # in the registry row's timings (bench's attribution section and
    # `rca kernels` read it) — never inside the block, which must stay
    # deterministic for replay digests
    get_registry().note_timing(
        n_pad, e_pad, "attribution",
        (time.perf_counter() - t0) * 1e3,
        variant="attribution", steps=p.steps,
    )
    return block
