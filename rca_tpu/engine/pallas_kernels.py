"""Pallas TPU kernels for the engine's feature-matrix hot path.

The propagation pipeline reads the [S, C] feature matrix twice (anomaly and
hard-evidence noisy-ORs).  :func:`noisy_or_pair_pallas` fuses both
noisy-ORs into ONE blocked pass over the channel-major [C, S] layout —
full 128-lane utilization, each feature element read once.

MEASURED VERDICT (v5e, 65k services, in-jit amortized — recorded by
bench.py as ``pallas_noisyor_50k_ms`` vs ``xla_noisyor_50k_ms``): the
fused kernel compiles, runs, and matches XLA numerically, but is a WASH
(±2%) — XLA's own fusion already makes the evidence pass ~1.2 ms of a
~41 ms 50k pipeline.  The pipeline's real cost is the per-step edge
gather/scatter in the propagation scans (~1.8 ms/step at 100k edges,
scalar-unit bound), and that cannot be moved into Pallas on this stack:
Mosaic has no TPU lowering for scatter-add and only a same-rank 2D
gather (probed: ``NotImplementedError: scatter-add`` / "Only 2D gather
is supported").  The kernel is therefore an explicit OPT-IN
(``RCA_PALLAS=1``); the default engine path stays XLA.  ``RCA_PALLAS=0``
disables even the probe; CPU tests run the kernel in interpret mode.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from rca_tpu.config import env_str
from rca_tpu.engine.propagate import _noisy_or

BLOCK_S = 1024


def _pair_kernel(ft_ref, aw_ref, hw_ref, a_ref, h_ref):
    # channel product unrolled (C is static and small; Mosaic has no
    # reduce_prod lowering) — one clipped read per feature element feeds
    # BOTH products
    C = ft_ref.shape[0]
    prod_a = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    prod_h = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    for c in range(C):
        f = jnp.clip(ft_ref[c : c + 1, :], 0.0, 1.0)
        prod_a = prod_a * (1.0 - f * aw_ref[c, 0])
        prod_h = prod_h * (1.0 - f * hw_ref[c, 0])
    a_ref[:, :] = 1.0 - prod_a
    h_ref[:, :] = 1.0 - prod_h


@functools.partial(jax.jit, static_argnames=("interpret",))
def noisy_or_pair_pallas(features_t, anomaly_w, hard_w, interpret=False):
    """(anomaly, hard) noisy-OR vectors from channel-major features.

    ``features_t``: float32 [C, S] with S a power of two (block size adapts
    to min(S, BLOCK_S)).
    """
    from jax.experimental import pallas as pl

    C, S = features_t.shape
    block = min(S, BLOCK_S)
    grid = (S // block,)
    out = pl.pallas_call(
        _pair_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((C, block), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i)),
            pl.BlockSpec((1, block), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, S), jnp.float32),
            jax.ShapeDtypeStruct((1, S), jnp.float32),
        ],
        interpret=interpret,
    )(features_t, anomaly_w[:, None], hard_w[:, None])
    return out[0][0], out[1][0]


def noisy_or_pair_xla(features, anomaly_w, hard_w):
    """Reference implementation on row-major [S, C] features (the same
    expression the propagation core uses — one definition, propagate.py)."""
    return _noisy_or(features, anomaly_w), _noisy_or(features, hard_w)


def pallas_supported() -> bool:
    """Whether the fused kernel COMPILES on the active backend:
    ``RCA_PALLAS=0`` disables, anything else try-compiles once and caches
    the verdict (``RCA_PALLAS=1`` raises if the probe fails).  Note this is
    a capability probe only — whether the engine routes through the kernel
    is a separate opt-in decision (:func:`pallas_enabled`), because the
    measured result on real TPU is a wash (module docstring)."""
    global _SUPPORTED
    flag = env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1"))
    if flag == "0":
        return False
    if _SUPPORTED is None:
        try:
            ft = jnp.zeros((2, BLOCK_S), jnp.float32)
            w = jnp.zeros(2, jnp.float32)
            a, h = noisy_or_pair_pallas(ft, w, w)
            a.block_until_ready()
            _SUPPORTED = True
        except Exception:
            _SUPPORTED = False
    if flag == "1" and not _SUPPORTED:
        raise RuntimeError(
            "RCA_PALLAS=1 but the Pallas kernel failed to compile on this "
            "backend (set RCA_PALLAS=auto to fall back silently)"
        )
    return _SUPPORTED


_SUPPORTED = None


def pallas_enabled() -> bool:
    """Whether the ENGINE should route evidence through the fused kernel.
    Opt-in (``RCA_PALLAS=1``) because the kernel measures as a wash vs XLA
    on real TPU (module docstring) — capability is kept and proven by
    tests/bench, but the default hot path stays with XLA's fusion."""
    return (
        env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1")) == "1"
        and pallas_supported()
    )


def noisyor_autotune(refresh: bool = False) -> str:
    """RETIRED (ISSUE 14 satellite; deprecation stamped in ISSUE 13) —
    a thin alias kept ONLY for external/test importers.  The per-shape
    registry (:func:`rca_tpu.engine.registry.engaged_kernel`) is the
    real surface; every internal stamp of this process-level answer
    (the streaming sessions' ``noisyor_path``, health records, span
    attributes, bench, ``rca profile``) is gone — per-shape
    ``kernel_path`` says strictly more.  The ``kernel-dispatch`` lint
    flags calls to this alias anywhere inside ``rca_tpu/``."""
    import warnings

    warnings.warn(
        "noisyor_autotune() is retired: ask the per-shape registry "
        "(rca_tpu.engine.registry.engaged_kernel / autotune_path)",
        DeprecationWarning, stacklevel=2,
    )
    from rca_tpu.engine.registry import autotune_path

    return autotune_path(refresh=refresh)


def noisyor_path():
    """RETIRED twin of :func:`noisyor_autotune` (alias for external/
    test importers): the cached process-level choice, or None — use
    :func:`rca_tpu.engine.registry.autotuned_path`."""
    import warnings

    warnings.warn(
        "noisyor_path() is retired: use "
        "rca_tpu.engine.registry.autotuned_path()",
        DeprecationWarning, stacklevel=2,
    )
    from rca_tpu.engine.registry import autotuned_path

    return autotuned_path()
