"""Pallas TPU kernels for the engine's feature-matrix hot path.

The propagation pipeline reads the [S, C] feature matrix twice (anomaly and
hard-evidence noisy-ORs).  :func:`noisy_or_pair_pallas` fuses both
noisy-ORs into ONE blocked pass over the channel-major [C, S] layout —
full 128-lane utilization, each feature element read once.

MEASURED VERDICT (v5e, 65k services, in-jit amortized — recorded by
bench.py as ``pallas_noisyor_50k_ms`` vs ``xla_noisyor_50k_ms``): the
fused kernel compiles, runs, and matches XLA numerically, but is a WASH
(±2%) — XLA's own fusion already makes the evidence pass ~1.2 ms of a
~41 ms 50k pipeline.  The pipeline's real cost is the per-step edge
gather/scatter in the propagation scans (~1.8 ms/step at 100k edges,
scalar-unit bound), and that cannot be moved into Pallas on this stack:
Mosaic has no TPU lowering for scatter-add and only a same-rank 2D
gather (probed: ``NotImplementedError: scatter-add`` / "Only 2D gather
is supported").  The kernel is therefore an explicit OPT-IN
(``RCA_PALLAS=1``); the default engine path stays XLA.  ``RCA_PALLAS=0``
disables even the probe; CPU tests run the kernel in interpret mode.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from rca_tpu.config import env_str
from rca_tpu.engine.propagate import _noisy_or

BLOCK_S = 1024


def _pair_kernel(ft_ref, aw_ref, hw_ref, a_ref, h_ref):
    # channel product unrolled (C is static and small; Mosaic has no
    # reduce_prod lowering) — one clipped read per feature element feeds
    # BOTH products
    C = ft_ref.shape[0]
    prod_a = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    prod_h = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    for c in range(C):
        f = jnp.clip(ft_ref[c : c + 1, :], 0.0, 1.0)
        prod_a = prod_a * (1.0 - f * aw_ref[c, 0])
        prod_h = prod_h * (1.0 - f * hw_ref[c, 0])
    a_ref[:, :] = 1.0 - prod_a
    h_ref[:, :] = 1.0 - prod_h


@functools.partial(jax.jit, static_argnames=("interpret",))
def noisy_or_pair_pallas(features_t, anomaly_w, hard_w, interpret=False):
    """(anomaly, hard) noisy-OR vectors from channel-major features.

    ``features_t``: float32 [C, S] with S a power of two (block size adapts
    to min(S, BLOCK_S)).
    """
    from jax.experimental import pallas as pl

    C, S = features_t.shape
    block = min(S, BLOCK_S)
    grid = (S // block,)
    out = pl.pallas_call(
        _pair_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((C, block), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i)),
            pl.BlockSpec((1, block), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, S), jnp.float32),
            jax.ShapeDtypeStruct((1, S), jnp.float32),
        ],
        interpret=interpret,
    )(features_t, anomaly_w[:, None], hard_w[:, None])
    return out[0][0], out[1][0]


def noisy_or_pair_xla(features, anomaly_w, hard_w):
    """Reference implementation on row-major [S, C] features (the same
    expression the propagation core uses — one definition, propagate.py)."""
    return _noisy_or(features, anomaly_w), _noisy_or(features, hard_w)


def pallas_supported() -> bool:
    """Whether the fused kernel COMPILES on the active backend:
    ``RCA_PALLAS=0`` disables, anything else try-compiles once and caches
    the verdict (``RCA_PALLAS=1`` raises if the probe fails).  Note this is
    a capability probe only — whether the engine routes through the kernel
    is a separate opt-in decision (:func:`pallas_enabled`), because the
    measured result on real TPU is a wash (module docstring)."""
    global _SUPPORTED
    flag = env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1"))
    if flag == "0":
        return False
    if _SUPPORTED is None:
        try:
            ft = jnp.zeros((2, BLOCK_S), jnp.float32)
            w = jnp.zeros(2, jnp.float32)
            a, h = noisy_or_pair_pallas(ft, w, w)
            a.block_until_ready()
            _SUPPORTED = True
        except Exception:
            _SUPPORTED = False
    if flag == "1" and not _SUPPORTED:
        raise RuntimeError(
            "RCA_PALLAS=1 but the Pallas kernel failed to compile on this "
            "backend (set RCA_PALLAS=auto to fall back silently)"
        )
    return _SUPPORTED


_SUPPORTED = None


def pallas_enabled() -> bool:
    """Whether the ENGINE should route evidence through the fused kernel.
    Opt-in (``RCA_PALLAS=1``) because the kernel measures as a wash vs XLA
    on real TPU (module docstring) — capability is kept and proven by
    tests/bench, but the default hot path stays with XLA's fusion."""
    return (
        env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1")) == "1"
        and pallas_supported()
    )


_AUTOTUNED_PATH = None


def noisyor_autotune(refresh: bool = False) -> str:
    """The noisy-OR combine path sessions should run: ``"xla"`` or
    ``"pallas"``, decided ONCE per process (ISSUE 2 satellite).

    BENCH_r05 showed why a static flag is wrong in both directions:
    ``pallas_supported: true`` yet XLA 4.5x faster on that backend
    (0.0091 vs 0.0414 ms) — so instead of trusting a capability probe, an
    ``RCA_PALLAS=auto`` session TIMES both paths once at first session
    start (two small amortized in-jit loops, fetch-synced per the PERF.md
    methodology) and takes the measured winner.  ``RCA_PALLAS=1`` still
    forces the kernel, ``RCA_PALLAS=0`` forces XLA, and non-accelerator
    backends (CPU tests) short-circuit to XLA without timing — the kernel
    only ever runs interpreted there, and timing an interpreter would
    burn seconds to confirm the obvious.  The choice is recorded by
    bench.py and every streaming tick health record as ``noisyor_path``.
    """
    global _AUTOTUNED_PATH
    if _AUTOTUNED_PATH is not None and not refresh:
        return _AUTOTUNED_PATH
    flag = env_str("RCA_PALLAS", "auto", choices=("auto", "0", "1"))
    if flag == "1":
        # forced: pallas_supported raises loudly if the compile fails
        pallas_supported()
        _AUTOTUNED_PATH = "pallas"
        return _AUTOTUNED_PATH
    if (
        flag == "0"
        or jax.default_backend() == "cpu"
        or not pallas_supported()
    ):
        _AUTOTUNED_PATH = "xla"
        return _AUTOTUNED_PATH
    _AUTOTUNED_PATH = (
        "pallas" if _time_pallas_beats_xla() else "xla"
    )
    return _AUTOTUNED_PATH


def noisyor_path():
    """The autotuned choice, or None when no session has autotuned yet."""
    return _AUTOTUNED_PATH


def engaged_kernel(n_pad: int) -> str:
    """The combine path a session over an ``n_pad``-padded graph
    actually ENGAGES (ISSUE 11 satellite): the autotuner's choice is
    per-process, but the Pallas grid additionally needs the node pad to
    divide into blocks — so ``pallas_engaged: false`` at round level can
    hide a per-shape story.  This is the per-shape answer, stamped into
    streaming health records, dispatch span attributes, and bench's
    ``kernel_by_shape``."""
    n_pad = int(n_pad)
    if noisyor_autotune() != "pallas":
        return "xla"
    return "pallas" if n_pad % min(n_pad, BLOCK_S) == 0 else "xla"


def _time_pallas_beats_xla(s: int = 8192, reps: int = 200) -> bool:
    """One-shot timing of both combine paths on a representative [S, C]
    block: amortized in-jit loops (rep count folds a salt so no transport
    cache can replay), synced by FETCHING a slice — never
    block_until_ready (PERF.md round-1 correction).  Returns whether the
    fused kernel measurably beats XLA's fusion; ties go to XLA (the
    simpler, default-tested path)."""
    import time

    import numpy as np

    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    rng = np.random.default_rng(0)
    f = jnp.asarray(
        rng.uniform(0, 1, (s, NUM_SERVICE_FEATURES)).astype(np.float32)
    )
    ft = f.T
    w = jnp.asarray(
        rng.uniform(0.2, 0.9, NUM_SERVICE_FEATURES).astype(np.float32)
    )

    def timed(fn, arg):
        @jax.jit
        def many(x, salt):
            def body(i, acc):
                a, h = fn(x * (1.0 + salt + i * 1e-7), w, w)
                return acc + a + h
            return jax.lax.fori_loop(0, reps, body, jnp.zeros(s))

        jax.device_get(many(arg, jnp.float32(1e-7))[:4])  # compile
        outs = []
        for j in range(3):
            t0 = time.perf_counter()
            jax.device_get(many(arg, jnp.float32((j + 2) * 1e-7))[:4])
            outs.append(time.perf_counter() - t0)
        return min(outs)

    try:
        t_pallas = timed(noisy_or_pair_pallas, ft)
        t_xla = timed(noisy_or_pair_xla, f)
    except Exception:
        return False  # a path that cannot even time cannot win
    return t_pallas < 0.95 * t_xla
