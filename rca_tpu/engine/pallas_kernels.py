"""Pallas TPU kernels for the engine's feature-matrix hot path.

The propagation pipeline reads the [S, C] feature matrix twice (anomaly and
hard-evidence noisy-ORs).  With C=12 channels the matrix pads 12→128 lanes
(10.7x traffic blowup), making these reads the pipeline's dominant HBM cost
at 50k+ services.  :func:`noisy_or_pair` fuses both noisy-ORs into ONE
blocked pass over the channel-major [C, S] layout — full 128-lane
utilization, each feature element read once.

Falls back to the XLA expression when Pallas/Mosaic is unavailable on the
active backend (``RCA_PALLAS=0`` forces the fallback; CPU tests run the
kernel in interpret mode).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from rca_tpu.engine.propagate import _noisy_or

BLOCK_S = 1024


def _pair_kernel(ft_ref, aw_ref, hw_ref, a_ref, h_ref):
    # channel product unrolled (C is static and small; Mosaic has no
    # reduce_prod lowering) — one clipped read per feature element feeds
    # BOTH products
    C = ft_ref.shape[0]
    prod_a = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    prod_h = jnp.ones((1, ft_ref.shape[1]), jnp.float32)
    for c in range(C):
        f = jnp.clip(ft_ref[c : c + 1, :], 0.0, 1.0)
        prod_a = prod_a * (1.0 - f * aw_ref[c, 0])
        prod_h = prod_h * (1.0 - f * hw_ref[c, 0])
    a_ref[:, :] = 1.0 - prod_a
    h_ref[:, :] = 1.0 - prod_h


@functools.partial(jax.jit, static_argnames=("interpret",))
def noisy_or_pair_pallas(features_t, anomaly_w, hard_w, interpret=False):
    """(anomaly, hard) noisy-OR vectors from channel-major features.

    ``features_t``: float32 [C, S] with S a power of two (block size adapts
    to min(S, BLOCK_S)).
    """
    from jax.experimental import pallas as pl

    C, S = features_t.shape
    block = min(S, BLOCK_S)
    grid = (S // block,)
    out = pl.pallas_call(
        _pair_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((C, block), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i)),
            pl.BlockSpec((1, block), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, S), jnp.float32),
            jax.ShapeDtypeStruct((1, S), jnp.float32),
        ],
        interpret=interpret,
    )(features_t, anomaly_w[:, None], hard_w[:, None])
    return out[0][0], out[1][0]


def noisy_or_pair_xla(features, anomaly_w, hard_w):
    """Reference implementation on row-major [S, C] features (the same
    expression the propagation core uses — one definition, propagate.py)."""
    return _noisy_or(features, anomaly_w), _noisy_or(features, hard_w)


def pallas_supported() -> bool:
    """Whether the fused kernel is usable: ``RCA_PALLAS=0`` disables,
    ``RCA_PALLAS=1`` requires it (raises if the probe fails), default
    ``auto`` try-compiles once and caches the verdict."""
    global _SUPPORTED
    flag = os.environ.get("RCA_PALLAS", "auto")
    if flag == "0":
        return False
    if _SUPPORTED is None:
        try:
            ft = jnp.zeros((2, BLOCK_S), jnp.float32)
            w = jnp.zeros(2, jnp.float32)
            a, h = noisy_or_pair_pallas(ft, w, w)
            a.block_until_ready()
            _SUPPORTED = True
        except Exception:
            _SUPPORTED = False
    if flag == "1" and not _SUPPORTED:
        raise RuntimeError(
            "RCA_PALLAS=1 but the Pallas kernel failed to compile on this "
            "backend (set RCA_PALLAS=auto to fall back silently)"
        )
    return _SUPPORTED


_SUPPORTED = None
