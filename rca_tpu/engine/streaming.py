"""Streaming analysis session: repeated ticks over a fixed service graph.

The BASELINE.md 10k-service streaming config ticks metrics at 1 Hz.  A
:class:`StreamingSession` pins the padded edge arrays, the weights, AND the
feature matrix on the device for the whole session; between ticks only the
changed rows travel host→device, applied with a donated-argument scatter so
XLA updates the resident buffer in place (SURVEY.md §7 "donate-argument
in-place updates to avoid host↔device churn" — round 1 re-uploaded the full
[S, C] matrix every tick).

Per-tick transfer is therefore proportional to the delta count: U changed
services upload one [U] int32 index vector and one [U, C] float32 row block
(U padded to a small power of two so the scatter executable is reused), not
the [S_pad, C] matrix.  The whole tick — scatter, propagation, top-k — runs
as a SINGLE fused dispatch (:func:`_flush_propagate_ranked`): on tunneled
TPUs each dispatch pays a host round trip that dwarfs device compute, so
flush-then-propagate as two calls would double the tick latency.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.runner import GraphEngine, _propagate_ranked, up_ell_for


@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast",
    ),
)
def _flush_propagate_ranked(
    features, idx, rows, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0,
):
    """Whole tick in ONE dispatch: scatter the delta rows into the donated
    resident buffer, propagate, top-k.  On tunneled TPUs every dispatch pays
    a host round trip, so flush-then-propagate as two calls doubles tick
    latency; fused, the tick costs one RTT plus device compute.

    The finite-mask sanitize runs fused after the scatter: a delta row
    carrying NaN/Inf telemetry zeroes out ON DEVICE (persisting into the
    resident buffer — "no signal" until a clean row arrives) and the
    zeroed-row count rides back with the same top-k fetch, so the guard
    costs no extra host sync.  Clean rows pass through bit-identically."""
    from rca_tpu.engine.propagate import finite_mask_rows, propagate

    features = features.at[idx].set(rows)
    features, n_bad = finite_mask_rows(features)
    a, h, u, m, score = propagate(
        features, edges[0], edges[1], anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        error_contrast=error_contrast,
    )
    vals, topi = jax.lax.top_k(score, k)
    return features, vals, topi, n_bad


def make_streaming_session(
    names: Sequence[str],
    dep_src: np.ndarray,
    dep_dst: np.ndarray,
    num_features: int,
    engine=None,
    k: int = 5,
):
    """Streaming session matched to the engine kind: a
    :class:`rca_tpu.parallel.streaming.ShardedStreamingSession` when the
    engine is sharded (VERDICT r3 item 3 — 50k live ticks on the mesh),
    else the single-device :class:`StreamingSession`."""
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    if isinstance(engine, ShardedGraphEngine):
        from rca_tpu.parallel.streaming import ShardedStreamingSession

        return ShardedStreamingSession(
            names, dep_src, dep_dst, num_features=num_features,
            engine=engine, k=k,
        )
    return StreamingSession(
        names, dep_src, dep_dst, num_features=num_features,
        engine=engine, k=k,
    )


class StreamingHostState:
    """Host-side state every streaming session shares (dense and sharded):
    the pending-delta dict, the padded delta packing, the upload-rows
    accounting, and the ranked-output rendering.  One definition so the
    documented invariants — rows copied on update (callers reuse scratch
    buffers), deltas cleared only AFTER the dispatch is accepted, set_all's
    bulk upload reported by the next tick — cannot drift between the two
    session kinds."""

    # set by subclasses: names, k, _n, _n_pad, _num_features
    def _init_host_state(self) -> None:
        # pending row updates, keyed by service index (last write wins, so
        # the scatter never carries duplicate indices)
        self._pending: Dict[int, np.ndarray] = {}
        self.ticks = 0
        self.last_upload_rows = 0  # padded rows uploaded by the last flush
        self._bulk_upload = 0      # set by set_all; reported by next tick
        # rows zeroed by a host-side finite-mask pass (sharded session's
        # set_all) awaiting the next tick's report; the dense session
        # sanitizes on device and never uses it
        self._san_pending = 0

    def update(self, service_index: int, features: np.ndarray) -> None:
        """Replace one service's feature row (delta update between ticks)."""
        # copy: callers may reuse one scratch buffer across update() calls
        self._pending[int(service_index)] = np.array(features, np.float32)

    def update_many(self, rows: Dict[int, np.ndarray]) -> None:
        for i, f in rows.items():
            self.update(i, f)

    def _pack_pending(self, drop_index: int):
        """Pending deltas as power-of-two-padded (count, idx, rows); pad
        slots point at ``drop_index`` (the dense session's dummy row / the
        sharded session's out-of-bounds sentinel)."""
        u = len(self._pending)
        u_pad = 1 << max(0, (u - 1).bit_length()) if u else 1
        idx_h = np.full(u_pad, drop_index, np.int32)
        rows_h = np.zeros((u_pad, self._num_features), np.float32)
        for j, (i, f) in enumerate(self._pending.items()):
            idx_h[j] = i
            rows_h[j] = f
        return u, u_pad, idx_h, rows_h

    def _account_upload(self, uploaded_rows: int) -> int:
        """Drop the applied deltas and fold in any preceding set_all.
        Call only once the dispatch is accepted — a raise before this must
        leave the deltas retryable."""
        self._pending.clear()
        total = uploaded_rows + self._bulk_upload
        self._bulk_upload = 0
        self.last_upload_rows = total
        return total

    def _render_tick(self, vals, idx, latency_ms: float,
                     sanitized_rows: int = 0) -> Dict[str, object]:
        ranked: List[dict] = []
        for j, i in enumerate(np.asarray(idx).tolist()):
            if i >= self._n or len(ranked) >= self.k:
                continue
            ranked.append(
                {"component": self.names[i], "score": float(np.asarray(vals)[j])}
            )
        self.ticks += 1
        return {"ranked": ranked, "latency_ms": latency_ms,
                "tick": self.ticks, "upload_rows": self.last_upload_rows,
                "sanitized_rows": int(sanitized_rows)}


class StreamingSession(StreamingHostState):
    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine: Optional[GraphEngine] = None,
        k: int = 5,
    ):
        self.engine = engine or GraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        cfg = self.engine.config
        self._n = n
        self._n_live = jnp.asarray(n, jnp.int32)
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        self._num_features = num_features
        e_pad = bucket_for(max(len(dep_src), 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[: len(dep_src)] = dep_src
        d[: len(dep_dst)] = dep_dst
        # edges + weights + FEATURES live on device for the whole session
        self._edges = jnp.asarray(np.stack([s, d]))
        # segscan layouts at large tiers (same gate as the one-shot
        # engine: hybrid default only; replaces the hybrid up-table when
        # engaged), built once for the session's pinned edges
        from rca_tpu.engine.runner import edge_layout
        from rca_tpu.engine.segscan import seg_layouts_for

        self._down_seg, self._up_seg = (
            seg_layouts_for(self._n_pad, e_pad, dep_src, dep_dst)
            if edge_layout() == "hybrid" else (None, None)
        )
        self._up_ell = (
            None if self._up_seg is not None
            else up_ell_for(self._n_pad, dep_src, dep_dst)
        )
        self._features = jnp.zeros((self._n_pad, num_features), jnp.float32)
        self._kk = min(k + 8, self._n_pad)
        self._init_host_state()

    def set_all(self, features: np.ndarray) -> None:
        """Full re-upload (session start or resync) — the one bulk path.
        The next tick reports the full padded matrix in ``upload_rows`` so
        bandwidth accounting sees the most expensive upload of the session
        instead of a zero."""
        f = np.zeros((self._n_pad, self._num_features), np.float32)
        f[: len(features)] = features
        self._features = jnp.asarray(f)
        self._pending.clear()
        self._bulk_upload = self._n_pad

    # -- tick ---------------------------------------------------------------
    def tick(self) -> Dict[str, object]:
        """One inference pass; returns ranked root causes + tick latency."""
        p = self.engine.params
        t0 = time.perf_counter()
        if self._pending:
            # fused path: scatter + propagate + top-k in a single dispatch
            _, u_pad, idx_h, rows_h = self._pack_pending(self._n_pad - 1)
            self._features, vals, idx, n_bad = _flush_propagate_ranked(
                self._features, jnp.asarray(idx_h), jnp.asarray(rows_h),
                self._edges, self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, self._n_live, self._up_ell, self._down_seg,
                self._up_seg, error_contrast=p.error_contrast,
            )
            # only drop the deltas once the dispatch is accepted — a raise
            # above (fresh-tier compile failure) must leave them retryable
            self._account_upload(u_pad)
        else:
            self._account_upload(0)
            stacked, vals, idx, n_bad = _propagate_ranked(
                self._features, self._edges,
                self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, False, self._n_live, self._up_ell, self._down_seg,
                self._up_seg, error_contrast=p.error_contrast,
            )
        # sync through the fetch: block_until_ready alone can return at
        # enqueue time on tunneled backends, under-measuring the tick
        # (the sanitized-row count rides the same fetch — no extra sync)
        vals, idx, n_bad = jax.device_get((vals, idx, n_bad))
        latency_ms = (time.perf_counter() - t0) * 1e3
        return self._render_tick(vals, idx, latency_ms, int(n_bad))
