"""Streaming analysis session: repeated ticks over a fixed service graph.

The BASELINE.md 10k-service streaming config ticks metrics at 1 Hz.  A
:class:`StreamingSession` pins the padded edge arrays, the weights, AND the
feature matrix on the device for the whole session; between ticks only the
changed rows travel host→device, applied with a donated-argument scatter so
XLA updates the resident buffer in place (SURVEY.md §7 "donate-argument
in-place updates to avoid host↔device churn" — round 1 re-uploaded the full
[S, C] matrix every tick).

Per-tick transfer is therefore proportional to the delta count: U changed
services upload one [U] int32 index vector and one [U, C] float32 row block
(U padded to a small power of two so the scatter executable is reused), not
the [S_pad, C] matrix.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.runner import GraphEngine, _propagate_ranked


@functools.partial(jax.jit, donate_argnums=(0,))
def _apply_rows(features, idx, rows):
    """Scatter changed rows into the DONATED device-resident feature buffer;
    XLA reuses the buffer in place instead of materializing a copy."""
    return features.at[idx].set(rows)


class StreamingSession:
    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine: Optional[GraphEngine] = None,
        k: int = 5,
    ):
        self.engine = engine or GraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        cfg = self.engine.config
        self._n = n
        self._n_live = jnp.asarray(n, jnp.int32)
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        self._num_features = num_features
        e_pad = bucket_for(max(len(dep_src), 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[: len(dep_src)] = dep_src
        d[: len(dep_dst)] = dep_dst
        # edges + weights + FEATURES live on device for the whole session
        self._edges = jnp.asarray(np.stack([s, d]))
        self._features = jnp.zeros((self._n_pad, num_features), jnp.float32)
        # pending row updates, keyed by service index (last write wins, so
        # the scatter never carries duplicate indices)
        self._pending: Dict[int, np.ndarray] = {}
        self._kk = min(k + 8, self._n_pad)
        self.ticks = 0
        self.last_upload_rows = 0  # padded rows uploaded by the last flush

    # -- host-side incremental state --------------------------------------
    def update(self, service_index: int, features: np.ndarray) -> None:
        """Replace one service's feature row (delta update between ticks)."""
        # copy: callers may reuse one scratch buffer across update() calls
        self._pending[int(service_index)] = np.array(features, np.float32)

    def update_many(self, rows: Dict[int, np.ndarray]) -> None:
        for i, f in rows.items():
            self.update(i, f)

    def set_all(self, features: np.ndarray) -> None:
        """Full re-upload (session start or resync) — the one bulk path."""
        f = np.zeros((self._n_pad, self._num_features), np.float32)
        f[: len(features)] = features
        self._features = jnp.asarray(f)
        self._pending.clear()

    # -- device-side delta flush -------------------------------------------
    def _flush(self) -> None:
        if not self._pending:
            self.last_upload_rows = 0
            return
        u = len(self._pending)
        # pad the delta block to a power of two: one scatter executable per
        # tier, padded lanes write zeros onto the zero dummy row
        u_pad = 1 << max(0, (u - 1).bit_length())
        idx = np.full(u_pad, self._n_pad - 1, np.int32)
        rows = np.zeros((u_pad, self._num_features), np.float32)
        for j, (i, f) in enumerate(self._pending.items()):
            idx[j] = i
            rows[j] = f
        self._features = _apply_rows(
            self._features, jnp.asarray(idx), jnp.asarray(rows)
        )
        self.last_upload_rows = u_pad
        self._pending.clear()

    # -- tick ---------------------------------------------------------------
    def tick(self) -> Dict[str, object]:
        """One inference pass; returns ranked root causes + tick latency."""
        p = self.engine.params
        t0 = time.perf_counter()
        self._flush()
        stacked, vals, idx = _propagate_ranked(
            self._features, self._edges,
            self.engine._aw, self.engine._hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus, self._kk,
            False, self._n_live,
        )
        idx.block_until_ready()
        latency_ms = (time.perf_counter() - t0) * 1e3
        idx = np.asarray(idx)
        vals = np.asarray(vals)
        ranked: List[dict] = []
        for j, i in enumerate(idx.tolist()):
            if i >= self._n or len(ranked) >= self.k:
                continue
            ranked.append(
                {"component": self.names[i], "score": float(vals[j])}
            )
        self.ticks += 1
        return {"ranked": ranked, "latency_ms": latency_ms,
                "tick": self.ticks, "upload_rows": self.last_upload_rows}
