"""Streaming analysis session: repeated ticks over a fixed service graph.

The BASELINE.md 10k-service streaming config ticks metrics at 1 Hz.  A
:class:`StreamingSession` pins the padded edge arrays, the weights, AND the
feature matrix on the device for the whole session; between ticks only the
changed rows travel host→device, applied with a donated-argument scatter so
XLA updates the resident buffer in place (SURVEY.md §7 "donate-argument
in-place updates to avoid host↔device churn" — round 1 re-uploaded the full
[S, C] matrix every tick).

Per-tick transfer is therefore proportional to the delta count: U changed
services upload one [U] int32 index vector and one [U, C] float32 row block
(U padded to a small power of two so the scatter executable is reused), not
the [S_pad, C] matrix.  The whole tick — scatter, propagation, top-k — runs
as a SINGLE fused dispatch (:func:`_flush_propagate_ranked`): on tunneled
TPUs each dispatch pays a host round trip that dwarfs device compute, so
flush-then-propagate as two calls would double the tick latency.

Round 6 splits the tick into its two host-visible halves so callers can
PIPELINE ticks (ISSUE 2): :meth:`StreamingHostState.dispatch` packs the
pending deltas and enqueues the fused executable (JAX dispatch is async —
this returns in microseconds with a :class:`TickHandle` over the in-flight
device values), and :meth:`StreamingHostState.fetch` blocks on the handle's
results and renders the ranking.  ``tick()`` is exactly
``fetch(dispatch())`` — the serial path stays bit-identical — while a
depth-2 caller issues tick N, runs tick N+1's host capture, and only then
fetches tick N: the ~90–110 ms tunnel RTT and the host capture hide behind
each other instead of summing (bench: ``tick_ms_10k_pipelined``).  The
ONLY place the tick path may synchronize with the device is
:meth:`StreamingHostState.fetch` (enforced by tools/lint_tick_sync.py).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.runner import GraphEngine, _propagate_ranked


def topology_digest(tag: str, parts) -> str:
    """Stable hex digest over a topology description.

    ``parts`` is any JSON-serializable nested structure of strings /
    numbers / sequences (tuples are canonicalized to lists).  Used by
    the multi-cluster :class:`~rca_tpu.cluster.clusterset.ClusterSet`
    both per member (the rendezvous key ingest ownership is routed by)
    and over the merged world (the fleet's replay/routing identity).
    Same topology — regardless of construction or iteration order at the
    call site, which must pre-sort — same digest, across processes
    (sha256, not ``hash()``).
    """
    import hashlib
    import json

    def _canon(x):
        if isinstance(x, (list, tuple)):
            return [_canon(v) for v in x]
        if isinstance(x, dict):
            return {str(k): _canon(v) for k, v in sorted(x.items())}
        return x

    blob = json.dumps([tag, _canon(parts)], sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
        "error_contrast", "kernel",
    ),
)
def _flush_propagate_ranked(
    features, idx, rows, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live, up_ell=None, down_seg=None, up_seg=None,
    error_contrast: float = 0.0, kernel: str = "xla", dbl=None,
):
    """Whole tick in ONE dispatch: scatter the delta rows into the donated
    resident buffer, propagate, top-k.  On tunneled TPUs every dispatch pays
    a host round trip, so flush-then-propagate as two calls doubles tick
    latency; fused, the tick costs one RTT plus device compute.

    The finite-mask sanitize runs fused after the scatter: a delta row
    carrying NaN/Inf telemetry zeroes out ON DEVICE (persisting into the
    resident buffer — "no signal" until a clean row arrives) and the
    zeroed-row count rides back with the same top-k fetch, so the guard
    costs no extra host sync.  Clean rows pass through bit-identically."""
    from rca_tpu.engine.propagate import finite_mask_rows
    from rca_tpu.engine.runner import propagate_auto

    features = features.at[idx].set(rows)
    features, n_bad = finite_mask_rows(features)
    # propagate_auto is the ONE traced propagation body (per-kernel
    # branch included) shared with the one-shot and resident executables,
    # so the engaged kernel cannot drift between the call surfaces
    a, h, u, m, score = propagate_auto(
        features, edges, anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
        error_contrast=error_contrast, kernel=kernel, dbl=dbl,
    )
    vals, topi = jax.lax.top_k(score, k)
    return features, vals, topi, n_bad


def make_streaming_session(
    names: Sequence[str],
    dep_src: np.ndarray,
    dep_dst: np.ndarray,
    num_features: int,
    engine=None,
    k: int = 5,
    clock=None,
):
    """Streaming session matched to the engine kind: a
    :class:`rca_tpu.parallel.streaming.ShardedStreamingSession` when the
    engine is sharded (VERDICT r3 item 3 — 50k live ticks on the mesh),
    else the single-device :class:`StreamingSession`."""
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    if isinstance(engine, ShardedGraphEngine):
        from rca_tpu.parallel.streaming import ShardedStreamingSession

        return ShardedStreamingSession(
            names, dep_src, dep_dst, num_features=num_features,
            engine=engine, k=k, clock=clock,
        )
    return StreamingSession(
        names, dep_src, dep_dst, num_features=num_features,
        engine=engine, k=k, clock=clock,
    )


@dataclasses.dataclass
class TickHandle:
    """One in-flight tick: the device values an async dispatch left behind
    plus everything the eventual fetch needs to render the result without
    touching the session's CURRENT host state (which may already describe
    a LATER tick — or, after a resync, a different session entirely).

    ``session`` is the session that dispatched it: rankings render with
    THAT session's names, so a handle stays fetchable across a live
    session's topology resync or degradation rebuild."""

    session: "StreamingHostState"
    vals: object                 # [kk] device (or concrete) values
    idx: object                  # [kk] device indices
    n_bad: object                # sanitized-row count (device scalar or int)
    upload_rows: int             # padded rows this tick uploaded
    dispatch_ms: float           # host time to pack + enqueue
    dispatched_at: float         # perf_counter at dispatch start


class StreamingHostState:
    """Host-side state every streaming session shares (dense and sharded):
    the pending-delta dict, the padded delta packing, the upload-rows
    accounting, and the ranked-output rendering.  One definition so the
    documented invariants — rows copied on update (callers reuse scratch
    buffers), deltas cleared only AFTER the dispatch is accepted, set_all's
    bulk upload reported by the next tick — cannot drift between the two
    session kinds."""

    # set by subclasses: names, k, _n, _n_pad, _num_features
    def _init_host_state(self, clock=None) -> None:
        # injectable monotonic timer (nondet-discipline: latency stamps
        # never read the clock module directly on the tick path)
        self._clock = clock or time.perf_counter
        # pending row updates, keyed by service index (last write wins, so
        # the scatter never carries duplicate indices); bulk dirty-row
        # slices stage as (idx, rows) blocks beside it (update_rows)
        self._pending: Dict[int, np.ndarray] = {}
        self._pending_blocks: list = []
        self.ticks = 0
        self.last_upload_rows = 0  # padded rows uploaded by the last flush
        self._bulk_upload = 0      # set by set_all; reported by next tick
        # rows zeroed by a host-side finite-mask pass (sharded session's
        # set_all) awaiting the next tick's report; the dense session
        # sanitizes on device and never uses it
        self._san_pending = 0

    def update(self, service_index: int, features: np.ndarray) -> None:
        """Replace one service's feature row (delta update between ticks)."""
        # copy: callers may reuse one scratch buffer across update() calls
        self._pending[int(service_index)] = np.array(features, np.float32)

    def update_many(self, rows: Dict[int, np.ndarray]) -> None:
        for i, f in rows.items():
            self.update(i, f)

    def update_rows(self, indices: np.ndarray, rows: np.ndarray) -> None:
        """Bulk delta staging (ISSUE 10): a dirty-row slice — an [U] index
        vector plus its [U, C] row block — feeds the delta scatter
        directly, with no per-row dict insertion.  Semantically identical
        to ``update_many`` over the same pairs (last write per index
        wins, including against earlier ``update`` calls)."""
        idx = np.asarray(indices, np.int64).ravel()
        if idx.size == 0:
            return
        block = np.array(rows, np.float32).reshape(idx.size, -1)
        self._pending_blocks.append((idx, block))
        # a block supersedes earlier per-index updates for the same rows
        if self._pending:
            for i in idx.tolist():
                self._pending.pop(int(i), None)

    def _pack_pending(self, drop_index: int):
        """Pending deltas as power-of-two-padded (count, idx, rows); pad
        slots point at ``drop_index`` (the dense session's dummy row / the
        sharded session's out-of-bounds sentinel)."""
        blocks = self._pending_blocks
        if blocks:
            # merge block staging with any dict staging; later writes win
            # per index (the scatter must never carry duplicate indices —
            # duplicate-lane scatter order is undefined on device)
            all_idx = np.concatenate(
                [b[0] for b in blocks]
                + ([np.fromiter(self._pending, np.int64, len(self._pending))]
                   if self._pending else [])
            )
            all_rows = np.concatenate(
                [b[1] for b in blocks]
                + ([np.stack(list(self._pending.values()))]
                   if self._pending else [])
            )
            rev = all_idx[::-1]
            _uniq, first_in_rev = np.unique(rev, return_index=True)
            keep = np.sort(len(all_idx) - 1 - first_in_rev)
            u = int(len(keep))
            u_pad = 1 << max(0, (u - 1).bit_length()) if u else 1
            idx_h = np.full(u_pad, drop_index, np.int32)
            rows_h = np.zeros((u_pad, self._num_features), np.float32)
            idx_h[:u] = all_idx[keep]
            rows_h[:u] = all_rows[keep]
            return u, u_pad, idx_h, rows_h
        u = len(self._pending)
        u_pad = 1 << max(0, (u - 1).bit_length()) if u else 1
        idx_h = np.full(u_pad, drop_index, np.int32)
        rows_h = np.zeros((u_pad, self._num_features), np.float32)
        for j, (i, f) in enumerate(self._pending.items()):
            idx_h[j] = i
            rows_h[j] = f
        return u, u_pad, idx_h, rows_h

    def _account_upload(self, uploaded_rows: int) -> int:
        """Drop the applied deltas and fold in any preceding set_all.
        Call only once the dispatch is accepted — a raise before this must
        leave the deltas retryable."""
        self._pending.clear()
        self._pending_blocks.clear()
        total = uploaded_rows + self._bulk_upload
        self._bulk_upload = 0
        self.last_upload_rows = total
        return total

    def _render_tick(self, vals, idx, latency_ms: float,
                     sanitized_rows: int = 0,
                     upload_rows: Optional[int] = None) -> Dict[str, object]:
        ranked: List[dict] = []
        for j, i in enumerate(np.asarray(idx).tolist()):
            if i >= self._n or len(ranked) >= self.k:
                continue
            ranked.append(
                {"component": self.names[i], "score": float(np.asarray(vals)[j])}
            )
        self.ticks += 1
        return {"ranked": ranked, "latency_ms": latency_ms,
                "tick": self.ticks,
                "upload_rows": (self.last_upload_rows
                                if upload_rows is None else upload_rows),
                "sanitized_rows": int(sanitized_rows)}

    # -- pipelined tick halves ----------------------------------------------
    def dispatch(self) -> TickHandle:
        """Pack pending deltas and ENQUEUE the fused tick executable;
        returns without synchronizing (JAX dispatch is async).  Implemented
        by each session kind."""
        raise NotImplementedError

    def fetch(self, handle: TickHandle) -> Dict[str, object]:
        """Block on an in-flight tick's results and render the ranking.

        THE designated device-sync point of the whole tick path
        (tools/lint_tick_sync.py forbids ``jax.device_get`` /
        ``block_until_ready`` anywhere else in it): sync is through the
        fetch, never ``block_until_ready`` alone — on tunneled backends
        the latter can return at enqueue time (PERF.md methodology).

        ``latency_ms`` is dispatch_ms + fetch_ms — the host time the tick
        COST, not the handle's age: a pipelined caller parks a handle for
        a whole poll interval, and age would read as latency."""
        clock = handle.session._clock
        t1 = clock()
        vals, idx, n_bad = jax.device_get(
            (handle.vals, handle.idx, handle.n_bad)
        )
        fetch_ms = (clock() - t1) * 1e3
        out = handle.session._render_tick(
            vals, idx, handle.dispatch_ms + fetch_ms, int(n_bad),
            upload_rows=handle.upload_rows,
        )
        out["dispatch_ms"] = round(handle.dispatch_ms, 3)
        out["fetch_ms"] = round(fetch_ms, 3)
        return out

    def tick(self) -> Dict[str, object]:
        """One serial inference pass (dispatch immediately fetched):
        ranked root causes + tick latency, bit-identical to the
        pre-pipeline behavior."""
        return self.fetch(self.dispatch())


class StreamingSession(StreamingHostState):
    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine: Optional[GraphEngine] = None,
        k: int = 5,
        clock=None,
    ):
        self.engine = engine or GraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        cfg = self.engine.config
        self._n = n
        self._n_live = jnp.asarray(n, jnp.int32)
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        self._num_features = num_features
        e_pad = bucket_for(max(len(dep_src), 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[: len(dep_src)] = dep_src
        d[: len(dep_dst)] = dep_dst
        # edges + weights + FEATURES live on device for the whole session
        self._edges = jnp.asarray(np.stack([s, d]))
        # kernel + layouts from the per-shape registry (ISSUE 12/13 —
        # the ONE dispatch seam): the engaged kernel for THIS padded
        # shape, its layouts built once for the session's pinned edges
        from rca_tpu.engine.runner import kernel_plan

        p = self.engine.params
        self._plan = kernel_plan(
            self._n_pad, e_pad, dep_src, dep_dst, steps=p.steps
        )
        self._down_seg = self._plan.down_seg
        self._up_seg = self._plan.up_seg
        self._up_ell = self._plan.up_ell
        self._features = jnp.zeros((self._n_pad, num_features), jnp.float32)
        self._kk = min(k + 8, self._n_pad)
        # the ENGAGED kernel for THIS padded shape — health records and
        # span attributes carry it so a kernel regression names a shape.
        # (The retired process-level noisyor_path stamp — one canonical-
        # shape autotune per session construction — is gone: ISSUE 14
        # satellite; per-shape kernel_path says strictly more.)
        self.kernel_path = self._plan.kernel
        self._init_host_state(clock)

    def set_all(self, features: np.ndarray) -> None:
        """Full re-upload (session start or resync) — the one bulk path.
        The next tick reports the full padded matrix in ``upload_rows`` so
        bandwidth accounting sees the most expensive upload of the session
        instead of a zero."""
        f = np.zeros((self._n_pad, self._num_features), np.float32)
        f[: len(features)] = features
        self._features = jnp.asarray(f)
        self._pending.clear()
        self._pending_blocks.clear()
        self._bulk_upload = self._n_pad

    # -- tick ---------------------------------------------------------------
    def dispatch(self) -> TickHandle:
        """Enqueue one fused tick (scatter + propagate + top-k) and return
        the in-flight handle; :meth:`fetch` renders it.  ``tick()`` (the
        serial path) is fetch(dispatch()) back to back."""
        p = self.engine.params
        t0 = self._clock()
        if self._pending or self._pending_blocks:
            # fused path: scatter + propagate + top-k in a single dispatch
            _, u_pad, idx_h, rows_h = self._pack_pending(self._n_pad - 1)
            self._features, vals, idx, n_bad = _flush_propagate_ranked(
                self._features, jnp.asarray(idx_h), jnp.asarray(rows_h),
                self._edges, self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, self._n_live, self._up_ell, self._down_seg,
                self._up_seg, error_contrast=p.error_contrast,
                kernel=self._plan.kernel, dbl=self._plan.dbl,
            )
            # only drop the deltas once the dispatch is accepted — a raise
            # above (fresh-tier compile failure) must leave them retryable
            upload = self._account_upload(u_pad)
        else:
            upload = self._account_upload(0)
            # quiet tick: same one-shot executable, top-k values only —
            # the stacked/diag device values stay unfetched
            stacked, _diag, vals, idx, n_bad = _propagate_ranked(
                self._features, self._edges,
                self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, self._plan.kernel, self._n_live, self._up_ell,
                self._down_seg, self._up_seg, self._plan.dbl,
                error_contrast=p.error_contrast,
            )
        now = self._clock()
        return TickHandle(
            session=self, vals=vals, idx=idx, n_bad=n_bad,
            upload_rows=upload, dispatch_ms=(now - t0) * 1e3,
            dispatched_at=t0,
        )
