"""Streaming analysis session: repeated ticks over a fixed service graph.

The BASELINE.md 10k-service streaming config ticks metrics at 1 Hz.  A
:class:`StreamingSession` pins the padded edge arrays (and weights) on the
device once; each tick uploads only the feature matrix and runs the cached
executable — no per-tick graph rebuild, no edge re-upload, no recompile
(shapes are fixed at session construction).  Feature deltas can be applied
host-side via :meth:`update` so a tick touches only changed services.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.runner import GraphEngine, _propagate_ranked


class StreamingSession:
    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine: Optional[GraphEngine] = None,
        k: int = 5,
    ):
        self.engine = engine or GraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        cfg = self.engine.config
        self._n = n
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        e_pad = bucket_for(max(len(dep_src), 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[: len(dep_src)] = dep_src
        d[: len(dep_dst)] = dep_dst
        # edges + weights live on device for the whole session
        self._edges = jnp.asarray(np.stack([s, d]))
        self._features = np.zeros((self._n_pad, num_features), np.float32)
        self._kk = min(k + 8, self._n_pad)
        self.ticks = 0

    # -- host-side incremental state --------------------------------------
    def update(self, service_index: int, features: np.ndarray) -> None:
        """Replace one service's feature row (delta update between ticks)."""
        self._features[service_index] = features

    def update_many(self, rows: Dict[int, np.ndarray]) -> None:
        for i, f in rows.items():
            self._features[i] = f

    def set_all(self, features: np.ndarray) -> None:
        self._features[: len(features)] = features

    # -- tick ---------------------------------------------------------------
    def tick(self) -> Dict[str, object]:
        """One inference pass; returns ranked root causes + tick latency."""
        p = self.engine.params
        t0 = time.perf_counter()
        stacked, vals, idx = _propagate_ranked(
            jnp.asarray(self._features), self._edges,
            self.engine._aw, self.engine._hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus, self._kk,
            False, jnp.asarray(self._n, jnp.int32),
        )
        idx.block_until_ready()
        latency_ms = (time.perf_counter() - t0) * 1e3
        idx = np.asarray(idx)
        vals = np.asarray(vals)
        ranked: List[dict] = []
        for j, i in enumerate(idx.tolist()):
            if i >= self._n or len(ranked) >= self.k:
                continue
            ranked.append(
                {"component": self.names[i], "score": float(vals[j])}
            )
        self.ticks += 1
        return {"ranked": ranked, "latency_ms": latency_ms,
                "tick": self.ticks}
