"""Streaming analysis session: repeated ticks over a fixed service graph.

The BASELINE.md 10k-service streaming config ticks metrics at 1 Hz.  A
:class:`StreamingSession` pins the padded edge arrays, the weights, AND the
feature matrix on the device for the whole session; between ticks only the
changed rows travel host→device, applied with a donated-argument scatter so
XLA updates the resident buffer in place (SURVEY.md §7 "donate-argument
in-place updates to avoid host↔device churn" — round 1 re-uploaded the full
[S, C] matrix every tick).

Per-tick transfer is therefore proportional to the delta count: U changed
services upload one [U] int32 index vector and one [U, C] float32 row block
(U padded to a small power of two so the scatter executable is reused), not
the [S_pad, C] matrix.  The whole tick — scatter, propagation, top-k — runs
as a SINGLE fused dispatch (:func:`_flush_propagate_ranked`): on tunneled
TPUs each dispatch pays a host round trip that dwarfs device compute, so
flush-then-propagate as two calls would double the tick latency.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rca_tpu.config import RCAConfig, bucket_for
from rca_tpu.engine.runner import GraphEngine, _propagate_ranked, up_ell_for


@functools.partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=(
        "steps", "decay", "explain_strength", "impact_bonus", "k",
    ),
)
def _flush_propagate_ranked(
    features, idx, rows, edges, anomaly_w, hard_w,
    steps: int, decay: float, explain_strength: float, impact_bonus: float,
    k: int, n_live, up_ell=None,
):
    """Whole tick in ONE dispatch: scatter the delta rows into the donated
    resident buffer, propagate, top-k.  On tunneled TPUs every dispatch pays
    a host round trip, so flush-then-propagate as two calls doubles tick
    latency; fused, the tick costs one RTT plus device compute."""
    from rca_tpu.engine.propagate import propagate

    features = features.at[idx].set(rows)
    a, h, u, m, score = propagate(
        features, edges[0], edges[1], anomaly_w, hard_w,
        steps, decay, explain_strength, impact_bonus, n_live=n_live,
        up_ell=up_ell,
    )
    vals, topi = jax.lax.top_k(score, k)
    return features, vals, topi


class StreamingSession:
    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine: Optional[GraphEngine] = None,
        k: int = 5,
    ):
        # deliberately the SINGLE-device engine even when RCA_SHARD is set:
        # a streaming session's whole design is a device-resident feature
        # buffer updated by donated-argument scatters, which has no sharded
        # twin yet — a sharded session would need a per-shard delta scatter
        # and a sharded resident buffer (future work, not a one-line swap;
        # make_engine() returns engines without the _aw/_hw weight handles
        # this class scatters with)
        self.engine = engine or GraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        cfg = self.engine.config
        self._n = n
        self._n_live = jnp.asarray(n, jnp.int32)
        self._n_pad = bucket_for(n + 1, cfg.shape_buckets)
        self._num_features = num_features
        e_pad = bucket_for(max(len(dep_src), 1), cfg.shape_buckets)
        dummy = self._n_pad - 1
        s = np.full(e_pad, dummy, np.int32)
        d = np.full(e_pad, dummy, np.int32)
        s[: len(dep_src)] = dep_src
        d[: len(dep_dst)] = dep_dst
        # edges + weights + FEATURES live on device for the whole session
        self._edges = jnp.asarray(np.stack([s, d]))
        # hybrid layout's upstream table, built once for the session
        self._up_ell = up_ell_for(self._n_pad, dep_src, dep_dst)
        self._features = jnp.zeros((self._n_pad, num_features), jnp.float32)
        # pending row updates, keyed by service index (last write wins, so
        # the scatter never carries duplicate indices)
        self._pending: Dict[int, np.ndarray] = {}
        self._kk = min(k + 8, self._n_pad)
        self.ticks = 0
        self.last_upload_rows = 0  # padded rows uploaded by the last flush
        self._bulk_upload = 0  # set by set_all; reported by the next tick

    # -- host-side incremental state --------------------------------------
    def update(self, service_index: int, features: np.ndarray) -> None:
        """Replace one service's feature row (delta update between ticks)."""
        # copy: callers may reuse one scratch buffer across update() calls
        self._pending[int(service_index)] = np.array(features, np.float32)

    def update_many(self, rows: Dict[int, np.ndarray]) -> None:
        for i, f in rows.items():
            self.update(i, f)

    def set_all(self, features: np.ndarray) -> None:
        """Full re-upload (session start or resync) — the one bulk path.
        The next tick reports the full padded matrix in ``upload_rows`` so
        bandwidth accounting sees the most expensive upload of the session
        instead of a zero."""
        f = np.zeros((self._n_pad, self._num_features), np.float32)
        f[: len(features)] = features
        self._features = jnp.asarray(f)
        self._pending.clear()
        self._bulk_upload = self._n_pad

    # -- tick ---------------------------------------------------------------
    def tick(self) -> Dict[str, object]:
        """One inference pass; returns ranked root causes + tick latency."""
        p = self.engine.params
        t0 = time.perf_counter()
        if self._pending:
            # fused path: scatter + propagate + top-k in a single dispatch
            u = len(self._pending)
            u_pad = 1 << max(0, (u - 1).bit_length())
            idx_h = np.full(u_pad, self._n_pad - 1, np.int32)
            rows_h = np.zeros((u_pad, self._num_features), np.float32)
            for j, (i, f) in enumerate(self._pending.items()):
                idx_h[j] = i
                rows_h[j] = f
            self._features, vals, idx = _flush_propagate_ranked(
                self._features, jnp.asarray(idx_h), jnp.asarray(rows_h),
                self._edges, self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, self._n_live, self._up_ell,
            )
            # only drop the deltas once the dispatch is accepted — a raise
            # above (fresh-tier compile failure) must leave them retryable
            self._pending.clear()
            # count a set_all that preceded this tick as well
            self.last_upload_rows = u_pad + self._bulk_upload
            self._bulk_upload = 0
        else:
            self.last_upload_rows = self._bulk_upload
            self._bulk_upload = 0
            stacked, vals, idx = _propagate_ranked(
                self._features, self._edges,
                self.engine._aw, self.engine._hw,
                p.steps, p.decay, p.explain_strength, p.impact_bonus,
                self._kk, False, self._n_live, self._up_ell,
            )
        # sync through the fetch: block_until_ready alone can return at
        # enqueue time on tunneled backends, under-measuring the tick
        vals, idx = jax.device_get((vals, idx))
        latency_ms = (time.perf_counter() - t0) * 1e3
        ranked: List[dict] = []
        for j, i in enumerate(idx.tolist()):
            if i >= self._n or len(ranked) >= self.k:
                continue
            ranked.append(
                {"component": self.names[i], "score": float(vals[j])}
            )
        self.ticks += 1
        return {"ranked": ranked, "latency_ms": latency_ms,
                "tick": self.ticks, "upload_rows": self.last_upload_rows}
