"""LLM backend: providers, real tool loop, per-signal tool registries."""

from rca_tpu.llm.client import LLMClient, parse_json_response
from rca_tpu.llm.providers import (
    AnthropicProvider,
    LLMQuotaExceeded,
    LLMUnavailable,
    OfflineProvider,
    OpenAIProvider,
    Provider,
    ProviderReply,
    ToolCall,
    make_provider,
)
from rca_tpu.llm.tools import ToolSpec, cluster_toolsets

__all__ = [
    "AnthropicProvider",
    "LLMClient",
    "LLMQuotaExceeded",
    "LLMUnavailable",
    "OfflineProvider",
    "OpenAIProvider",
    "Provider",
    "ProviderReply",
    "ToolCall",
    "ToolSpec",
    "cluster_toolsets",
    "make_provider",
    "parse_json_response",
]
