"""LLMClient: analyze / structured output / completion, with a REAL tool loop.

Surface parity with the reference (reference: utils/llm_client_improved.py —
``analyze(context, tools, system_prompt)`` :68, ``generate_structured_output``
:163 with fenced-block rescue :257-262, ``generate_completion`` :384 with
max_tokens=2000 / temperature=0.2 defaults) plus the tool-execution loop the
reference declared but never ran (its ``tools`` argument was ignored,
reference: llm_client_improved.py:68; SURVEY.md §2.3 "the loop is
vestigial").  Every LLM interaction is reported to an optional ``log_fn``
hook (wired to the PromptLogger, reference format:
utils/prompt_logger.py:76-89).
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, List, Optional, Sequence

from rca_tpu.llm.providers import (
    LLMQuotaExceeded,
    LLMUnavailable,
    OfflineProvider,
    Provider,
    ProviderReply,
    make_provider,
)
from rca_tpu.llm.tools import ToolSpec
from rca_tpu.resilience.policy import CircuitBreaker, CircuitOpen, suppressed

MAX_TOOL_ROUNDS = 6

LogFn = Callable[[Dict[str, Any]], None]

# quota-failover chain (reference: app.py:50-67 fell over from OpenAI to
# Anthropic on quota errors; here any provider can fail over, ending at the
# deterministic offline provider so analysis never dies on a 429)
_FAILOVER_ORDER = ("anthropic", "openai", "offline")

# breaker defaults: a provider that 429s twice in a row is held out of the
# rotation for BREAKER_RESET_S, then probed half-open — replaces the
# round-1 one-shot failover, which hammered a quota-exhausted provider on
# every completion until the process died or the quota reset
BREAKER_FAILURES = 2
BREAKER_RESET_S = 30.0


class LLMClient:
    def __init__(
        self,
        provider: Optional[Provider] = None,
        provider_name: Optional[str] = None,
        log_fn: Optional[LogFn] = None,
        breakers: Optional[Dict[str, CircuitBreaker]] = None,
    ):
        self.provider = provider or make_provider(provider_name)
        self.log_fn = log_fn
        # one breaker per provider NAME (injectable for hermetic tests)
        self._breakers: Dict[str, CircuitBreaker] = breakers or {}

    def _breaker(self, name: str) -> CircuitBreaker:
        br = self._breakers.get(name)
        if br is None:
            br = self._breakers[name] = CircuitBreaker(
                failure_threshold=BREAKER_FAILURES,
                reset_after=BREAKER_RESET_S, name=f"llm.{name}",
            )
        return br

    def _complete(self, messages, **kwargs) -> ProviderReply:
        """One completion with breaker-gated provider rotation.

        The current provider runs only when its circuit allows; a quota
        failure (or an open circuit) rotates through ``_FAILOVER_ORDER``,
        skipping providers whose breakers are open, sticking with the
        first that answers.  The offline provider ends every chain, so
        analysis never dies on a 429.  If the whole rotation fails, the
        raised error CHAINS the original quota failure (satellite fix:
        round-1 dropped it)."""
        primary = self.provider
        first_exc: Optional[LLMUnavailable] = None
        br = self._breaker(primary.name)
        if br.allow():
            try:
                reply = primary.complete(messages, **kwargs)
                br.record_success()
                return reply
            except LLMQuotaExceeded as exc:
                br.record_failure()
                first_exc = exc
        else:
            first_exc = CircuitOpen(
                f"provider {primary.name!r} circuit open "
                "(recent quota failures)"
            )
        for name in _FAILOVER_ORDER:
            if name == primary.name:
                continue
            cand_br = self._breaker(name)
            if not cand_br.allow():
                continue
            try:
                candidate = (
                    OfflineProvider() if name == "offline"
                    else make_provider(name)
                )
                reply = candidate.complete(messages, **kwargs)
            except LLMUnavailable:
                cand_br.record_failure()
                continue
            cand_br.record_success()
            self.provider = candidate  # stick with the working provider
            self._log(
                "", "", kind="provider_failover",
                failed_provider=primary.name, new_provider=candidate.name,
            )
            return reply
        raise LLMUnavailable(
            f"all providers exhausted after failure on {primary.name!r}"
        ) from first_exc

    # -- logging -----------------------------------------------------------
    def _log(self, prompt: str, response: str, **context: Any) -> None:
        if self.log_fn is None:
            return
        # observability must never break analysis — but the swallow goes
        # through the policy channel so it is still visible in health
        with suppressed("llm.log_fn"):
            self.log_fn(
                {
                    "prompt": prompt,
                    "response": response,
                    "additional_context": {
                        "provider": self.provider.name,
                        "model": self.provider.model,
                        **context,
                    },
                }
            )

    # -- tool loop ----------------------------------------------------------
    def analyze(
        self,
        context: str,
        tools: Optional[Sequence[ToolSpec]] = None,
        system_prompt: str = "",
        max_rounds: int = MAX_TOOL_ROUNDS,
    ) -> Dict[str, Any]:
        """Multi-round tool-calling analysis.

        Returns ``{final_analysis, reasoning_steps}`` where each reasoning
        step records a real executed tool call (name, arguments, result
        excerpt) — the audit trail the reference's vestigial loop never
        produced.
        """
        tool_map = {t.name: t for t in tools or []}
        schemas = [t.schema() for t in tools or []]
        messages: List[dict] = []
        if system_prompt:
            messages.append({"role": "system", "content": system_prompt})
        messages.append({"role": "user", "content": context})
        steps: List[dict] = []

        reply: ProviderReply = self._complete(messages, tools=schemas or None)
        rounds = 0
        while reply.tool_calls and rounds < max_rounds:
            rounds += 1
            messages.append(
                {
                    "role": "assistant",
                    "content": reply.text,
                    "tool_calls": [
                        {"id": tc.id, "name": tc.name,
                         "arguments": tc.arguments}
                        for tc in reply.tool_calls
                    ],
                }
            )
            for tc in reply.tool_calls:
                spec = tool_map.get(tc.name)
                if spec is None:
                    result = json.dumps({"error": f"unknown tool {tc.name}"})
                else:
                    result = spec.execute(tc.arguments)
                steps.append(
                    {
                        "observation": (
                            f"tool {tc.name}({json.dumps(tc.arguments)}) -> "
                            f"{result[:400]}"
                        ),
                        "conclusion": "evidence gathered",
                        "tool": tc.name,
                        "arguments": tc.arguments,
                    }
                )
                messages.append(
                    {"role": "tool", "tool_call_id": tc.id, "content": result}
                )
            reply = self._complete(messages, tools=schemas or None)

        self._log(context, reply.text, kind="analyze", tool_rounds=rounds)
        return {"final_analysis": reply.text, "reasoning_steps": steps}

    # -- structured output ---------------------------------------------------
    def generate_structured_output(
        self,
        prompt: str,
        system_prompt: str = "",
        **log_context: Any,
    ) -> Optional[Dict[str, Any]]:
        messages: List[dict] = []
        if system_prompt:
            messages.append({"role": "system", "content": system_prompt})
        messages.append({"role": "user", "content": prompt})
        reply = self._complete(messages, json_mode=True)
        self._log(prompt, reply.text, **{"kind": "structured", **log_context})
        return parse_json_response(reply.text)

    # -- plain completion ----------------------------------------------------
    def generate_completion(
        self,
        prompt: str,
        system_prompt: str = "",
        temperature: float = 0.2,
        max_tokens: int = 2000,
        **log_context: Any,
    ) -> str:
        messages: List[dict] = []
        if system_prompt:
            messages.append({"role": "system", "content": system_prompt})
        messages.append({"role": "user", "content": prompt})
        reply = self._complete(
            messages, temperature=temperature, max_tokens=max_tokens
        )
        self._log(prompt, reply.text, **{"kind": "completion", **log_context})
        return reply.text


_FENCED = re.compile(r"```(?:json)?\s*(\{.*?\}|\[.*?\])\s*```", re.S)


def parse_json_response(text: str) -> Optional[Dict[str, Any]]:
    """Parse a JSON object from model output, rescuing fenced blocks and
    leading/trailing prose (reference: llm_client_improved.py:257-262)."""
    if not text:
        return None
    for candidate in (text, *(m for m in _FENCED.findall(text))):
        try:
            out = json.loads(candidate)
            if isinstance(out, dict):
                return out
        except json.JSONDecodeError:
            continue
    # last resort: widest braces span
    start, end = text.find("{"), text.rfind("}")
    if 0 <= start < end:
        try:
            out = json.loads(text[start : end + 1])
            if isinstance(out, dict):
                return out
        except json.JSONDecodeError:
            pass
    return None
