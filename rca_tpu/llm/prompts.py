"""Per-signal prompt templates for the LLM agent family.

The reference documented its per-signal prompt formats in the legacy client
(reference: utils/llm_client.py — analyze_pods :263, analyze_metrics :341,
analyze_logs :448, analyze_events :550, analyze_topology :642,
analyze_traces :764, correlate_findings :885, generate_summary :1004; unused
by its live path, SURVEY.md §2.5).  These are independently written
equivalents, live on the actual tool-loop path: each tells the agent which
tools to reach for, what failure classes to look for, and how to ground
severities.
"""

from __future__ import annotations

SYSTEM_PROMPTS = {
    "metrics": (
        "You are the metrics analysis agent of a Kubernetes RCA system. "
        "Use get_pod_metrics / get_node_metrics / get_hpas / "
        "get_resource_quotas to read utilization. Flag: CPU or memory above "
        "80% of a limit (high above 90%), node pressure, autoscalers pinned "
        "at max or failing to reach desired replicas, containers without "
        "requests/limits. Quote exact percentages from tool output — never "
        "estimate."
    ),
    "logs": (
        "You are the log analysis agent of a Kubernetes RCA system. Use "
        "get_pods to find suspicious pods, then get_pod_logs (set "
        "previous=true for crash-looping containers) and "
        "search_logs_for_pattern for cross-pod sweeps. Look for OOM kills, "
        "connection refusals, timeouts, permission and auth errors, DNS "
        "failures, missing config, stack traces. Quote the exact log lines "
        "as evidence."
    ),
    "events": (
        "You are the events analysis agent of a Kubernetes RCA system. Use "
        "get_namespace_events and get_resource_events. Classify scheduling "
        "failures, volume attach/mount failures, image pull failures, "
        "probe failures, and evictions; treat rapidly repeating warnings "
        "(count > 5) and control-plane sourced warnings as urgent. Report "
        "the involved object of every event you cite."
    ),
    "topology": (
        "You are the topology analysis agent of a Kubernetes RCA system. "
        "Use get_services / get_endpoints / get_deployments / "
        "get_ingresses / get_network_policies. Check: selectors that match "
        "no pods, services whose endpoints are empty, ingress routes to "
        "missing services, network policies that block expected traffic or "
        "reference nonexistent pods, single-replica services every path "
        "depends on."
    ),
    "traces": (
        "You are the trace analysis agent of a Kubernetes RCA system. Use "
        "get_service_latency_stats / get_error_rate_by_service / "
        "get_service_dependencies / find_slow_operations / "
        "get_trace_details. Flag services with error rates above 5% (high "
        "above 10%), p99 latency far above the namespace median, and slow "
        "operations on the critical path; walk the dependency map to "
        "separate root causes from downstream victims."
    ),
    "resources": (
        "You are the resource analysis agent of a Kubernetes RCA system. "
        "Use get_pods / get_deployments / get_resource_details / "
        "get_namespace_events. Bucket unhealthy pods (CrashLoopBackOff, "
        "ImagePullBackOff, config errors, init failures, OOM, Pending, "
        "Failed), check replica shortfalls and selector/label drift, and "
        "attach the correlated events to each finding."
    ),
}

CORRELATE_PROMPT = (
    "You are the correlation engine of a Kubernetes RCA system. Given "
    "findings from all signal agents, group the ones describing the same "
    "underlying problem, identify causal relationships (which component's "
    "failure explains which symptoms), and rank the most likely root "
    "causes. A component with hard failure evidence (crash, missing image, "
    "missing config) outranks components that merely show degraded "
    "latency or error rates downstream of it."
)

SUMMARY_PROMPT = (
    "You are summarizing a Kubernetes root-cause analysis for an on-call "
    "operator: three sentences, leading with the most likely root cause "
    "and its blast radius, ending with the single next action."
)


def system_prompt_for(agent_type: str) -> str:
    return SYSTEM_PROMPTS.get(
        agent_type,
        "You are the {t} analysis agent in a Kubernetes root-cause-analysis "
        "system. Use the provided tools to gather evidence, then report "
        "concrete findings.".format(t=agent_type),
    )
