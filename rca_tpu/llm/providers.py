"""LLM provider backends: OpenAI, Anthropic, and a hermetic offline fake.

Parity with the reference's provider handling (reference:
utils/llm_client_improved.py:39-66 provider init, gpt-4o /
claude-3-5-sonnet-20241022 defaults) with three deliberate changes:

- a missing API key raises :class:`LLMUnavailable` instead of hard-exiting
  the process (reference: llm_client_improved.py:44-48 called ``sys.exit``);
- every provider implements one small surface — ``complete(messages, tools)``
  returning text plus structured tool calls — so the tool loop in
  :meth:`rca_tpu.llm.client.LLMClient.analyze` actually executes tools (the
  reference accepted a ``tools`` argument and ignored it, reference:
  llm_client_improved.py:68);
- an :class:`OfflineProvider` provides deterministic, network-free behavior
  so the hermetic/JAX path has zero network deps (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import dataclasses
import json
import os

from rca_tpu.config import env_raw, env_str
from typing import Any, Callable, Dict, List, Optional

DEFAULT_OPENAI_MODEL = "gpt-4o"
DEFAULT_ANTHROPIC_MODEL = "claude-3-5-sonnet-20241022"


class LLMUnavailable(RuntimeError):
    """Provider cannot run (missing SDK, missing key, or quota exhausted)."""


class LLMQuotaExceeded(LLMUnavailable):
    """Rate-limit / quota error — callers may fail over to another provider
    (reference: app.py:50-67 OpenAI→Anthropic failover)."""


@dataclasses.dataclass
class ToolCall:
    id: str
    name: str
    arguments: Dict[str, Any]


@dataclasses.dataclass
class ProviderReply:
    text: str
    tool_calls: List[ToolCall] = dataclasses.field(default_factory=list)
    stop_reason: str = "end"


class Provider:
    """Minimal chat-completion surface shared by all backends.

    ``messages`` is a provider-neutral list of
    ``{"role": "system"|"user"|"assistant"|"tool", "content": str,
    "tool_calls"?: [...], "tool_call_id"?: str}``.
    ``tools`` is a list of ``{"name", "description", "parameters"}`` JSON
    schemas.
    """

    name = "base"
    model = ""

    def complete(
        self,
        messages: List[dict],
        tools: Optional[List[dict]] = None,
        temperature: float = 0.2,
        max_tokens: int = 2000,
        json_mode: bool = False,
    ) -> ProviderReply:
        raise NotImplementedError


class OpenAIProvider(Provider):
    name = "openai"

    def __init__(self, model: str = DEFAULT_OPENAI_MODEL):
        key = env_raw("OPENAI_API_KEY")
        if not key:
            raise LLMUnavailable("OPENAI_API_KEY is not set")
        try:
            import openai  # noqa: F401
        except ImportError as e:  # pragma: no cover - env dependent
            raise LLMUnavailable("openai SDK not installed") from e
        from openai import OpenAI

        self._client = OpenAI(api_key=key)
        self.model = model

    def complete(self, messages, tools=None, temperature=0.2,
                 max_tokens=2000, json_mode=False) -> ProviderReply:
        kwargs: Dict[str, Any] = {}
        if tools:
            kwargs["tools"] = [
                {"type": "function", "function": t} for t in tools
            ]
        if json_mode:
            kwargs["response_format"] = {"type": "json_object"}
        oai_messages = []
        for m in messages:
            if m["role"] == "tool":
                oai_messages.append(
                    {"role": "tool", "tool_call_id": m["tool_call_id"],
                     "content": m["content"]}
                )
            elif m["role"] == "assistant" and m.get("tool_calls"):
                oai_messages.append(
                    {
                        "role": "assistant",
                        "content": m.get("content") or None,
                        "tool_calls": [
                            {
                                "id": tc["id"],
                                "type": "function",
                                "function": {
                                    "name": tc["name"],
                                    "arguments": json.dumps(tc["arguments"]),
                                },
                            }
                            for tc in m["tool_calls"]
                        ],
                    }
                )
            else:
                oai_messages.append({"role": m["role"], "content": m["content"]})
        try:
            resp = self._client.chat.completions.create(
                model=self.model, messages=oai_messages,
                temperature=temperature, max_tokens=max_tokens, **kwargs,
            )
        except Exception as e:  # pragma: no cover - network dependent
            raise _classify_error(e, self.name) from e
        choice = resp.choices[0]
        calls = [
            ToolCall(
                id=tc.id, name=tc.function.name,
                arguments=_safe_json(tc.function.arguments),
            )
            for tc in (choice.message.tool_calls or [])
        ]
        return ProviderReply(
            text=choice.message.content or "",
            tool_calls=calls,
            stop_reason=choice.finish_reason or "end",
        )


class AnthropicProvider(Provider):
    name = "anthropic"

    def __init__(self, model: str = DEFAULT_ANTHROPIC_MODEL):
        key = env_raw("ANTHROPIC_API_KEY")
        if not key:
            raise LLMUnavailable("ANTHROPIC_API_KEY is not set")
        try:
            import anthropic  # noqa: F401
        except ImportError as e:  # pragma: no cover - env dependent
            raise LLMUnavailable("anthropic SDK not installed") from e
        from anthropic import Anthropic

        self._client = Anthropic(api_key=key)
        self.model = model

    def complete(self, messages, tools=None, temperature=0.2,
                 max_tokens=2000, json_mode=False) -> ProviderReply:
        system = "\n".join(
            m["content"] for m in messages if m["role"] == "system"
        )
        if json_mode:
            system = (system + "\nRespond ONLY with valid JSON.").strip()
        conv: List[dict] = []
        for m in messages:
            if m["role"] == "system":
                continue
            if m["role"] == "tool":
                conv.append(
                    {
                        "role": "user",
                        "content": [
                            {
                                "type": "tool_result",
                                "tool_use_id": m["tool_call_id"],
                                "content": m["content"],
                            }
                        ],
                    }
                )
            elif m["role"] == "assistant" and m.get("tool_calls"):
                blocks: List[dict] = []
                if m.get("content"):
                    blocks.append({"type": "text", "text": m["content"]})
                blocks += [
                    {
                        "type": "tool_use",
                        "id": tc["id"],
                        "name": tc["name"],
                        "input": tc["arguments"],
                    }
                    for tc in m["tool_calls"]
                ]
                conv.append({"role": "assistant", "content": blocks})
            else:
                conv.append({"role": m["role"], "content": m["content"]})
        kwargs: Dict[str, Any] = {}
        if tools:
            kwargs["tools"] = [
                {
                    "name": t["name"],
                    "description": t.get("description", ""),
                    "input_schema": t.get(
                        "parameters", {"type": "object", "properties": {}}
                    ),
                }
                for t in tools
            ]
        try:
            resp = self._client.messages.create(
                model=self.model,
                system=system or None,
                messages=conv,
                temperature=temperature,
                max_tokens=max_tokens,
                **kwargs,
            )
        except Exception as e:  # pragma: no cover - network dependent
            raise _classify_error(e, self.name) from e
        text_parts, calls = [], []
        for block in resp.content:
            if block.type == "text":
                text_parts.append(block.text)
            elif block.type == "tool_use":
                calls.append(
                    ToolCall(id=block.id, name=block.name,
                             arguments=dict(block.input or {}))
                )
        return ProviderReply(
            text="\n".join(text_parts),
            tool_calls=calls,
            stop_reason=resp.stop_reason or "end",
        )


class OfflineProvider(Provider):
    """Deterministic hermetic provider.

    Behavior contract (what tests rely on):

    - when tools are offered and none has been called yet, it requests every
      offered tool once (exercising the real tool loop);
    - after tool results arrive, it emits a final text that embeds the tool
      outputs, so the loop's result provably contains executed-tool data;
    - in ``json_mode`` it returns a minimal valid JSON object echoing the
      prompt's requested shape when recognizable.
    """

    name = "offline"
    model = "offline-deterministic"

    def __init__(self, scripted: Optional[Callable[[List[dict]], str]] = None):
        self._scripted = scripted
        self._counter = 0

    def complete(self, messages, tools=None, temperature=0.2,
                 max_tokens=2000, json_mode=False) -> ProviderReply:
        if self._scripted is not None:
            return ProviderReply(text=self._scripted(messages))
        called = {
            tc["name"]
            for m in messages
            if m["role"] == "assistant"
            for tc in m.get("tool_calls", [])
        }
        if tools and not called:
            calls = []
            for t in tools:
                self._counter += 1
                args = {
                    k: v.get("default", "")
                    for k, v in (
                        t.get("parameters", {}).get("properties", {}) or {}
                    ).items()
                    if k in t.get("parameters", {}).get("required", [])
                }
                calls.append(
                    ToolCall(id=f"offline-{self._counter}", name=t["name"],
                             arguments=args)
                )
            return ProviderReply(text="", tool_calls=calls,
                                 stop_reason="tool_use")
        tool_payloads = [
            m["content"] for m in messages if m["role"] == "tool"
        ]
        if json_mode:
            return ProviderReply(
                text=json.dumps(
                    {
                        "summary": "offline deterministic analysis",
                        "observations": [p[:2000] for p in tool_payloads[:5]],
                    }
                )
            )
        body = "\n".join(p[:2000] for p in tool_payloads)
        return ProviderReply(
            text="Offline analysis over gathered evidence:\n" + body
            if body
            else "Offline analysis: no tool evidence gathered.",
        )


def _safe_json(s: str) -> Dict[str, Any]:
    try:
        out = json.loads(s)
        return out if isinstance(out, dict) else {}
    except (json.JSONDecodeError, TypeError):
        return {}


def _classify_error(e: Exception, provider: str = "") -> LLMUnavailable:
    # the provider name rides in the message: a failure surfacing
    # mid-failover must say WHICH backend died, and callers chain the
    # original via ``raise _classify_error(e, name) from e`` so the root
    # quota error is never dropped (round-6 satellite fix)
    prefix = f"{provider}: " if provider else ""
    msg = str(e).lower()
    if any(k in msg for k in ("quota", "rate limit", "rate_limit", "429")):
        return LLMQuotaExceeded(f"{prefix}{e}")
    return LLMUnavailable(f"{prefix}{e}")


def make_provider(name: Optional[str] = None) -> Provider:
    """Resolve a provider by name or environment.

    ``RCA_LLM_PROVIDER`` ∈ {openai, anthropic, offline}; unset → first of
    anthropic/openai whose key+SDK is available, else offline (reference
    default order: app.py:45-67).
    """
    name = (name or env_str("RCA_LLM_PROVIDER", "")).lower()
    if name == "openai":
        return OpenAIProvider()
    if name == "anthropic":
        return AnthropicProvider()
    if name == "offline":
        return OfflineProvider()
    for cls in (AnthropicProvider, OpenAIProvider):
        try:
            return cls()
        except LLMUnavailable:
            continue
    return OfflineProvider()
