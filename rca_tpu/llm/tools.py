"""Per-signal tool registries bound to the ClusterClient protocol.

The reference declared per-agent OpenAI function schemas
(reference: agents/mcp_metrics_agent.py:35-114, mcp_logs_agent.py:35-139,
mcp_events_agent.py:35-120, mcp_topology_agent.py:35-128,
mcp_traces_agent.py:36-136) but its LLM client never invoked them
(reference: utils/llm_client_improved.py:68 ignores ``tools``).  Here every
schema is paired with an executable bound to the one typed
:class:`~rca_tpu.cluster.protocol.ClusterClient`, so the loop in
:meth:`rca_tpu.llm.client.LLMClient.analyze` really runs them — and since both the real and
mock backends implement the same protocol, every tool works against both
(the reference's mock-only tool breakage, SURVEY.md §2.6, cannot recur).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional

MAX_TOOL_RESULT_CHARS = 6000


@dataclasses.dataclass
class ToolSpec:
    name: str
    description: str
    parameters: Dict[str, Any]
    fn: Callable[..., Any]

    def schema(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "description": self.description,
            "parameters": self.parameters,
        }

    def execute(self, arguments: Dict[str, Any]) -> str:
        props = self.parameters.get("properties", {})
        kwargs = {k: v for k, v in (arguments or {}).items() if k in props}
        try:
            out = self.fn(**kwargs)
        except Exception as e:
            return json.dumps({"error": f"{type(e).__name__}: {e}"})
        try:
            text = json.dumps(out, default=str)
        except (TypeError, ValueError):
            text = str(out)
        if len(text) > MAX_TOOL_RESULT_CHARS:
            text = text[:MAX_TOOL_RESULT_CHARS] + "...[truncated]"
        return text


def _obj(props: Dict[str, dict], required: Optional[List[str]] = None) -> dict:
    return {
        "type": "object",
        "properties": props,
        "required": required or [],
    }


_STR = {"type": "string"}
_INT = {"type": "integer"}


def cluster_toolsets(client, namespace: str) -> Dict[str, List[ToolSpec]]:
    """Tool registry per signal agent, all bound to ``client``/``namespace``."""
    ns = namespace

    def pod_logs(pod_name: str, container: str = "", previous: bool = False,
                 tail_lines: int = 100):
        return client.get_pod_logs(
            ns, pod_name, container=container or None,
            previous=bool(previous), tail_lines=int(tail_lines),
        )

    def search_logs(pattern: str, tail_lines: int = 200):
        """Cross-pod substring search (reference: mcp_logs_agent.py:256-292)."""
        hits = []
        for pod in client.get_pods(ns):
            name = pod.get("metadata", {}).get("name", "")
            try:
                text = client.get_pod_logs(ns, name, tail_lines=int(tail_lines))
            except Exception:
                continue
            for line in (text or "").splitlines():
                if pattern.lower() in line.lower():
                    hits.append({"pod": name, "line": line.strip()[:300]})
                    if len(hits) >= 50:
                        return hits
        return hits

    def resource_events(kind: str, name: str):
        return client.get_events(
            ns,
            field_selector=(
                f"involvedObject.kind={kind},involvedObject.name={name}"
            ),
        )

    def deployment_resource_usage(deployment: str = ""):
        """Deployment-level usage: join deployment → pod metrics by pod-name
        prefix and aggregate (reference: mcp_metrics_agent.py:35-114 declares
        the tool, :201-204 joins by name substring — here the join actually
        executes and averages usage_percentage across the pods)."""
        pod_mets = (client.get_pod_metrics(ns) or {}).get("pods", {})

        def avg(vals):
            vals = [v for v in vals if isinstance(v, (int, float))]
            return round(sum(vals) / len(vals), 2) if vals else None

        deployments = client.get_deployments(ns)
        all_names = [
            d.get("metadata", {}).get("name", "") for d in deployments
        ]

        def owner_of(pod_name: str):
            """Longest deployment-name prefix wins, so pods of
            'backend-worker' never count toward 'backend'."""
            best = None
            for n in all_names:
                if pod_name == n or pod_name.startswith(n + "-"):
                    if best is None or len(n) > len(best):
                        best = n
            return best

        out = []
        for dep in deployments:
            name = dep.get("metadata", {}).get("name", "")
            if deployment and name != deployment:
                continue
            pods = {
                p: m for p, m in pod_mets.items() if owner_of(p) == name
            }
            status = dep.get("status", {}) or {}
            out.append({
                "deployment": name,
                "replicas_desired": (dep.get("spec", {}) or {}).get("replicas"),
                "replicas_ready": status.get("readyReplicas", 0),
                "pods_with_metrics": len(pods),
                "cpu_usage_percentage_avg": avg(
                    (m.get("cpu", {}) or {}).get("usage_percentage")
                    for m in pods.values()
                ),
                "memory_usage_percentage_avg": avg(
                    (m.get("memory", {}) or {}).get("usage_percentage")
                    for m in pods.values()
                ),
                "per_pod": {
                    p: {
                        "cpu": (m.get("cpu", {}) or {}).get("usage"),
                        "memory": (m.get("memory", {}) or {}).get("usage"),
                    }
                    for p, m in pods.items()
                },
            })
        return out

    metrics = [
        ToolSpec("get_pod_metrics", "CPU/memory usage per pod in the namespace",
                 _obj({}), lambda: client.get_pod_metrics(ns)),
        ToolSpec("get_deployment_resource_usage",
                 "Aggregated CPU/memory usage per deployment (joins pod "
                 "metrics onto deployments; optionally one deployment)",
                 _obj({"deployment": _STR}), deployment_resource_usage),
        ToolSpec("get_node_metrics", "CPU/memory usage per cluster node",
                 _obj({}), client.get_node_metrics),
        ToolSpec("get_hpas", "HorizontalPodAutoscaler specs and status",
                 _obj({}), lambda: client.get_hpas(ns)),
        ToolSpec("get_resource_quotas", "ResourceQuota objects in the namespace",
                 _obj({}), lambda: client.get_resource_quotas(ns)),
        ToolSpec("get_deployments",
                 "Deployment specs (includes per-container resource requests/limits)",
                 _obj({}), lambda: client.get_deployments(ns)),
    ]
    logs = [
        ToolSpec("get_pod_logs", "Logs of one pod (optionally one container)",
                 _obj({"pod_name": _STR, "container": _STR,
                       "previous": {"type": "boolean"}, "tail_lines": _INT},
                      ["pod_name"]),
                 pod_logs),
        ToolSpec("search_logs_for_pattern",
                 "Search all pods' recent logs for a substring",
                 _obj({"pattern": _STR, "tail_lines": _INT}, ["pattern"]),
                 search_logs),
        ToolSpec("get_pods", "Pod list with status/containerStatuses",
                 _obj({}), lambda: client.get_pods(ns)),
    ]
    events = [
        ToolSpec("get_namespace_events", "All events in the namespace",
                 _obj({}), lambda: client.get_events(ns)),
        ToolSpec("get_resource_events", "Events for one object (kind + name)",
                 _obj({"kind": _STR, "name": _STR}, ["kind", "name"]),
                 resource_events),
    ]
    topology = [
        ToolSpec("get_services", "Service list with selectors",
                 _obj({}), lambda: client.get_services(ns)),
        ToolSpec("get_endpoints", "Endpoints (ready addresses) per service",
                 _obj({}), lambda: client.get_endpoints(ns)),
        ToolSpec("get_deployments", "Deployment list",
                 _obj({}), lambda: client.get_deployments(ns)),
        ToolSpec("get_ingresses", "Ingress routes",
                 _obj({}), lambda: client.get_ingresses(ns)),
        ToolSpec("get_network_policies", "NetworkPolicy objects",
                 _obj({}), lambda: client.get_network_policies(ns)),
    ]
    traces = [
        ToolSpec("get_trace_ids", "Recent trace ids",
                 _obj({"limit": _INT}),
                 lambda limit=20: client.get_trace_ids(ns, limit=int(limit))),
        ToolSpec("get_trace_details", "Spans of one trace",
                 _obj({"trace_id": _STR}, ["trace_id"]),
                 client.get_trace_details),
        ToolSpec("get_service_latency_stats", "p50/p95/p99 latency per service",
                 _obj({}), lambda: client.get_service_latency_stats(ns)),
        ToolSpec("get_error_rate_by_service", "Error rate per service",
                 _obj({}), lambda: client.get_error_rate_by_service(ns)),
        ToolSpec("get_service_dependencies", "Service dependency map",
                 _obj({}), lambda: client.get_service_dependencies(ns)),
        ToolSpec("find_slow_operations", "Operations slower than threshold_ms",
                 _obj({"threshold_ms": {"type": "number"}}),
                 lambda threshold_ms=500.0: client.find_slow_operations(
                     ns, threshold_ms=float(threshold_ms))),
    ]
    resources = [
        ToolSpec("get_pods", "Pod list with status", _obj({}),
                 lambda: client.get_pods(ns)),
        ToolSpec("get_deployments", "Deployment list", _obj({}),
                 lambda: client.get_deployments(ns)),
        ToolSpec("get_resource_details",
                 "Full manifest of one resource (kind + name)",
                 _obj({"kind": _STR, "name": _STR}, ["kind", "name"]),
                 lambda kind, name: client.get_resource_details(ns, kind, name)),
        ToolSpec("get_namespace_events", "All namespace events", _obj({}),
                 lambda: client.get_events(ns)),
    ]
    return {
        "metrics": metrics,
        "logs": logs,
        "events": events,
        "topology": topology,
        "traces": traces,
        "resources": resources,
    }
