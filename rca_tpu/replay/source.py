"""ReplaySource: a cluster client served entirely from a recording.

Satisfies the same duck-typed ``ClusterClient``/watch-pump protocol the
live and mock clients do — but every method call is answered from the
flight recording's ``call`` frames for the CURRENT tick (the harness
advances the tick cursor before each ``poll()``).  Recorded exceptions
re-raise with equivalent types, so a replayed chaos soak hits the same
retry/degrade/resync paths the live run did.

Lookup is keyed, not blindly positional: within a tick, calls consume
the first unconsumed record matching ``(method, args)`` — the session's
call SEQUENCE is deterministic, but keying makes a divergence loud and
attributable (:class:`ReplayMismatch` names the tick, method, and args)
instead of silently feeding the engine another call's payload.  A repeat
of an already-consumed key within the same tick re-serves the last value
(idempotent reads); a key the tick never recorded is a hard mismatch.

Presence semantics matter: ``hasattr(client, "collect_errors")`` and
``getattr(client, "drain_injected", None)`` gate real control flow in the
session, so :meth:`__getattr__` raises ``AttributeError`` for any method
the recording never saw — a chaos recording replays with a
``drain_injected`` surface, a plain one without, exactly as captured.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Tuple

from rca_tpu.replay.format import make_call_key, restore_ndarrays
from rca_tpu.resilience.chaos import InjectedTimeout


class ReplayMismatch(RuntimeError):
    """The replayed session asked the cluster something the recording
    cannot answer — the replay has diverged at the CAPTURE level (before
    any engine math), which almost always means the session construction
    knobs differ from the header's."""


class ReplayedFault(RuntimeError):
    """Stand-in for a recorded exception type this build cannot (or need
    not) reconstruct exactly; carries the original type name."""


def _rebuild_error(error_type: str, error_msg: str) -> Exception:
    if error_type == "InjectedTimeout":
        return InjectedTimeout(error_msg)
    if "Timeout" in error_type:
        return TimeoutError(error_msg)
    return ReplayedFault(f"{error_type}: {error_msg}")


class ReplaySource:
    """Replay client over parsed ``call`` frames (see replayer.py for the
    full-recording loader).  Drive with :meth:`advance` per tick."""

    def __init__(self, call_frames: List[Dict[str, Any]]):
        # tick -> (method, key) -> FIFO of call records
        by_tick: Dict[int, Dict[Tuple[str, str], collections.deque]] = {}
        methods = set()
        for fr in call_frames:
            methods.add(fr["method"])
            if fr.get("kind") == "coldiff" and fr.get("ok"):
                # column-diff frames (ISSUE 10) carry tagged raw-byte
                # array encodings; restore them once at load so the
                # replayed mirror sees bit-identical numpy columns.  A
                # recording WITHOUT these frames simply never advertises
                # ``get_columnar`` (presence semantics below) and the
                # replayed session runs the dict capture path — old
                # recordings replay exactly as before.
                fr = dict(fr)
                fr["result"] = restore_ndarrays(fr["result"])
            bucket = by_tick.setdefault(int(fr["tick"]), {})
            bucket.setdefault(
                (fr["method"], fr["key"]), collections.deque()
            ).append(fr)
        self._by_tick = by_tick
        self._methods = methods
        self._tick = 0
        # last consumed record per (method, key), reset per tick: repeat
        # reads within one tick re-serve; across ticks they must re-match
        self._served: Dict[Tuple[str, str], Dict[str, Any]] = {}

    # -- harness surface ----------------------------------------------------
    def advance(self, tick: int) -> None:
        self._tick = int(tick)
        self._served = {}

    def unconsumed(self) -> int:
        """Recorded calls of the current tick the session never made —
        nonzero means the replayed session took a DIFFERENT capture path
        (divergence evidence even when rankings happen to agree)."""
        return sum(
            len(dq) for dq in self._by_tick.get(self._tick, {}).values()
        )

    # -- client surface -----------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_") or name not in self._methods:
            raise AttributeError(name)

        def replayed(*args: Any, **kwargs: Any) -> Any:
            return self._consume(name, make_call_key(args, kwargs))

        replayed.__name__ = name
        return replayed

    def _consume(self, method: str, key: str) -> Any:
        bucket = self._by_tick.get(self._tick, {})
        dq = bucket.get((method, key))
        if dq:
            rec = dq.popleft()
            self._served[(method, key)] = rec
        else:
            rec = self._served.get((method, key))
            if rec is None:
                raise ReplayMismatch(
                    f"tick {self._tick}: {method}({key}) has no recorded "
                    "answer — replayed session diverged from the capture "
                    "path (check pipeline_depth/topology_check_every "
                    "against the recording header)"
                )
        if rec["ok"]:
            return rec["result"]
        raise _rebuild_error(rec["error_type"], rec["error_msg"])
