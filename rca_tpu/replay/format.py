"""Flight-recorder log format: CRC-framed, chunked, schema-versioned.

A recording is either a **directory** of chunk files (the recorder's
append path — chunks rotate at a byte budget and each rotation fsyncs, so
a crash loses at most the unsynced tail of one chunk) or a **single file**
(the minted corpus form, ``rca replay --mint``).  Either way the byte
layout is the same:

- every chunk starts with an 8-byte magic ``RCAREC<version>\\n`` — a file
  with a foreign magic is not a recording, and a matching magic with a
  different version byte is a :class:`ReplayFormatError` (schema-version
  mismatch is an ERROR, never a silent partial read);
- frames follow back to back: ``[u32 payload_len][u32 crc32][u8 flags]``
  then the payload — UTF-8 JSON, zlib-compressed when flags bit 0 is set
  (the CRC covers the stored, possibly-compressed bytes);
- a **truncated tail** (EOF inside a frame — the writer crashed mid
  append) or a **corrupt frame** (CRC mismatch — bit rot, torn write)
  stops the read CLEANLY at the last good frame: the reader reports
  ``truncated``/``corrupt`` in its status instead of raising, because a
  crashed recording is still evidence for every tick it completed.

Frame payloads are JSON objects tagged by ``kind`` (``header`` / ``call``
/ ``tick`` / ``serve`` / ``end``); the recorder and replayer own those
schemas (REPLAY.md documents them).  ``json.dumps`` round-trips NaN and
Infinity (Python's non-strict JSON), which matters: chaos-injected
``nan_metrics`` payloads must replay poisoned, not cleaned.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

SCHEMA_VERSION = 1
_MAGIC_PREFIX = b"RCAREC"
MAGIC = _MAGIC_PREFIX + bytes([SCHEMA_VERSION]) + b"\n"
_FRAME_HEAD = struct.Struct("<IIB")  # payload_len, crc32, flags

FLAG_ZLIB = 0x01

#: rotate the active chunk once it exceeds this many bytes (recorder dirs)
DEFAULT_CHUNK_BYTES = 8 * 1024 * 1024
#: compress call/tick payloads larger than this (small frames stay raw —
#: zlib overhead beats the saving under ~1 KiB)
COMPRESS_OVER_BYTES = 1024

CHUNK_GLOB_PREFIX = "chunk-"
CHUNK_SUFFIX = ".rcr"


class ReplayFormatError(ValueError):
    """The bytes are not a (supported) recording: foreign magic, or a
    schema version this build does not read."""


def make_call_key(args: tuple, kwargs: dict) -> str:
    """Stable identity of one client call's arguments — the replay lookup
    key.  Positional and keyword spellings are deliberately NOT unified:
    the session's call sites are the same code at record and replay time,
    so the spelling is part of the determinism being checked."""
    return json.dumps(
        [list(args), sorted(kwargs.items())], sort_keys=True, default=str
    )


def digest_obj(obj: Any) -> str:
    """Stable content digest of a JSON-able object (rankings, changes)."""
    blob = json.dumps(obj, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def digest_array(arr: np.ndarray) -> str:
    """Content digest of an ndarray (shape + dtype + raw bytes)."""
    a = np.ascontiguousarray(arr)
    h = hashlib.sha1()
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    h.update(a.tobytes())
    return h.hexdigest()[:16]


def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    """ndarray -> JSON-able {b64, dtype, shape} (raw little-endian bytes;
    recordings are not meant to cross endianness, the env fingerprint in
    the header says where they came from)."""
    a = np.ascontiguousarray(arr)
    return {
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def decode_array(d: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


def digest_array_crc(arr: np.ndarray) -> str:
    """Content digest of an ndarray in ONE vectorized CRC pass (shape +
    dtype + raw bytes through crc32).  ~10x cheaper per tick than the
    sha1 digest for the recorder's per-tick feature stamp; sha1
    (:func:`digest_array`) remains for old recordings — the tick frame's
    ``digest_algo`` field says which one sealed it."""
    a = np.ascontiguousarray(arr)
    crc = zlib.crc32(f"{a.shape}{a.dtype}".encode())
    crc = zlib.crc32(a.tobytes(), crc)
    return f"{crc:08x}"


_ND_TAG = "__ndarray__"


def jsonify_ndarrays(obj: Any) -> Any:
    """Deep-copy ``obj`` with every ndarray replaced by a tagged
    :func:`encode_array` dict — how a columnar payload (raw numpy columns
    in process) becomes a JSON-able ``coldiff`` frame.  Tuples become
    lists (JSON would anyway); scalars/str/dict keys pass through."""
    if isinstance(obj, np.ndarray):
        return {_ND_TAG: encode_array(obj)}
    if isinstance(obj, dict):
        return {k: jsonify_ndarrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonify_ndarrays(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    return obj


def restore_ndarrays(obj: Any) -> Any:
    """Inverse of :func:`jsonify_ndarrays` (bit-exact: the arrays ride as
    raw little-endian bytes)."""
    if isinstance(obj, dict):
        if set(obj.keys()) == {_ND_TAG}:
            return decode_array(obj[_ND_TAG])
        return {k: restore_ndarrays(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [restore_ndarrays(v) for v in obj]
    return obj


def _pack_frame(obj: Dict[str, Any], compress: Optional[bool] = None
                ) -> bytes:
    payload = json.dumps(obj, default=str).encode("utf-8")
    flags = 0
    if compress is None:
        compress = len(payload) > COMPRESS_OVER_BYTES
    if compress:
        packed = zlib.compress(payload, 6)
        if len(packed) < len(payload):
            payload = packed
            flags |= FLAG_ZLIB
    return _FRAME_HEAD.pack(len(payload), zlib.crc32(payload), flags) + payload


class RecordingWriter:
    """Append-only frame writer.

    ``path`` is a directory (chunked recorder output; created if absent)
    unless ``single_file`` — then it is one file holding every frame (the
    minted form).  Chunks rotate once the active one exceeds
    ``chunk_bytes``; rotation fsyncs the finished chunk so a later crash
    cannot lose it."""

    def __init__(self, path: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 single_file: bool = False):
        self.path = str(path)
        self.chunk_bytes = max(4096, int(chunk_bytes))
        self.single_file = bool(single_file)
        self.bytes_written = 0
        self.frames_written = 0
        self._chunk_index = -1
        self._fh = None
        if not self.single_file:
            os.makedirs(self.path, exist_ok=True)
            existing = chunk_files(self.path)
            if existing:
                raise FileExistsError(
                    f"recording directory {self.path!r} already holds "
                    f"{len(existing)} chunk(s) — refusing to interleave "
                    "two recordings"
                )
        self._open_next()

    def _chunk_path(self, index: int) -> str:
        return os.path.join(
            self.path, f"{CHUNK_GLOB_PREFIX}{index:05d}{CHUNK_SUFFIX}"
        )

    def _open_next(self) -> None:
        if self._fh is not None:
            self._sync_close()
        self._chunk_index += 1
        target = (
            self.path if self.single_file
            else self._chunk_path(self._chunk_index)
        )
        self._fh = open(target, "wb")
        self._fh.write(MAGIC)
        self.bytes_written += len(MAGIC)

    def _sync_close(self) -> None:
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._fh = None

    def append(self, obj: Dict[str, Any],
               compress: Optional[bool] = None) -> None:
        if self._fh is None:
            raise ValueError("writer is closed")
        frame = _pack_frame(obj, compress=compress)
        self._fh.write(frame)
        self.bytes_written += len(frame)
        self.frames_written += 1
        if (not self.single_file
                and self._fh.tell() >= self.chunk_bytes):
            self._open_next()

    def close(self) -> None:
        if self._fh is not None:
            self._sync_close()


@dataclasses.dataclass
class ReadStatus:
    """How a read ended.  ``clean`` means every byte parsed; a truncated
    or corrupt recording still yields its good prefix of frames."""

    frames: int = 0
    chunks: int = 0
    truncated: bool = False    # EOF inside a frame (writer crashed)
    corrupt: bool = False      # CRC mismatch (stopped at last good frame)
    detail: str = ""

    @property
    def clean(self) -> bool:
        return not (self.truncated or self.corrupt)

    def to_dict(self) -> dict:
        return {
            "frames": self.frames, "chunks": self.chunks,
            "truncated": self.truncated, "corrupt": self.corrupt,
            "clean": self.clean,
            **({"detail": self.detail} if self.detail else {}),
        }


def chunk_files(path: str) -> List[str]:
    """The recording directory's chunk files, in append order."""
    try:
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith(CHUNK_GLOB_PREFIX) and n.endswith(CHUNK_SUFFIX)
        )
    except NotADirectoryError:
        return []
    return [os.path.join(path, n) for n in names]


def _check_magic(head: bytes, source: str) -> None:
    if len(head) < len(MAGIC) or head[:len(_MAGIC_PREFIX)] != _MAGIC_PREFIX:
        raise ReplayFormatError(f"{source}: not a flight recording")
    version = head[len(_MAGIC_PREFIX)]
    if version != SCHEMA_VERSION:
        raise ReplayFormatError(
            f"{source}: recording schema version {version}, this build "
            f"reads version {SCHEMA_VERSION} only"
        )


def _iter_file_frames(fp: str, status: ReadStatus
                      ) -> Iterator[Dict[str, Any]]:
    with open(fp, "rb") as f:
        head = f.read(len(MAGIC))
        _check_magic(head, fp)
        while True:
            hdr = f.read(_FRAME_HEAD.size)
            if not hdr:
                return  # clean end of chunk
            if len(hdr) < _FRAME_HEAD.size:
                status.truncated = True
                status.detail = f"{fp}: EOF inside frame header"
                return
            length, crc, flags = _FRAME_HEAD.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length:
                status.truncated = True
                status.detail = f"{fp}: EOF inside frame payload"
                return
            if zlib.crc32(payload) != crc:
                status.corrupt = True
                status.detail = f"{fp}: CRC mismatch at frame {status.frames}"
                return
            if flags & FLAG_ZLIB:
                payload = zlib.decompress(payload)
            try:
                obj = json.loads(payload.decode("utf-8"))
            except ValueError:
                status.corrupt = True
                status.detail = (
                    f"{fp}: undecodable payload at frame {status.frames}"
                )
                return
            status.frames += 1
            yield obj


def read_frames(path: str) -> Tuple[List[Dict[str, Any]], ReadStatus]:
    """Every frame of a recording (directory of chunks, or one file),
    stopping cleanly at a truncated tail or corrupt frame — a broken
    frame also discards the chunks after it (tick continuity is gone).
    Raises :class:`ReplayFormatError` only for a foreign or
    version-mismatched magic, and ``FileNotFoundError`` for no recording
    at all."""
    status = ReadStatus()
    if os.path.isdir(path):
        files = chunk_files(path)
        if not files:
            raise FileNotFoundError(f"no recording chunks under {path!r}")
    elif os.path.exists(path):
        files = [path]
    else:
        raise FileNotFoundError(path)
    frames: List[Dict[str, Any]] = []
    for fp in files:
        status.chunks += 1
        for obj in _iter_file_frames(fp, status):
            frames.append(obj)
        if not status.clean:
            break
    return frames, status
