"""Flight recorder: deterministic incident record/replay + divergence
bisect (REPLAY.md).

- :mod:`rca_tpu.replay.format`    CRC-framed, chunked, schema-versioned
  on-disk log (truncated tails and corrupt frames stop cleanly);
- :mod:`rca_tpu.replay.recorder`  the :class:`Recorder`
  ``LiveStreamingSession`` and ``ServeLoop`` write through — per-tick
  client calls, rankings, feature digests, env fingerprint;
- :mod:`rca_tpu.replay.source`    :class:`ReplaySource`, a cluster
  client answered entirely from a recording (errors re-raise);
- :mod:`rca_tpu.replay.replayer`  replay/seek/bisect/mint + the serve
  replay path, behind ``rca replay``.
"""

from rca_tpu.replay.format import (
    ReadStatus,
    ReplayFormatError,
    SCHEMA_VERSION,
    decode_array,
    digest_array,
    digest_obj,
    encode_array,
    read_frames,
)
from rca_tpu.replay.recorder import (
    FEATURES_FULL_CAP,
    Recorder,
    RecordingClusterClient,
    env_fingerprint,
)
from rca_tpu.replay.replayer import (
    Recording,
    bisect_divergence,
    load_recording,
    mint_recording,
    replay,
    replay_serve,
    replay_stream,
)
from rca_tpu.replay.source import ReplayMismatch, ReplaySource, ReplayedFault

__all__ = [
    "SCHEMA_VERSION",
    "FEATURES_FULL_CAP",
    "ReadStatus",
    "ReplayFormatError",
    "Recorder",
    "Recording",
    "RecordingClusterClient",
    "ReplayMismatch",
    "ReplaySource",
    "ReplayedFault",
    "bisect_divergence",
    "decode_array",
    "digest_array",
    "digest_obj",
    "encode_array",
    "env_fingerprint",
    "load_recording",
    "mint_recording",
    "read_frames",
    "replay",
    "replay_serve",
    "replay_stream",
]
