"""Flight recorder: capture everything a session consumed, per tick.

The determinism argument the whole subsystem rests on: a
:class:`rca_tpu.engine.live.LiveStreamingSession` is a deterministic
function of (a) its construction knobs and (b) the byte-for-byte sequence
of cluster-client responses it observes — every other input (feature
extraction, edge build, the jitted tick) is pure on one platform, which
is exactly what the chaos-parity property has asserted since PR 1.  So
the recorder does NOT snapshot engine internals; it wraps the client and
records every call's (method, args, result-or-exception) inside tick
boundaries, plus each tick's produced ranking and a digest of the host
feature mirror.  Replay (:mod:`rca_tpu.replay.source`) re-serves those
responses to the REAL engine and the rankings must come back
bit-identical — at any pipeline depth and on either engine kind, because
neither changes what the capture path asks the cluster.

Chaos runs record faithfully: injected faults surface as client-call
EXCEPTIONS (recorded, re-raised on replay) and ``drain_injected`` results
(recorded like any call), so a replayed chaos soak walks the exact same
degraded paths the live one did.

Frame kinds written here (format.py owns the byte layout):

- ``header``  once, first: schema, mode (stream/serve), session knobs,
  env fingerprint, optional seeds;
- ``call``    one per client call, tagged with the current tick
  (tick 0 = the session's bootstrap capture);
- ``tick``    one per poll: delivered ranking (+ digest), host feature
  digest (full rows too, below the size cap), health excerpt;
- ``serve``   one per served request (serve mode): full request inputs +
  the ranking it got — self-contained, replayable without a cluster;
- ``end``     on close: tick/serve counts.  A recording without it is a
  crashed (possibly truncated) capture; replay still covers every
  complete tick.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from rca_tpu.replay.format import (
    SCHEMA_VERSION,
    RecordingWriter,
    digest_array_crc,
    digest_obj,
    encode_array,
    jsonify_ndarrays,
    make_call_key,
)

#: record full per-tick feature rows only while the matrix stays under
#: this many elements — above it, the digest alone rides along (bisect
#: then diffs replayed tensors against the digest, not stored rows)
FEATURES_FULL_CAP = 65536

#: health-record keys copied into each tick frame (forensics; the parity
#: contract itself is on the ranking digest)
_HEALTH_KEYS = (
    "sanitized_rows", "degradation", "resyncs_expired", "resyncs_topology",
    "pipeline_fill", "retries",
    # ISSUE 11: the tick's span list (absent with RCA_TRACE=0) — what
    # lets `rca replay --trace-out` rebuild an incident's timeline from
    # the tape instead of re-running it
    "spans",
)


def wall_now() -> str:
    """Wall-clock stamp for recording METADATA (header ``created_at``).
    The one legitimate wall read in the replay subsystem — nothing
    replayed ever depends on it (nondet-discipline allowlists exactly
    this function)."""
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def env_fingerprint() -> Dict[str, Any]:
    """What machine/stack produced a recording — stamped into the header
    so a cross-host parity failure is attributable before any bisect."""
    import jax

    from rca_tpu.config import env_raw, env_str
    from rca_tpu.version import __version__

    return {
        "rca_tpu": __version__,
        "jax": jax.__version__,
        "numpy": np.__version__,
        "jax_backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "rca_backend": env_str("RCA_BACKEND", "jax"),
        "rca_shard": env_raw("RCA_SHARD"),
        "rca_pallas": env_raw("RCA_PALLAS"),
        "rca_pipeline_depth": env_raw("RCA_PIPELINE_DEPTH"),
    }


class Recorder:
    """One recording in progress.  Thread-compat note: the streaming path
    is single-threaded by construction; the serve path records from the
    one serve-worker thread — neither needs a lock here."""

    def __init__(
        self,
        path: str,
        mode: str = "stream",
        features_cap: int = FEATURES_FULL_CAP,
        chunk_bytes: Optional[int] = None,
        seeds: Optional[Dict[str, int]] = None,
        meta: Optional[Dict[str, Any]] = None,
    ):
        if mode not in ("stream", "serve"):
            raise ValueError(f"mode must be stream|serve, got {mode!r}")
        kw = {} if chunk_bytes is None else {"chunk_bytes": chunk_bytes}
        self._writer = RecordingWriter(str(path), **kw)
        self.path = str(path)
        self.mode = mode
        self.features_cap = int(features_cap)
        self._tick = 0
        self.ticks_recorded = 0
        self.serve_recorded = 0
        self.calls_recorded = 0
        self._closed = False
        self._header_written = False
        self._pending_header: Dict[str, Any] = {
            "kind": "header", "schema": SCHEMA_VERSION, "mode": mode,
            "created_at": wall_now(), "env": env_fingerprint(),
            "seeds": dict(seeds or {}), "meta": dict(meta or {}),
            "session": {},
        }

    # -- header -------------------------------------------------------------
    def begin_session(self, info: Dict[str, Any]) -> None:
        """Session construction knobs (namespace, k, depth, engine tag...)
        — merged into the header, which is written on the first frame so
        it is always frame 0 even when info arrives in pieces."""
        self._pending_header["session"].update(info)

    def _ensure_header(self) -> None:
        if not self._header_written:
            self._writer.append(self._pending_header)
            self._header_written = True

    # -- client wrapping ----------------------------------------------------
    def wrap_client(self, client: Any) -> "RecordingClusterClient":
        return RecordingClusterClient(client, self)

    def record_call(self, method: str, key: str, ok: bool,
                    result: Any = None,
                    error: Optional[BaseException] = None) -> None:
        self._ensure_header()
        # columnar feed answers are first-class COLUMN-DIFF frames
        # (ISSUE 10): the full table dump once, then row ops — instead of
        # re-recording whole object lists every capture.  Their numpy
        # columns ride as tagged raw-byte encodings (bit-exact on
        # replay); every other call records exactly as before, so
        # pre-columnar recordings and sessions are unaffected.
        coldiff = method == "get_columnar"
        frame: Dict[str, Any] = {
            "kind": "coldiff" if coldiff else "call",
            "tick": self._tick, "method": method,
            "key": key, "ok": bool(ok),
        }
        if ok:
            frame["result"] = jsonify_ndarrays(result) if coldiff else result
        else:
            frame["error_type"] = type(error).__name__
            frame["error_msg"] = str(error)
        self._writer.append(frame)
        self.calls_recorded += 1

    # -- tick boundaries ----------------------------------------------------
    def begin_tick(self, tick: int) -> None:
        self._ensure_header()
        self._tick = int(tick)

    def end_tick(self, out: Dict[str, Any],
                 features: Optional[np.ndarray] = None) -> None:
        """Seal one poll: the DELIVERED ranking (depth-lagged at pipeline
        depth >= 2 — replay at the same depth reproduces the same lag) and
        the host feature mirror's digest, with full rows while small."""
        health = out.get("health", {}) or {}
        frame: Dict[str, Any] = {
            "kind": "tick", "tick": self._tick,
            "ranked": out.get("ranked", []),
            "ranked_digest": digest_obj(out.get("ranked", [])),
            "quiet": bool(out.get("quiet", False)),
            "resynced": bool(out.get("resynced", False)),
            "degraded": bool(out.get("degraded", False)),
            "changed_rows": int(out.get("changed_rows", 0)),
            "health": {k: health.get(k) for k in _HEALTH_KEYS},
        }
        if "attribution_digest" in out:
            # causelens (ISSUE 14): the digest of this tick's attribution
            # block — `rca replay --explain` recomputes the block from
            # the tape and parity-checks against THIS
            frame["attribution_digest"] = out["attribution_digest"]
        if features is not None:
            f = np.asarray(features, np.float32)
            # one vectorized CRC pass over the host mirror (ISSUE 10);
            # old recordings carry sha1 digests — digest_algo says which
            frame["features_digest"] = digest_array_crc(f)
            frame["digest_algo"] = "crc32"
            frame["features_shape"] = list(f.shape)
            if f.size <= self.features_cap:
                frame["features"] = encode_array(f)
        self._writer.append(frame)
        self.ticks_recorded += 1

    # -- serve records -------------------------------------------------------
    def record_serve(self, req: Any, ranked: List[dict]) -> None:
        """One served request, self-contained: the full inputs plus the
        ranking the coalesced batch produced — replay re-runs the same
        analysis solo and the serve parity contract (any batch width ==
        solo) makes bit-identity the expectation, not a hope."""
        self._ensure_header()
        trace = getattr(req, "trace", None)
        self._writer.append({
            "kind": "serve", "index": self.serve_recorded,
            "request_id": req.request_id, "tenant": req.tenant,
            # trace identity (ISSUE 11): lets a serve recording map each
            # request onto its wire trace without re-serving anything
            "trace_id": trace.trace_id if trace is not None else None,
            "k": int(req.k),
            "names": list(req.names) if req.names is not None else None,
            "features": encode_array(req.features),
            "dep_src": encode_array(req.dep_src),
            "dep_dst": encode_array(req.dep_dst),
            "ranked": ranked,
            "ranked_digest": digest_obj(ranked),
        }, compress=True)
        self.serve_recorded += 1

    # -- lifecycle -----------------------------------------------------------
    @property
    def bytes_written(self) -> int:
        return self._writer.bytes_written

    def close(self) -> None:
        if self._closed:
            return
        self._ensure_header()
        self._writer.append({
            "kind": "end", "ticks": self.ticks_recorded,
            "serve": self.serve_recorded, "calls": self.calls_recorded,
        })
        self._writer.close()
        self._closed = True

    def __enter__(self) -> "Recorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RecordingClusterClient:
    """Transparent recording proxy over any ``ClusterClient`` (or a chaos
    wrapper around one).  Every METHOD call passes through and its result
    — or raised exception — is recorded under the current tick; results
    are serialized at call time, so later in-place mutation by the caller
    cannot retro-edit the tape.  Non-callable attributes pass through
    unrecorded, and attributes the inner client lacks raise
    ``AttributeError`` exactly as before, so ``hasattr``-gated optional
    surfaces (``collect_errors``, ``drain_injected``, ``watch_close``)
    keep their presence/absence semantics on replay."""

    def __init__(self, inner: Any, recorder: Recorder):
        self._rec_inner = inner
        self._rec_recorder = recorder

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._rec_inner, name)  # AttributeError propagates
        if not callable(attr):
            return attr
        recorder = self._rec_recorder

        def recorded(*args: Any, **kwargs: Any) -> Any:
            key = make_call_key(args, kwargs)
            try:
                result = attr(*args, **kwargs)
            except Exception as exc:
                recorder.record_call(name, key, ok=False, error=exc)
                raise
            recorder.record_call(name, key, ok=True, result=result)
            return result

        recorded.__name__ = name
        return recorded
