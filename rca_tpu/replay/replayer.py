"""Deterministic replay: re-drive the real engine from a recording.

``replay_stream`` rebuilds a :class:`LiveStreamingSession` with the
knobs the recording's header captured, feeds it a
:class:`rca_tpu.replay.source.ReplaySource` instead of a cluster, and
asserts tick-by-tick bit-identity of the delivered rankings against the
``tick`` frames.  Any engine kind may replay any recording — the capture
path asks the cluster the same questions regardless of engine, and the
dense/sharded engines are parity-locked — so a production incident
recorded on a sharded TPU session re-drives on a laptop CPU.

``bisect_divergence`` localizes a parity break: probe(T) replays a FRESH
session from tick 1 through T and compares only tick T, and a binary
search finds the minimal divergent T.  The monotonicity this relies on is
the state-contamination property the chaos harness already leans on: a
tick that computes from diverged state stays diverged until a full resync
rewrites it — and a resync's inputs come from the same recorded calls, so
a pre-resync divergence moves the probe boundary, not the verdict.  At
the first divergent tick both sides' feature/ranking tensors dump to a
JSON file for diffing.

``replay_serve`` replays serve-mode recordings: every ``serve`` frame is
self-contained (full request inputs + the ranking its coalesced batch
produced), so replay re-runs each analysis solo and leans on the serving
parity contract (any batch width == solo, SERVING.md) for bit-identity.

``mint_recording`` compacts a recording directory into one
frame-compressed file — the committed-corpus form consumed by
``tests/corpus`` (every fixture there replays under tier-1).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

import numpy as np

from rca_tpu.replay.format import (
    ReadStatus,
    RecordingWriter,
    ReplayFormatError,
    SCHEMA_VERSION,
    decode_array,
    digest_array,
    digest_array_crc,
    digest_obj,
    read_frames,
)


def _feature_digest_like(frame: Optional[dict], arr: np.ndarray) -> str:
    """Digest ``arr`` with the SAME algorithm that sealed the recorded
    tick frame (crc32 since ISSUE 10; sha1 before), so recorded-vs-
    replayed digests stay comparable across recording vintages."""
    if frame is not None and frame.get("digest_algo") == "crc32":
        return digest_array_crc(arr)
    return digest_array(arr)
from rca_tpu.replay.recorder import env_fingerprint
from rca_tpu.replay.source import ReplaySource

#: mismatched ticks listed in full before the report truncates (the
#: count and first divergence always survive)
_MISMATCH_DETAIL_CAP = 8


@dataclasses.dataclass
class Recording:
    """A parsed recording: header + frames partitioned by kind."""

    path: str
    header: Dict[str, Any]
    calls: List[Dict[str, Any]]
    ticks: Dict[int, Dict[str, Any]]
    serve: List[Dict[str, Any]]
    end: Optional[Dict[str, Any]]
    status: ReadStatus

    @property
    def mode(self) -> str:
        return self.header.get("mode", "stream")

    @property
    def session_info(self) -> Dict[str, Any]:
        return self.header.get("session", {}) or {}

    @property
    def clean_close(self) -> bool:
        """The recorder closed properly (end frame present, no broken
        tail) — a crashed capture still replays its complete ticks."""
        return self.end is not None and self.status.clean


def load_recording(path: str) -> Recording:
    frames, status = read_frames(path)
    if not frames or frames[0].get("kind") != "header":
        raise ReplayFormatError(f"{path}: recording has no header frame")
    header = frames[0]
    if header.get("schema") != SCHEMA_VERSION:
        raise ReplayFormatError(
            f"{path}: header schema {header.get('schema')!r}, this build "
            f"reads {SCHEMA_VERSION}"
        )
    calls: List[Dict[str, Any]] = []
    ticks: Dict[int, Dict[str, Any]] = {}
    serve: List[Dict[str, Any]] = []
    end = None
    for fr in frames[1:]:
        kind = fr.get("kind")
        if kind in ("call", "coldiff"):
            # coldiff = a recorded get_columnar answer (column diffs,
            # ISSUE 10) — consumed through the same keyed call table
            calls.append(fr)
        elif kind == "tick":
            ticks[int(fr["tick"])] = fr
        elif kind == "serve":
            serve.append(fr)
        elif kind == "end":
            end = fr
    # calls are written before their tick frame seals the poll, so a tick
    # frame's presence implies its calls all survived any truncation —
    # ticks past the break simply have no frame and are not replayed
    return Recording(path=str(path), header=header, calls=calls,
                     ticks=ticks, serve=serve, end=end, status=status)


# -- stream replay ----------------------------------------------------------

@dataclasses.dataclass
class _StreamRun:
    session: Any
    delivered: Dict[int, List[dict]]  # tick -> delivered ranking
    mismatched: List[int]             # ticks whose digest diverged
    unconsumed_calls: int             # recorded calls replay never made
    # causelens (ISSUE 14): attribution-digest parity per tick —
    # compared wherever the recorded frame carries a digest
    attribution_compared: int = 0
    attribution_mismatched: List[int] = dataclasses.field(
        default_factory=list
    )


def _engine_for(rec: Recording, engine: Any) -> Any:
    """Default replay engine: the RECORDED kind.  Stream rankings
    (component + score) are parity-locked across engines, but serve
    results carry per-node channels (downstream_impact, ...) whose
    sharded psum reductions differ from the dense sum at the last ulp —
    bitwise claims only hold like-for-like, so like-for-like is the
    default and cross-engine replay is an explicit choice."""
    if engine is not None:
        return engine
    tag = rec.session_info.get("engine")
    if tag == "GraphEngine":
        from rca_tpu.engine.runner import GraphEngine

        return GraphEngine()
    if tag == "ShardedGraphEngine":
        from rca_tpu.engine.sharded_runner import ShardedGraphEngine

        return ShardedGraphEngine()
    from rca_tpu.engine.sharded_runner import make_engine

    return make_engine()


def _replay_session(rec: Recording, source: ReplaySource, engine: Any,
                    pipeline_depth: Optional[int]) -> Any:
    from rca_tpu.engine.live import LiveStreamingSession

    info = rec.session_info
    return LiveStreamingSession(
        source,
        info.get("namespace", "default"),
        k=int(info.get("k", 5)),
        engine=engine,
        topology_check_every=int(info.get("topology_check_every", 5)),
        use_watch=bool(info.get("use_watch", True)),
        pipeline_depth=pipeline_depth,
        # pin the recorded capture path: a columnar recording must replay
        # columnar even if RCA_COLUMNAR is off in the replaying process
        # (and vice versa) — pre-columnar headers default to True, which
        # is harmless because ReplaySource only advertises get_columnar
        # when coldiff frames exist
        use_columnar=bool(info.get("use_columnar", True)),
        # pin the recorded explain mode the same way (ISSUE 14): an
        # explained recording recomputes its per-tick attribution
        # digests on replay so they can parity-check against the tape
        explain=bool(info.get("explain", False)),
    )


def _tick_diverged(recorded: dict, delivered: List[dict],
                   parity: str) -> bool:
    """One tick's parity verdict.  ``exact`` is the bitwise digest gate;
    ``rank`` (ISSUE 13) judges hit@1/hit@3 + Kendall-tau instead — the
    gate mode that makes the quantized kernel replayable (its scores
    move in the 4th decimal; its RANKING must not)."""
    if parity == "rank":
        from rca_tpu.engine.quantized import rank_parity

        return not rank_parity(
            recorded.get("ranked") or [], delivered
        )["ok"]
    return digest_obj(delivered) != recorded["ranked_digest"]


def _run_stream(rec: Recording, engine: Any = None,
                pipeline_depth: Optional[int] = None,
                upto: Optional[int] = None,
                compare: bool = True, parity: str = "exact") -> _StreamRun:
    info = rec.session_info
    depth = (
        int(info.get("pipeline_depth", 1)) if pipeline_depth is None
        else max(1, int(pipeline_depth))
    )
    src_ticks = sorted(rec.ticks)
    if upto is not None:
        src_ticks = [t for t in src_ticks if t <= upto]
    # bootstrap (tick 0) consumes the recorded initial capture
    source = ReplaySource(rec.calls)
    session = _replay_session(rec, source, _engine_for(rec, engine), depth)
    delivered: Dict[int, List[dict]] = {}
    mismatched: List[int] = []
    attribution_compared = 0
    attribution_mismatched: List[int] = []
    unconsumed = 0
    for t in src_ticks:
        source.advance(t)
        out = session.poll()
        delivered[t] = out["ranked"]
        unconsumed += source.unconsumed()
        if compare and _tick_diverged(rec.ticks[t], out["ranked"], parity):
            mismatched.append(t)
        recorded_digest = rec.ticks[t].get("attribution_digest")
        if compare and recorded_digest is not None:
            # causelens parity (ISSUE 14): the replayed session
            # recomputed this tick's attribution from the tape — its
            # digest must match what the live session recorded
            attribution_compared += 1
            if out.get("attribution_digest") != recorded_digest:
                attribution_mismatched.append(t)
    return _StreamRun(session=session, delivered=delivered,
                      mismatched=mismatched, unconsumed_calls=unconsumed,
                      attribution_compared=attribution_compared,
                      attribution_mismatched=attribution_mismatched)


def _serial_sequence(by_tick: Dict[int, List[dict]], depth: int
                     ) -> List[List[dict]]:
    """Strip pipeline lag: delivered tick t carries serial ranking
    t-(depth-1), so the serial sequence is the delivered one with the
    first depth-1 (fill) entries dropped.  Exact for fault-free logs;
    degradation flushes re-fill the pipeline and shift the tail."""
    ordered = [by_tick[t] for t in sorted(by_tick)]
    return ordered[max(0, depth - 1):]


def replay_stream(
    path: str,
    engine: Any = None,
    pipeline_depth: Optional[int] = None,
    seek: Optional[int] = None,
    ticks: Optional[int] = None,
    parity: str = "exact",
    explain: bool = False,
) -> Dict[str, Any]:
    """Replay a stream recording and score per-tick parity.

    ``parity`` picks the gate mode: ``exact`` (the default bitwise
    digest claim) or ``rank`` (hit@1/hit@3 + Kendall-tau per tick —
    ISSUE 13's first-class gate for the quantized kernel, whose scores
    legitimately move in the low decimals while its ranking must not).

    ``seek`` replays up to that tick (time travel) and attaches its full
    detail (both rankings, feature digests/rows) to the report.  When the
    replay depth differs from the recorded one, per-tick delivered
    rankings legitimately shift by the lag difference, so the report
    compares the lag-stripped SERIAL sequences instead."""
    if parity not in ("exact", "rank"):
        raise ValueError(f"parity={parity!r}: expected 'exact' or 'rank'")
    rec = load_recording(path)
    if rec.mode != "stream":
        raise ValueError(f"{path}: {rec.mode!r} recording; use replay_serve")
    info = rec.session_info
    rec_depth = int(info.get("pipeline_depth", 1))
    depth = rec_depth if pipeline_depth is None else max(1, int(pipeline_depth))
    upto = seek
    if ticks is not None:
        upto = min(ticks, upto) if upto is not None else ticks
    run = _run_stream(rec, engine=engine, pipeline_depth=depth, upto=upto,
                      compare=(depth == rec_depth), parity=parity)
    report: Dict[str, Any] = {
        "mode": "stream",
        "parity_mode": parity,
        "recording": rec.path,
        "ticks_recorded": len(rec.ticks),
        "ticks_replayed": len(run.delivered),
        "clean_close": rec.clean_close,
        "read_status": rec.status.to_dict(),
        "pipeline_depth_recorded": rec_depth,
        "pipeline_depth_replayed": depth,
        "engine_recorded": info.get("engine"),
        "engine_replayed": type(run.session.engine).__name__,
        "unconsumed_calls": run.unconsumed_calls,
        "env_recorded": rec.header.get("env", {}),
        "env_replay": env_fingerprint(),
    }
    if depth == rec_depth:
        report["parity_ok"] = (
            not run.mismatched and run.unconsumed_calls == 0
        )
        report["mismatched_ticks"] = run.mismatched[:_MISMATCH_DETAIL_CAP]
        report["first_divergent_tick"] = (
            run.mismatched[0] if run.mismatched else None
        )
        # causelens parity (ISSUE 14): compared automatically wherever
        # recorded frames carry attribution digests; ``explain=True``
        # (`rca replay --explain`) additionally REQUIRES them — a
        # recording made without RCA_EXPLAIN cannot satisfy the gate
        if run.attribution_compared or explain:
            report["attribution_ticks_compared"] = run.attribution_compared
            report["attribution_mismatched_ticks"] = (
                run.attribution_mismatched[:_MISMATCH_DETAIL_CAP]
            )
            attribution_ok = not run.attribution_mismatched and (
                run.attribution_compared > 0 or not explain
            )
            report["attribution_parity_ok"] = attribution_ok
            if explain and run.attribution_compared == 0:
                report["attribution_error"] = (
                    "recording carries no attribution digests "
                    "(record with RCA_EXPLAIN=1)"
                )
            report["parity_ok"] = bool(
                report["parity_ok"] and attribution_ok
            )
    else:
        recorded_serial = _serial_sequence(
            {t: rec.ticks[t]["ranked"] for t in run.delivered}, rec_depth
        )
        replayed_serial = _serial_sequence(run.delivered, depth)
        n = min(len(recorded_serial), len(replayed_serial))
        if parity == "rank":
            from rca_tpu.engine.quantized import rank_parity

            def same(i):
                return rank_parity(
                    recorded_serial[i], replayed_serial[i]
                )["ok"]
        else:
            def same(i):
                return digest_obj(recorded_serial[i]) == digest_obj(
                    replayed_serial[i]
                )
        first = next((i for i in range(n) if not same(i)), None)
        report["serial_ticks_compared"] = n
        report["parity_ok"] = first is None and run.unconsumed_calls == 0
        report["first_divergent_serial"] = first
        if explain:
            # delivered rankings shift by the lag difference, so the
            # per-tick digest pairing is undefined across depths
            report["attribution_parity_ok"] = None
            report["attribution_error"] = (
                "cross-depth replay: attribution digests compare only "
                "at the recorded pipeline depth"
            )
    if seek is not None:
        t = seek
        recd = rec.ticks.get(t)
        detail: Dict[str, Any] = {
            "tick": t,
            "replayed_ranked": run.delivered.get(t),
            "recorded_ranked": recd["ranked"] if recd else None,
            "recorded_features_digest": (
                recd.get("features_digest") if recd else None
            ),
        }
        feats = getattr(run.session, "_features", None)
        if feats is not None:
            detail["replayed_features_digest"] = _feature_digest_like(
                recd, np.asarray(feats, np.float32)
            )
        report["seek"] = detail
    return report


# -- divergence bisect ------------------------------------------------------

def bisect_divergence(
    path: str,
    engine: Any = None,
    pipeline_depth: Optional[int] = None,
    dump_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Binary-search the FIRST divergent tick of a diverging recording.

    Each probe replays a fresh session from tick 1 through T and judges
    only tick T, so the search needs no per-tick trust in intermediate
    comparisons; O(n log n) tick replays total, all sharing the jitted
    executables.  On divergence, both sides' tensors at the first
    divergent tick are dumped for diffing."""
    rec = load_recording(path)
    if rec.mode != "stream":
        raise ValueError(f"{path}: {rec.mode!r} recording; use replay_serve")
    tick_ids = sorted(rec.ticks)
    if not tick_ids:
        raise ValueError(f"{path}: recording holds no ticks")

    def divergent_at(t: int) -> bool:
        run = _run_stream(rec, engine=engine, pipeline_depth=pipeline_depth,
                          upto=t)
        return t in set(run.mismatched)

    probes = 0
    last = tick_ids[-1]
    probes += 1
    if not divergent_at(last):
        return {
            "mode": "stream", "recording": rec.path, "divergent": False,
            "ticks": len(tick_ids), "probes": probes,
            "first_divergent_tick": None,
        }
    lo, hi = 0, len(tick_ids) - 1  # invariant: tick_ids[hi] divergent
    while lo < hi:
        mid = (lo + hi) // 2
        probes += 1
        if divergent_at(tick_ids[mid]):
            hi = mid
        else:
            lo = mid + 1
    first = tick_ids[lo]

    # dump both sides' tensors at the divergence for offline diffing
    run = _run_stream(rec, engine=engine, pipeline_depth=pipeline_depth,
                      upto=first)
    recd = rec.ticks[first]
    dump: Dict[str, Any] = {
        "tick": first,
        "recorded_ranked": recd["ranked"],
        "replayed_ranked": run.delivered.get(first),
        "recorded_features_digest": recd.get("features_digest"),
        "recorded_features": recd.get("features"),
    }
    feats = getattr(run.session, "_features", None)
    if feats is not None:
        f = np.asarray(feats, np.float32)
        dump["replayed_features_digest"] = _feature_digest_like(recd, f)
        dump["replayed_features_shape"] = list(f.shape)
        if recd.get("features") is not None:
            rf = decode_array(recd["features"])
            if rf.shape == f.shape:
                diff = np.abs(rf - f)
                rows = np.flatnonzero(np.any(rf != f, axis=1))
                dump["feature_diff"] = {
                    "max_abs": float(diff.max()),
                    "rows_differing": [int(r) for r in rows[:32]],
                    "n_rows_differing": int(len(rows)),
                }
    out_path = dump_path or _default_dump_path(rec.path)
    import json as _json

    with open(out_path, "w", encoding="utf-8") as f:
        _json.dump(dump, f, default=str)
    return {
        "mode": "stream", "recording": rec.path, "divergent": True,
        "first_divergent_tick": first, "probes": probes,
        "ticks": len(tick_ids), "dump": out_path,
        "recorded_ranked": recd["ranked"],
        "replayed_ranked": run.delivered.get(first),
    }


def _default_dump_path(path: str) -> str:
    base = path.rstrip("/\\")
    return base + ".divergence.json"


# -- serve replay -----------------------------------------------------------

def replay_serve(path: str, engine: Any = None) -> Dict[str, Any]:
    """Re-run every recorded served request solo and assert bit-identity
    with the ranking its (arbitrarily coalesced) batch produced."""
    from rca_tpu.serve.dispatcher import BatchDispatcher
    from rca_tpu.serve.request import ServeRequest

    rec = load_recording(path)
    if rec.mode != "serve":
        raise ValueError(f"{path}: {rec.mode!r} recording; use replay_stream")
    disp = BatchDispatcher(_engine_for(rec, engine))
    mismatched: List[Dict[str, Any]] = []
    for fr in rec.serve:
        req = ServeRequest(
            tenant=fr["tenant"],
            features=decode_array(fr["features"]),
            dep_src=decode_array(fr["dep_src"]),
            dep_dst=decode_array(fr["dep_dst"]),
            names=fr.get("names"), k=int(fr.get("k", 5)),
        )
        result = disp.fetch(disp.dispatch([req]))[0]
        ranked = [dict(r) for r in result.ranked]
        if digest_obj(ranked) != fr["ranked_digest"]:
            mismatched.append({
                "index": fr.get("index"),
                "request_id": fr.get("request_id"),
                "recorded": fr["ranked"], "replayed": ranked,
            })
    return {
        "mode": "serve",
        "recording": rec.path,
        "requests_recorded": len(rec.serve),
        "clean_close": rec.clean_close,
        "read_status": rec.status.to_dict(),
        "parity_ok": not mismatched,
        "mismatched": mismatched[:_MISMATCH_DETAIL_CAP],
        "first_divergent_index": (
            mismatched[0]["index"] if mismatched else None
        ),
        "engine_replayed": disp.engine_tag,
        "env_recorded": rec.header.get("env", {}),
        "env_replay": env_fingerprint(),
    }


def replay(path: str, **kwargs: Any) -> Dict[str, Any]:
    """Mode-dispatching convenience: stream recordings replay through the
    live session, serve recordings through the solo dispatcher."""
    rec = load_recording(path)
    if rec.mode == "serve":
        return replay_serve(path, engine=kwargs.get("engine"))
    return replay_stream(path, **kwargs)


# -- minting (corpus fixtures) ----------------------------------------------

def mint_recording(src: str, out: str,
                   require_clean: bool = True) -> Dict[str, Any]:
    """Compact a recording into ONE frame-compressed file — the committed
    corpus form.  Refuses (by default) to mint a truncated/corrupt or
    unclosed capture: a fixture must be complete evidence."""
    rec = load_recording(src)
    if require_clean and not rec.clean_close:
        raise ValueError(
            f"{src}: not cleanly closed ({rec.status.to_dict()}) — "
            "refusing to mint a fixture from partial evidence"
        )
    frames, _status = read_frames(src)
    writer = RecordingWriter(out, single_file=True)
    for fr in frames:
        writer.append(fr, compress=True)
    writer.close()
    src_bytes = _tree_bytes(src)
    return {
        "src": str(src), "out": str(out),
        "frames": len(frames),
        "ticks": len(rec.ticks), "serve": len(rec.serve),
        "bytes_in": src_bytes,
        "bytes_out": os.path.getsize(out),
    }


def _tree_bytes(path: str) -> int:
    if os.path.isdir(path):
        return sum(
            os.path.getsize(os.path.join(path, n))
            for n in os.listdir(path)
            if os.path.isfile(os.path.join(path, n))
        )
    return os.path.getsize(path)
