import sys

from rca_tpu.cli import main

sys.exit(main())
