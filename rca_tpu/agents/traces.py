"""Traces agent: platform detection, latency/error analysis, slow operations.

Parity with the reference's traces agent (reference: agents/traces_agent.py —
platform detection via service labels jaeger/zipkin/opentelemetry :43-45,
:118-146, instrumentation detection via env-var names :148-207, latency /
error-rate / dependency analyses :209-381).  Where the reference simulated
those analyses, this one computes them from the snapshot's trace data
(latency percentiles, per-service error rates, dependency fan-in) using the
same degradation scores the feature extractor packs for the engine.
"""

from __future__ import annotations

import numpy as np

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.features.schema import SvcF

TRACING_PLATFORMS = ("jaeger", "zipkin", "opentelemetry", "tempo")
INSTRUMENTATION_ENV_HINTS = (
    "OTEL_", "JAEGER_", "ZIPKIN_", "TRACING_", "TRACE_AGENT",
)
ERROR_HIGH, ERROR_MEDIUM = 0.10, 0.05
SLOW_MS = 500.0


class TracesAgent(Agent):
    agent_type = "traces"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        snap = ctx.snapshot
        fs = ctx.features
        traces = snap.traces or {}

        # -- platform / instrumentation detection ----------------------------
        platforms = set()
        for obj in list(snap.services) + list(snap.deployments):
            labels = obj.get("metadata", {}).get("labels", {}) or {}
            text = " ".join([*labels.keys(), *labels.values()]).lower()
            name = obj.get("metadata", {}).get("name", "").lower()
            for p in TRACING_PLATFORMS:
                if p in text or p in name:
                    platforms.add(p)
        instrumented = []
        for pod in snap.pods:
            for c in pod.get("spec", {}).get("containers", []) or []:
                env_names = {e.get("name", "") for e in c.get("env", []) or []}
                if any(
                    n.startswith(INSTRUMENTATION_ENV_HINTS) for n in env_names
                ):
                    instrumented.append(pod.get("metadata", {}).get("name", ""))
                    break
        r.add_step(
            f"Tracing platforms detected: {sorted(platforms) or 'none'}; "
            f"{len(instrumented)} pod(s) carry instrumentation env vars.",
            "Trace-derived signals follow." if traces else
            "No trace data in snapshot; structural checks only.",
        )
        if not platforms and not traces:
            r.add_finding(
                f"Namespace/{snap.namespace}",
                "no tracing platform detected in the namespace",
                "info",
                {"checked_labels": list(TRACING_PLATFORMS)},
                "Deploy a tracing backend (e.g. an OpenTelemetry collector) "
                "to make latency root-causing possible",
            )

        # -- per-service error rates ------------------------------------------
        err = traces.get("error_rates") or {}
        for name, rate in sorted(err.items()):
            rate = float(rate)
            if rate >= ERROR_MEDIUM:
                r.add_finding(
                    f"Service/{name}",
                    f"trace error rate at {rate * 100:.0f}%",
                    "high" if rate >= ERROR_HIGH else "medium",
                    {"error_rate": rate},
                    "Inspect failing spans for this service; correlate with "
                    "its logs and upstream dependencies",
                )

        # -- latency degradation (packed channel: p99 vs namespace median) ---
        lat = traces.get("latency") or {}
        degraded = np.nonzero(fs.service_features[:, SvcF.LATENCY] > 0.25)[0]
        for i in degraded.tolist():
            name = fs.service_names[i]
            stats = lat.get(name) or {}
            r.add_finding(
                f"Service/{name}",
                f"p99 latency degraded ({stats.get('p99', '?')} ms vs "
                "namespace median)",
                "medium",
                {"latency_stats": stats,
                 "degradation_score": round(
                     float(fs.service_features[i, SvcF.LATENCY]), 3)},
                "Profile this service's slow spans; check its downstream "
                "dependencies for queuing",
            )

        # -- slow operations ---------------------------------------------------
        slow = traces.get("slow_ops") or []
        if slow:
            r.add_finding(
                f"Namespace/{snap.namespace}",
                f"{len(slow)} operation(s) exceed {SLOW_MS:.0f} ms",
                "medium",
                {"slow_operations": slow[:10]},
                "Optimize or parallelize the listed operations",
            )

        # -- dependency fan-in: services many others depend on ----------------
        deps = traces.get("dependencies") or {}
        fan_in: dict = {}
        for src_name, dst_names in deps.items():
            for d in dst_names or []:
                fan_in[d] = fan_in.get(d, 0) + 1
        for name, count in sorted(fan_in.items()):
            if count >= 3:
                r.add_finding(
                    f"Service/{name}",
                    f"{count} services depend on this one (high fan-in)",
                    "info",
                    {"dependents": count},
                    "Treat this service as critical-path: prioritize its "
                    "alerts and capacity",
                )

        # viz payload: per-service latency percentiles + the dependency map
        # (reference: components/visualization.py latency charts per service
        # and the :516-646 service-dependency digraph)
        if lat:
            r.data["latency"] = {
                name: stats for name, stats in sorted(lat.items())
                if isinstance(stats, dict)
            }
        deps_map = traces.get("dependencies") or {}
        if deps_map:
            r.data["dependencies"] = {
                src: sorted(dsts) for src, dsts in sorted(deps_map.items())
                if dsts
            }

        summarize(r, "trace")
        return r
