"""Logs agent: 13-class error-pattern scan + container-state classification.

Parity with the reference's log agent (reference: agents/logs_agent.py —
pattern table :20-34, per-container scan :146-149, severity map :416-437,
recommendation table :451-477, container-status / pod-condition / init /
no-logs checks :183-414).  The scan itself already ran once inside the
feature extractor (counts live in the packed pod array); this agent reads
those counts as a vectorized prefilter and only re-touches the raw text of
pods that actually hit, to pull example lines for evidence.
"""

from __future__ import annotations

import numpy as np

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.features.logscan import (
    LOG_PATTERN_NAMES,
    LOG_PATTERNS,
    pattern_recommendation,
    pattern_severity,
)
from rca_tpu.features.schema import PodF

MAX_EXAMPLE_LINES = 3


def _example_lines(logs_by_container: dict, pattern_name: str) -> list:
    pat = LOG_PATTERNS[pattern_name]
    out = []
    for cname, text in logs_by_container.items():
        if not text:
            continue
        for line in text.splitlines():
            if pat.search(line):
                out.append({"container": cname, "line": line.strip()[:300]})
                if len(out) >= MAX_EXAMPLE_LINES:
                    return out
    return out


class LogsAgent(Agent):
    agent_type = "logs"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        fs = ctx.features
        snap = ctx.snapshot
        pf = fs.pod_features

        log_block = pf[:, PodF.LOG0 : PodF.LOG0 + len(LOG_PATTERN_NAMES)]
        hit_pods = np.nonzero(log_block.sum(axis=1) > 0)[0]
        r.add_step(
            f"Log-pattern counts for {fs.num_pods} pods read from the packed "
            f"feature array; {len(hit_pods)} pod(s) show error-class hits.",
            "Only hitting pods' raw logs are re-read for example lines.",
        )

        for i in hit_pods.tolist():
            pod_name = fs.pod_names[i]
            logs = snap.logs.get(pod_name, {})
            for j in np.nonzero(log_block[i] > 0)[0].tolist():
                name = LOG_PATTERN_NAMES[j]
                count = int(log_block[i, j])
                r.add_finding(
                    f"Pod/{pod_name}",
                    f"log pattern '{name}' matched {count} time(s)",
                    pattern_severity(name),
                    {
                        "pattern": name,
                        "count": count,
                        "examples": _example_lines(logs, name),
                    },
                    pattern_recommendation(name),
                )

        # -- container state classification (from packed flags) --------------
        flag_rules = [
            (PodF.WAIT_CRASHLOOP, "container in CrashLoopBackOff", "high",
             "Inspect the previous container logs for the crash cause"),
            (PodF.WAIT_IMAGEPULL, "container cannot pull its image", "high",
             "Verify the image name/tag, registry access, and pull secrets"),
            (PodF.WAIT_CONFIG, "container blocked on missing config "
             "(CreateContainerConfigError)", "high",
             "Create the referenced ConfigMap/Secret or fix the key names"),
            (PodF.INIT_FAILED, "init container failing", "high",
             "Fix the init container — the main containers will never start"),
            (PodF.TERM_OOM, "container OOM-killed", "high",
             "Raise the memory limit or reduce the container's footprint"),
        ]
        for channel, issue, sev, rec in flag_rules:
            for i in np.nonzero(pf[:, channel] > 0)[0].tolist():
                pod = snap.pod_by_name(fs.pod_names[i]) or {}
                statuses = pod.get("status", {}).get("containerStatuses", [])
                r.add_finding(
                    f"Pod/{fs.pod_names[i]}", issue, sev,
                    {"containerStatuses": statuses},
                    rec,
                )

        # restart pressure without a waiting reason (flapping but Running now)
        flapping = (pf[:, PodF.RESTARTS] >= 3) & (pf[:, PodF.WAIT_CRASHLOOP] == 0)
        for i in np.nonzero(flapping)[0].tolist():
            r.add_finding(
                f"Pod/{fs.pod_names[i]}",
                f"container restarted {int(pf[i, PodF.RESTARTS])} times",
                "medium",
                {"restart_count": int(pf[i, PodF.RESTARTS])},
                "Check previous-instance logs; the container is flapping",
            )

        # running pods that produced no logs at all
        for i in np.nonzero(pf[:, PodF.NO_LOGS] > 0)[0].tolist():
            r.add_finding(
                f"Pod/{fs.pod_names[i]}",
                "running pod produced no log output",
                "low",
                {},
                "Confirm the application logs to stdout/stderr; silent "
                "containers hide failures",
            )

        summarize(r, "log")
        return r
