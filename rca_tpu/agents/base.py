"""Agent contract + shared analysis context.

Parity with the reference's two agent families (reference:
agents/base_agent.py:18-52 ``analyze() -> {agent_type, findings,
reasoning_steps}``; agents/mcp_agent.py:33-69 ``analyze(context) ->
{findings, reasoning_steps}``) with two deliberate changes:

- agents are **stateless**: ``analyze`` returns a fresh :class:`AgentResult`
  instead of mutating ``self.findings`` (the reference accumulated state
  across calls, reference: agents/base_agent.py:28-31 cleared lists by hand);
- agents share one :class:`AnalysisContext` so the snapshot is captured once
  and the packed feature arrays / typed graph are computed once, not
  re-fetched per agent (reference re-fetched per runner, reference:
  agents/mcp_coordinator.py:322-620).
"""

from __future__ import annotations

import dataclasses
from functools import cached_property
from typing import Any, Dict, List, Optional

from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.findings import make_finding, make_reasoning_step


@dataclasses.dataclass
class AnalysisContext:
    """One snapshot + lazily-computed derived arrays, shared by all agents."""

    snapshot: ClusterSnapshot

    @cached_property
    def features(self):
        from rca_tpu.features.extract import extract_features

        return extract_features(self.snapshot)

    @cached_property
    def graph(self):
        from rca_tpu.graph.build import build_typed_graph

        return build_typed_graph(self.snapshot)

    @cached_property
    def dep_edges(self):
        from rca_tpu.graph.build import service_dependency_edges

        return service_dependency_edges(self.snapshot, self.features, self.graph)

    @classmethod
    def capture(cls, client, namespace: str, **kw) -> "AnalysisContext":
        return cls(ClusterSnapshot.capture(client, namespace, **kw))


@dataclasses.dataclass
class AgentResult:
    agent_type: str
    findings: List[dict] = dataclasses.field(default_factory=list)
    reasoning_steps: List[dict] = dataclasses.field(default_factory=list)
    summary: str = ""
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # findings are "as of the snapshot": one timestamp for the whole
    # analysis, taken from ClusterSnapshot.captured_at.  Per-finding
    # wall-clock stamps made two pipeline runs over the SAME world state
    # byte-differ whenever they straddled a second boundary — the ~1/16
    # parity-gate flake of round 2 (frozen mock time now makes the gate
    # deterministic; live captures get one consistent capture stamp).
    as_of: Optional[str] = None

    def add_finding(
        self,
        component: str,
        issue: str,
        severity: str,
        evidence: Any,
        recommendation: str,
        **extra: Any,
    ) -> dict:
        extra.setdefault("timestamp", self.as_of)
        f = make_finding(component, issue, severity, evidence, recommendation, **extra)
        self.findings.append(f)
        return f

    def add_step(self, observation: str, conclusion: str) -> dict:
        s = make_reasoning_step(observation, conclusion, timestamp=self.as_of)
        self.reasoning_steps.append(s)
        return s

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "agent_type": self.agent_type,
            "findings": self.findings,
            "reasoning_steps": self.reasoning_steps,
            "summary": self.summary,
        }
        if self.data:
            out["data"] = self.data
        return out


class Agent:
    """Base class for the deterministic signal agents."""

    agent_type: str = "base"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        raise NotImplementedError

    def analyze_snapshot(self, snapshot: ClusterSnapshot) -> AgentResult:
        return self.analyze(AnalysisContext(snapshot))


def pod_component(name: str) -> str:
    return f"Pod/{name}"


def summarize(result: AgentResult, what: str) -> None:
    """Fill ``result.summary`` with a one-line severity rollup."""
    if not result.findings:
        result.summary = f"No {what} issues detected."
        return
    counts: Dict[str, int] = {}
    for f in result.findings:
        counts[f["severity"]] = counts.get(f["severity"], 0) + 1
    parts = ", ".join(
        f"{counts[s]} {s}"
        for s in ("critical", "high", "medium", "low", "info")
        if s in counts
    )
    result.summary = f"{len(result.findings)} {what} finding(s): {parts}."
