"""LLM agent family: tool-driven evidence gathering behind the Agent API.

The reference's MCP agents (reference: agents/mcp_agent.py:33-69) sent one
context blob to the LLM, declared tools that were never invoked, and parsed
findings out of ``Issue:/Component:/Severity:`` markdown headers
(reference: agents/mcp_agent.py:170-251).  This family:

- runs a REAL tool loop (rca_tpu.llm.client.LLMClient.analyze) against the
  typed cluster client, so evidence in the answer is evidence that was
  actually fetched;
- requests findings as structured JSON instead of header-parsing;
- degrades deterministically: with the offline provider (or on any LLM
  failure) it falls back to the deterministic rule agent of the same signal,
  so `analyze` always returns findings (reference behavior on failure was an
  empty findings list swallowed by try/except, mcp_agent.py:60-69).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.findings import SEVERITY_ORDER
from rca_tpu.llm.client import LLMClient
from rca_tpu.llm.tools import ToolSpec, cluster_toolsets

_SEVERITY_GUIDE = (
    " Severity scale: info, low, medium, high, critical. Be specific: name "
    "components, cite the evidence you fetched."
)

# Per-signal system prompts (reference declares these per agent class:
# agents/mcp_metrics_agent.py / mcp_logs_agent.py / mcp_events_agent.py /
# mcp_topology_agent.py / mcp_traces_agent.py, each _get_system_prompt; the
# resources signal maps to resource_analyzer.py's sweep).  Unlike the
# reference, these prompts instruct the model to USE the tools, because our
# loop really executes them.
_SIGNAL_PROMPTS: Dict[str, str] = {
    "metrics": (
        "You are a Kubernetes metrics analyst. Use the tools to fetch pod "
        "and node CPU/memory usage, HPA state, and resource quotas. Flag "
        "utilization above 80% (above 90% is high severity), missing "
        "requests/limits, HPAs pinned at max or with desired > current "
        "replicas, and node pressure."
    ),
    "logs": (
        "You are a Kubernetes log analyst. Use the tools to pull logs from "
        "suspicious pods (crash-looping, restarting, failed) and search for "
        "error patterns: OOM kills, connection refused, permission denied, "
        "timeouts, crash loops, API errors, volume mounts, image pulls, DNS "
        "failures, auth errors, config errors, 5xx, exceptions."
    ),
    "events": (
        "You are a Kubernetes events analyst. Use the tools to fetch "
        "namespace and per-resource events. Group events by involved "
        "object; flag scheduling failures, volume problems, frequently "
        "repeating warnings, control-plane component errors, and node "
        "condition problems (NotReady, MemoryPressure, DiskPressure)."
    ),
    "topology": (
        "You are a Kubernetes topology analyst. Use the tools to map "
        "services, endpoints, deployments, ingresses, and network "
        "policies. Flag services whose selectors match no ready pods, "
        "ingresses routing to missing backends, dependency cycles, "
        "single points of failure (high-fanin services with replicas < 2), "
        "and over-permissive or missing network policies."
    ),
    "traces": (
        "You are a distributed-tracing analyst. Use the tools to fetch "
        "per-service latency percentiles, error rates, the service "
        "dependency map, and slow operations. Flag services with elevated "
        "p99 latency or error rate, and trace the failure to the most "
        "upstream unhealthy dependency."
    ),
    "resources": (
        "You are a Kubernetes resource-health analyst. Use the tools to "
        "sweep pods, deployments, and events in the namespace. Flag "
        "crash-looping / image-pull-failed / pending / evicted pods, "
        "deployments with ready < desired replicas, selector mismatches, "
        "and correlate warning events with the affected objects."
    ),
}

_SYSTEM_TEMPLATE = (
    "{prompt} Investigate the {signal} signal for the namespace described "
    "by the user, calling tools to gather real evidence before concluding."
    + _SEVERITY_GUIDE
)

_FINDINGS_PROMPT = (
    "Convert this {signal} analysis into JSON: "
    '{{"findings": [{{"component": "Kind/name", "issue": "...", '
    '"severity": "info|low|medium|high|critical", "evidence": "...", '
    '"recommendation": "..."}}], "summary": "one line"}}.\n'
    "Analysis:\n{analysis}"
)


class LLMAgent(Agent):
    """One LLM-driven signal agent with a deterministic fallback twin."""

    def __init__(
        self,
        agent_type: str,
        client: LLMClient,
        tools: Optional[List[ToolSpec]] = None,
        fallback: Optional[Agent] = None,
        cluster_client=None,
        tools_namespace: Optional[str] = None,
    ):
        self.agent_type = agent_type
        self.client = client
        self.tools = tools or []
        self.fallback = fallback
        self.cluster_client = cluster_client
        self._tools_ns = tools_namespace if self.tools else None
        self._toolset_cache: Dict[str, List[ToolSpec]] = {}

    # tools are bound per-namespace at ANALYZE time (from the snapshot's
    # namespace) unless preset for that same namespace — binding at
    # construction time with an unknown namespace would aim every tool at
    # the wrong place.
    def _tools_for(self, ctx: AnalysisContext, client) -> List[ToolSpec]:
        ns = ctx.snapshot.namespace
        # preset tools are trusted only for the namespace they were bound
        # to (or when no client is available to rebind them)
        if self.tools and (self._tools_ns == ns or client is None):
            return self.tools
        if client is None:
            return []
        if client is not self.cluster_client:
            # ad-hoc client for this one call: build fresh, don't retain it
            return cluster_toolsets(client, ns).get(self.agent_type, [])
        if ns not in self._toolset_cache:
            self._toolset_cache[ns] = cluster_toolsets(client, ns).get(
                self.agent_type, []
            )
        return self._toolset_cache[ns]

    def analyze(
        self, ctx: AnalysisContext, cluster_client=None
    ) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        tools = self._tools_for(ctx, cluster_client or self.cluster_client)
        context = self._context_blob(ctx)
        system_prompt = _SYSTEM_TEMPLATE.format(
            prompt=_SIGNAL_PROMPTS.get(
                self.agent_type,
                f"You are a Kubernetes {self.agent_type} analyst.",
            ),
            signal=self.agent_type,
        )
        try:
            out = self.client.analyze(
                context, tools=tools, system_prompt=system_prompt,
            )
        except Exception as e:
            return self._fall_back(ctx, r, f"LLM analyze failed: {e}")
        r.reasoning_steps.extend(out.get("reasoning_steps", []))
        analysis = out.get("final_analysis", "")

        try:
            structured = self.client.generate_structured_output(
                _FINDINGS_PROMPT.format(
                    signal=self.agent_type, analysis=analysis[:6000]
                )
            )
        except Exception as e:
            return self._fall_back(
                ctx, r, f"structured output failed: {e}", narrative=analysis,
            )
        findings = (structured or {}).get("findings")
        if isinstance(findings, list) and findings:
            for f in findings:
                if not isinstance(f, dict):
                    continue
                sev = str(f.get("severity", "info")).lower()
                r.add_finding(
                    str(f.get("component", "unknown")),
                    str(f.get("issue", "")),
                    sev if sev in SEVERITY_ORDER else "info",
                    f.get("evidence", ""),
                    str(f.get("recommendation", "")),
                    source="llm",
                )
            r.summary = str((structured or {}).get("summary", "")) or analysis[:200]
            r.data["final_analysis"] = analysis
            return r
        # no structured findings (offline provider or parse failure):
        # deterministic twin provides findings, LLM text kept as narrative
        return self._fall_back(
            ctx, r, "no structured findings from provider",
            narrative=analysis,
        )

    # ------------------------------------------------------------------
    def _fall_back(
        self,
        ctx: AnalysisContext,
        r: AgentResult,
        reason: str,
        narrative: str = "",
    ) -> AgentResult:
        r.add_step(
            f"LLM path degraded ({reason}); using deterministic "
            f"{self.agent_type} rules.",
            "Findings below come from the rule agent.",
        )
        if narrative:
            r.data["final_analysis"] = narrative
        if self.fallback is not None:
            det = self.fallback.analyze(ctx)
            r.findings.extend(det.findings)
            r.reasoning_steps.extend(det.reasoning_steps)
            r.data.update(det.data)
        summarize(r, self.agent_type)
        return r

    def _context_blob(self, ctx: AnalysisContext) -> str:
        """Compact cluster context for the first LLM turn (counts, not dumps —
        the tools exist to fetch detail)."""
        snap = ctx.snapshot
        fs = ctx.features
        phases: Dict[str, int] = {}
        for p in snap.pods:
            ph = p.get("status", {}).get("phase", "Unknown")
            phases[ph] = phases.get(ph, 0) + 1
        blob: Dict[str, Any] = {
            "namespace": snap.namespace,
            "captured_at": snap.captured_at,
            "pods_by_phase": phases,
            "services": fs.service_names,
            "warning_events": sum(
                1 for e in snap.events if e.get("type") != "Normal"
            ),
            "task": (
                f"Analyze the {self.agent_type} signal for this namespace "
                "and identify problems with evidence."
            ),
        }
        return json.dumps(blob)


def make_llm_agents(
    client: LLMClient, cluster_client=None, namespace: str = ""
) -> Dict[str, LLMAgent]:
    """LLM agent per signal, each with its deterministic twin as fallback.

    When ``namespace`` is given, tools are pre-bound to it; otherwise each
    agent binds its toolset at analyze time from the snapshot's namespace
    (so one agent set serves every namespace the coordinator analyzes).
    """
    from rca_tpu.agents import make_agents

    det = make_agents()
    toolsets = (
        cluster_toolsets(cluster_client, namespace)
        if (cluster_client and namespace) else {}
    )
    return {
        name: LLMAgent(
            name, client,
            tools=toolsets.get(name),
            fallback=det[name],
            cluster_client=cluster_client,
            tools_namespace=namespace or None,
        )
        for name in det
    }
