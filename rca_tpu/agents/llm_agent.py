"""LLM agent family: tool-driven evidence gathering behind the Agent API.

The reference's MCP agents (reference: agents/mcp_agent.py:33-69) sent one
context blob to the LLM, declared tools that were never invoked, and parsed
findings out of ``Issue:/Component:/Severity:`` markdown headers
(reference: agents/mcp_agent.py:170-251).  This family:

- runs a REAL tool loop (rca_tpu.llm.toolloop) against the typed cluster
  client, so evidence in the answer is evidence that was actually fetched;
- requests findings as structured JSON instead of header-parsing;
- degrades deterministically: with the offline provider (or on any LLM
  failure) it falls back to the deterministic rule agent of the same signal,
  so `analyze` always returns findings (reference behavior on failure was an
  empty findings list swallowed by try/except, mcp_agent.py:60-69).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.findings import SEVERITY_ORDER
from rca_tpu.llm.client import LLMClient
from rca_tpu.llm.tools import ToolSpec, cluster_toolsets

_SEVERITY_GUIDE = (
    " Severity scale: info, low, medium, high, critical. Be specific: name "
    "components, cite the evidence you fetched."
)

_FINDINGS_PROMPT = (
    "Convert this {signal} analysis into JSON: "
    '{{"findings": [{{"component": "Kind/name", "issue": "...", '
    '"severity": "info|low|medium|high|critical", "evidence": "...", '
    '"recommendation": "..."}}], "summary": "one line"}}.\n'
    "Analysis:\n{analysis}"
)


class LLMAgent(Agent):
    """One LLM-driven signal agent with a deterministic fallback twin."""

    def __init__(
        self,
        agent_type: str,
        client: LLMClient,
        tools: Optional[List[ToolSpec]] = None,
        fallback: Optional[Agent] = None,
    ):
        self.agent_type = agent_type
        self.client = client
        self.tools = tools or []
        self.fallback = fallback

    # tools are bound per-namespace at analyze time when not preset
    def _tools_for(self, ctx: AnalysisContext, client) -> List[ToolSpec]:
        if self.tools:
            return self.tools
        if client is None:
            return []
        return cluster_toolsets(client, ctx.snapshot.namespace).get(
            self.agent_type, []
        )

    def analyze(
        self, ctx: AnalysisContext, cluster_client=None
    ) -> AgentResult:
        r = AgentResult(self.agent_type)
        tools = self._tools_for(ctx, cluster_client)
        context = self._context_blob(ctx)
        try:
            out = self.client.analyze(
                context,
                tools=tools,
                system_prompt=_SYSTEM_TEMPLATE.format(signal=self.agent_type),
            )
        except Exception as e:
            return self._fall_back(ctx, r, f"LLM analyze failed: {e}")
        r.reasoning_steps.extend(out.get("reasoning_steps", []))
        analysis = out.get("final_analysis", "")

        structured = self.client.generate_structured_output(
            _FINDINGS_PROMPT.format(
                signal=self.agent_type, analysis=analysis[:6000]
            )
        )
        findings = (structured or {}).get("findings")
        if isinstance(findings, list) and findings:
            for f in findings:
                if not isinstance(f, dict):
                    continue
                sev = str(f.get("severity", "info")).lower()
                r.add_finding(
                    str(f.get("component", "unknown")),
                    str(f.get("issue", "")),
                    sev if sev in SEVERITY_ORDER else "info",
                    f.get("evidence", ""),
                    str(f.get("recommendation", "")),
                    source="llm",
                )
            r.summary = str((structured or {}).get("summary", "")) or analysis[:200]
            r.data["final_analysis"] = analysis
            return r
        # no structured findings (offline provider or parse failure):
        # deterministic twin provides findings, LLM text kept as narrative
        return self._fall_back(
            ctx, r, "no structured findings from provider",
            narrative=analysis,
        )

    # ------------------------------------------------------------------
    def _fall_back(
        self,
        ctx: AnalysisContext,
        r: AgentResult,
        reason: str,
        narrative: str = "",
    ) -> AgentResult:
        r.add_step(
            f"LLM path degraded ({reason}); using deterministic "
            f"{self.agent_type} rules.",
            "Findings below come from the rule agent.",
        )
        if narrative:
            r.data["final_analysis"] = narrative
        if self.fallback is not None:
            det = self.fallback.analyze(ctx)
            r.findings.extend(det.findings)
            r.reasoning_steps.extend(det.reasoning_steps)
            r.data.update(det.data)
        summarize(r, self.agent_type)
        return r

    def _context_blob(self, ctx: AnalysisContext) -> str:
        """Compact cluster context for the first LLM turn (counts, not dumps —
        the tools exist to fetch detail)."""
        snap = ctx.snapshot
        fs = ctx.features
        phases: Dict[str, int] = {}
        for p in snap.pods:
            ph = p.get("status", {}).get("phase", "Unknown")
            phases[ph] = phases.get(ph, 0) + 1
        blob: Dict[str, Any] = {
            "namespace": snap.namespace,
            "captured_at": snap.captured_at,
            "pods_by_phase": phases,
            "services": fs.service_names,
            "warning_events": sum(
                1 for e in snap.events if e.get("type") != "Normal"
            ),
            "task": (
                f"Analyze the {self.agent_type} signal for this namespace "
                "and identify problems with evidence."
            ),
        }
        return json.dumps(blob)


def make_llm_agents(
    client: LLMClient, cluster_client=None, namespace: str = ""
) -> Dict[str, LLMAgent]:
    """LLM agent per signal, each with its deterministic twin as fallback."""
    from rca_tpu.agents import make_agents

    det = make_agents()
    toolsets = (
        cluster_toolsets(cluster_client, namespace) if cluster_client else {}
    )
    return {
        name: LLMAgent(
            name, client,
            tools=toolsets.get(name),
            fallback=det[name],
        )
        for name in det
    }
