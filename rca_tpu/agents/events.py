"""Events agent: grouping, scheduling/volume classes, frequency, node health.

Parity with the reference's events agent (reference: agents/events_agent.py —
group by involvedObject :105, scheduling failures :169, volume failures :230,
frequent events count>5 / >20 :292-328, control-plane source components →
critical :330-376, node conditions NodeNotReady/MemoryPressure/DiskPressure/
NetworkUnavailable → critical with per-condition recommendations :377-446).
"""

from __future__ import annotations

from typing import Dict, List

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize

SCHEDULING_REASONS = {"FailedScheduling", "FailedPlacement", "Preempted"}
VOLUME_REASONS = {
    "FailedMount", "FailedAttachVolume", "FailedBinding", "VolumeFailedDelete",
    "ProvisioningFailed",
}
CONTROL_PLANE_COMPONENTS = {
    "kube-apiserver", "kube-controller-manager", "kube-scheduler", "etcd",
    "kube-proxy", "cloud-controller-manager",
}
NODE_CONDITION_RECS = {
    "MemoryPressure": "Free node memory: evict/rebalance pods or add nodes",
    "DiskPressure": "Reclaim node disk: prune images/logs or grow the volume",
    "PIDPressure": "Reduce process counts on the node or raise pid limits",
    "NetworkUnavailable": "Check CNI health and node network configuration",
    "Ready": "Investigate kubelet health and node connectivity",
}

FREQUENT, VERY_FREQUENT = 5, 20


def _obj_key(ev: dict) -> str:
    obj = ev.get("involvedObject", {}) or {}
    return f"{obj.get('kind', 'Unknown')}/{obj.get('name', 'unknown')}"


class EventsAgent(Agent):
    agent_type = "events"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        snap = ctx.snapshot
        warnings = [e for e in snap.events if e.get("type") != "Normal"]

        by_obj: Dict[str, List[dict]] = {}
        for ev in warnings:
            by_obj.setdefault(_obj_key(ev), []).append(ev)
        r.add_step(
            f"{len(warnings)} non-Normal events grouped into "
            f"{len(by_obj)} involved objects.",
            "Per-object classification follows.",
        )

        for key, evs in by_obj.items():
            reasons = {e.get("reason", "") for e in evs}
            messages = [e.get("message", "") for e in evs][:5]
            total = sum(int(e.get("count", 1) or 1) for e in evs)

            sched = reasons & SCHEDULING_REASONS
            if sched:
                r.add_finding(
                    key,
                    f"scheduling failures ({', '.join(sorted(sched))})",
                    "high",
                    {"reasons": sorted(sched), "messages": messages},
                    "Check node capacity, taints/tolerations, affinity rules, "
                    "and PVC binding — the pod cannot be placed",
                )
            vol = reasons & VOLUME_REASONS
            if vol:
                r.add_finding(
                    key,
                    f"volume failures ({', '.join(sorted(vol))})",
                    "high",
                    {"reasons": sorted(vol), "messages": messages},
                    "Verify the PVC, storage class, and attach/mount path",
                )
            if total > FREQUENT:
                r.add_finding(
                    key,
                    f"warning events recurring {total} times",
                    "high" if total > VERY_FREQUENT else "medium",
                    {"count": total, "reasons": sorted(reasons),
                     "messages": messages},
                    "A persistently recurring warning indicates an unresolved "
                    "failure loop — investigate the earliest occurrence",
                )
            cp = {
                (e.get("source", {}) or {}).get("component", "")
                for e in evs
            } & CONTROL_PLANE_COMPONENTS
            if cp:
                r.add_finding(
                    key,
                    f"control-plane component(s) {', '.join(sorted(cp))} "
                    "reporting warnings",
                    "critical",
                    {"components": sorted(cp), "messages": messages},
                    "Control-plane warnings affect the whole cluster — "
                    "triage these before workload-level symptoms",
                )

        # -- node conditions --------------------------------------------------
        for node in snap.nodes:
            name = node.get("metadata", {}).get("name", "")
            for cond in node.get("status", {}).get("conditions", []) or []:
                ctype = cond.get("type", "")
                status = cond.get("status", "")
                bad = (ctype == "Ready" and status != "True") or (
                    ctype != "Ready" and status == "True"
                )
                if ctype in NODE_CONDITION_RECS and bad:
                    label = "NotReady" if ctype == "Ready" else ctype
                    r.add_finding(
                        f"Node/{name}",
                        f"node condition {label}",
                        "critical",
                        {"condition": ctype, "status": status,
                         "message": cond.get("message", "")},
                        NODE_CONDITION_RECS[ctype],
                    )

        # viz payload: namespace-wide breakdowns by reason and by type
        # (reference: components/visualization.py event breakdown charts)
        reason_counts: Dict[str, int] = {}
        type_counts: Dict[str, int] = {}
        for ev in snap.events:
            n = int(ev.get("count", 1) or 1)
            reason = str(ev.get("reason", "") or "unknown")
            reason_counts[reason] = reason_counts.get(reason, 0) + n
            etype = str(ev.get("type", "") or "unknown")
            type_counts[etype] = type_counts.get(etype, 0) + n
        if reason_counts:
            r.data["reason_counts"] = reason_counts
            r.data["type_counts"] = type_counts

        summarize(r, "event")
        return r
