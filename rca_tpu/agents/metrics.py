"""Metrics agent: utilization thresholds, node pressure, limits audit, HPA.

Rule parity with the reference's metrics agent (reference:
agents/metrics_agent.py — pod CPU >80% flag / >90% high :88-104, memory same
:135-151, node pressure >80% :182-199, missing requests/limits audit
:234-261, HPA at-max / narrow-range / desired>current :302-322), but the
threshold scan runs vectorized over the packed pod-feature array instead of
one dict at a time.
"""

from __future__ import annotations

import numpy as np

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.features.schema import PodF

CPU_WARN, CPU_HIGH = 0.80, 0.90
MEM_WARN, MEM_HIGH = 0.80, 0.90
NODE_PRESSURE = 0.80


class MetricsAgent(Agent):
    agent_type = "metrics"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        fs = ctx.features
        snap = ctx.snapshot

        pf = fs.pod_features
        r.add_step(
            f"Scanned utilization for {fs.num_pods} pods and "
            f"{len(fs.node_names)} nodes from packed metric channels.",
            "Threshold comparison runs as one vector op per resource.",
        )

        # -- pod cpu/mem thresholds (vectorized prefilter, detail on hits) --
        for channel, warn, high, kind in (
            (PodF.CPU_PCT, CPU_WARN, CPU_HIGH, "CPU"),
            (PodF.MEM_PCT, MEM_WARN, MEM_HIGH, "memory"),
        ):
            vals = pf[:, channel]
            for i in np.nonzero(vals > warn)[0].tolist():
                pct = float(vals[i]) * 100.0
                sev = "high" if vals[i] > high else "medium"
                r.add_finding(
                    f"Pod/{fs.pod_names[i]}",
                    f"{kind} utilization at {pct:.0f}% of its limit",
                    sev,
                    {"usage_percentage": round(pct, 1), "resource": kind.lower()},
                    (
                        f"Raise the {kind.lower()} limit, scale the workload out, "
                        "or reduce the container's load"
                    ),
                )

        # -- node pressure ---------------------------------------------------
        for i, name in enumerate(fs.node_names):
            cpu, mem = float(fs.node_features[i, 0]), float(fs.node_features[i, 1])
            if max(cpu, mem) > NODE_PRESSURE:
                hot = "CPU" if cpu >= mem else "memory"
                pct = max(cpu, mem) * 100.0
                r.add_finding(
                    f"Node/{name}",
                    f"node under {hot} pressure ({pct:.0f}% used)",
                    "high" if max(cpu, mem) > 0.9 else "medium",
                    {"cpu_percentage": round(cpu * 100, 1),
                     "memory_percentage": round(mem * 100, 1)},
                    "Add capacity or rebalance workloads off the pressured node",
                )

        # -- missing requests/limits audit ----------------------------------
        missing = []
        for pod in snap.pods:
            name = pod.get("metadata", {}).get("name", "")
            for c in pod.get("spec", {}).get("containers", []) or []:
                res = c.get("resources") or {}
                lacks = [k for k in ("requests", "limits") if not res.get(k)]
                if lacks:
                    missing.append(
                        {"pod": name, "container": c.get("name", ""),
                         "missing": lacks}
                    )
        if missing:
            r.add_finding(
                "Namespace/" + snap.namespace,
                f"{len(missing)} container(s) run without resource "
                "requests and/or limits",
                "low",
                missing[:20],
                "Set resource requests and limits so the scheduler and "
                "evictions behave predictably",
            )

        # -- HPA posture -----------------------------------------------------
        for hpa in snap.hpas:
            name = hpa.get("metadata", {}).get("name", "")
            spec = hpa.get("spec", {}) or {}
            status = hpa.get("status", {}) or {}
            mn = int(spec.get("minReplicas", 1) or 1)
            mx = int(spec.get("maxReplicas", 1) or 1)
            cur = int(status.get("currentReplicas", 0) or 0)
            want = int(status.get("desiredReplicas", 0) or 0)
            if cur >= mx > 0:
                r.add_finding(
                    f"HPA/{name}",
                    f"autoscaler pinned at its max of {mx} replicas",
                    "medium",
                    {"current": cur, "max": mx},
                    "Raise maxReplicas or reduce per-replica load; the "
                    "autoscaler has no headroom left",
                )
            elif want > cur:
                r.add_finding(
                    f"HPA/{name}",
                    f"autoscaler wants {want} replicas but only {cur} are up",
                    "medium",
                    {"desired": want, "current": cur},
                    "Check scheduling capacity and pod health — scale-up "
                    "is not completing",
                )
            if mx - mn < 2 and mn > 1:
                r.add_finding(
                    f"HPA/{name}",
                    f"autoscaling range [{mn}, {mx}] is too narrow to absorb load swings",
                    "low",
                    {"min": mn, "max": mx},
                    "Widen the min/max replica range so the HPA can react",
                )

        summarize(r, "metrics")
        return r
