"""Topology agent: structural analyses over the typed resource graph.

Parity with the reference's topology agent (reference: agents/topology_agent.py
— graph build :94-260, cycles :268, longest chain :294-305, SPOF via
betweenness>0.5 with replicas<2 :329-346, isolated nodes :363, network-policy
permissiveness/coverage :403-499, ingress TLS / broken backends :501-590,
missing ConfigMap/Secret refs :592-655, service→pod mapping :407-481, graph
export :657-693) — but on the COO array representation with linear-time
algorithms (rca_tpu.graph.analysis) instead of networkx all-pairs paths.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.cluster.labels import selector_matches
from rca_tpu.graph.analysis import (
    betweenness_centrality,
    find_cycles,
    isolated_nodes,
    longest_dependency_chain,
)

SPOF_CENTRALITY = 0.5
LONG_CHAIN = 4


class TopologyAgent(Agent):
    agent_type = "topology"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        snap = ctx.snapshot
        fs = ctx.features
        graph = ctx.graph
        src, dst = ctx.dep_edges
        names = fs.service_names
        n = fs.num_services
        r.add_step(
            f"Typed graph: {graph.num_nodes} nodes / {graph.num_edges} edges; "
            f"service dependency graph: {n} services / {len(src)} edges.",
            "Structural analyses run on COO arrays in linear time.",
        )
        r.data["graph"] = graph.to_dict()

        # -- cycles ----------------------------------------------------------
        for cyc in find_cycles(n, src, dst):
            chain = " -> ".join(names[i] for i in cyc)
            r.add_finding(
                f"Service/{names[cyc[0]]}",
                f"circular dependency: {chain}",
                "high",
                {"cycle": [names[i] for i in cyc]},
                "Break the cycle (extract the shared piece or invert one "
                "dependency); circular services cannot start or fail cleanly",
            )

        # -- longest dependency chain ---------------------------------------
        chain = longest_dependency_chain(n, src, dst)
        if len(chain) >= LONG_CHAIN:
            r.add_finding(
                f"Service/{names[chain[0]]}",
                f"dependency chain of depth {len(chain)}: "
                + " -> ".join(names[i] for i in chain),
                "medium",
                {"chain": [names[i] for i in chain]},
                "Deep chains multiply failure probability and latency — "
                "consider collapsing or parallelizing hops",
            )
        elif chain:
            r.add_step(
                f"Longest dependency chain has depth {len(chain)}.",
                "Below the reporting threshold.",
            )

        # -- SPOF: high centrality + low replication -------------------------
        replicas = self._service_replicas(snap, names)
        if len(src):
            bc = betweenness_centrality(n, src, dst)
            for i in np.nonzero(bc > SPOF_CENTRALITY)[0].tolist():
                if replicas.get(names[i], 0) < 2:
                    r.add_finding(
                        f"Service/{names[i]}",
                        "single point of failure: high graph centrality "
                        f"({bc[i]:.2f}) with {replicas.get(names[i], 0)} "
                        "replica(s)",
                        "high",
                        {"centrality": round(float(bc[i]), 3),
                         "replicas": replicas.get(names[i], 0)},
                        "Run at least 2 replicas of this service; many "
                        "dependency paths flow through it",
                    )

        # -- isolated services ----------------------------------------------
        if len(src):
            for i in isolated_nodes(n, src, dst).tolist():
                r.add_finding(
                    f"Service/{names[i]}",
                    "service participates in no dependency edges",
                    "low",
                    {},
                    "Confirm the service is still used; unused services add "
                    "surface without value",
                )

        # -- service → pod mapping -------------------------------------------
        self._service_pod_mapping(r, ctx)

        # -- network policies ------------------------------------------------
        self._network_policies(r, ctx)

        # -- ingress ---------------------------------------------------------
        for ing in snap.ingresses:
            iname = ing.get("metadata", {}).get("name", "")
            if not (ing.get("spec") or {}).get("tls"):
                r.add_finding(
                    f"Ingress/{iname}",
                    "ingress terminates no TLS",
                    "low",
                    {},
                    "Add a TLS section unless plaintext exposure is intended",
                )
        for miss in graph.missing_refs:
            if miss["kind"] == "ingress_backend":
                r.add_finding(
                    f"Ingress/{miss['from']}",
                    f"ingress routes to nonexistent service "
                    f"'{miss['missing']}'",
                    "high",
                    miss,
                    "Create the backend service or fix the ingress rule",
                )
            else:  # missing_configmap / missing_secret
                kind = miss["kind"].replace("missing_", "")
                r.add_finding(
                    f"Workload/{miss['from']}",
                    f"references a {kind} '{miss['missing']}' that does not "
                    "exist",
                    "high",
                    miss,
                    f"Create the {kind} or remove the dangling reference — "
                    "pods will fail to start or run misconfigured",
                )

        summarize(r, "topology")
        return r

    # ------------------------------------------------------------------
    @staticmethod
    def _service_replicas(snap, names: List[str]) -> Dict[str, int]:
        """Ready-replica count of each service's backing workload(s).
        One pass over workloads via the inverted selector index."""
        from rca_tpu.cluster.labels import SelectorIndex

        svc_names = [
            s.get("metadata", {}).get("name", "") for s in snap.services
        ]
        index = SelectorIndex(
            [(s.get("spec") or {}).get("selector") or {}
             for s in snap.services]
        )
        out: Dict[str, int] = {
            name: 0
            for s, name in zip(snap.services, svc_names)
            if (s.get("spec") or {}).get("selector")
        }
        workloads = (
            list(snap.deployments) + list(snap.statefulsets) + list(snap.daemonsets)
        )
        for w in workloads:
            tlabels = (
                ((w.get("spec") or {}).get("template") or {})
                .get("metadata", {})
                .get("labels", {})
                or {}
            )
            st = w.get("status", {}) or {}
            ready = int(st.get("readyReplicas", st.get("numberReady", 0)) or 0)
            for j in index.matches(tlabels):
                out[svc_names[j]] = out.get(svc_names[j], 0) + ready
        return out

    @staticmethod
    def _service_pod_mapping(r: AgentResult, ctx: AnalysisContext) -> None:
        """Selector matching + ready/unready split (reference:
        agents/topology_agent.py:407-481)."""
        fs = ctx.features
        snap = ctx.snapshot
        pf = fs.pod_features
        from rca_tpu.features.schema import PodF

        ready = (pf[:, PodF.PHASE_RUNNING] > 0) & (pf[:, PodF.NOT_READY] == 0)
        mapping = {}
        for j, sname in enumerate(fs.service_names):
            sel = (snap.services[j].get("spec") or {}).get("selector") or {}
            if not sel:
                continue
            members = fs.service_members(j)
            n_ready = int(ready[members].sum()) if len(members) else 0
            mapping[sname] = {
                "pods": [fs.pod_names[i] for i in members.tolist()],
                "ready": n_ready,
                "unready": int(len(members) - n_ready),
            }
            if len(members) == 0:
                r.add_finding(
                    f"Service/{sname}",
                    "selector matches no pods",
                    "high",
                    {"selector": sel},
                    "Fix the selector or deploy the backing workload; the "
                    "service has nothing to route to",
                )
            elif n_ready == 0:
                r.add_finding(
                    f"Service/{sname}",
                    f"all {len(members)} backing pod(s) are unready",
                    "high",
                    mapping[sname],
                    "Traffic to this service is failing — investigate the "
                    "backing pods",
                )
        r.data["service_pod_mapping"] = mapping

    @staticmethod
    def _network_policies(r: AgentResult, ctx: AnalysisContext) -> None:
        """Permissiveness, coverage, and dead selectors (reference:
        agents/topology_agent.py:403-499)."""
        snap = ctx.snapshot
        fs = ctx.features
        pod_labels = [
            p.get("metadata", {}).get("labels", {}) or {} for p in snap.pods
        ]
        covered = np.zeros(len(pod_labels), dtype=bool)
        for pol in snap.network_policies:
            pname = pol.get("metadata", {}).get("name", "")
            spec = pol.get("spec", {}) or {}
            sel = (spec.get("podSelector") or {}).get("matchLabels", {}) or {}
            if not sel and not (spec.get("podSelector") or {}).get(
                "matchExpressions"
            ):
                covered[:] = True
            else:
                for i, labels in enumerate(pod_labels):
                    if selector_matches(sel, labels):
                        covered[i] = True
            if not spec.get("ingress") and not spec.get("egress"):
                r.add_finding(
                    f"NetworkPolicy/{pname}",
                    "policy defines no ingress or egress rules "
                    "(default-deny for selected pods)",
                    "medium",
                    {"podSelector": sel},
                    "Confirm default-deny is intended; selected pods accept "
                    "no traffic",
                )
            # 'from' selectors that match no pod in the namespace
            for rule in spec.get("ingress", []) or []:
                for frm in rule.get("from", []) or []:
                    fsel = (frm.get("podSelector") or {}).get(
                        "matchLabels", {}
                    ) or {}
                    if fsel and not any(
                        selector_matches(fsel, labels) for labels in pod_labels
                    ):
                        r.add_finding(
                            f"NetworkPolicy/{pname}",
                            f"ingress 'from' selector {fsel} matches no pods",
                            "medium",
                            {"from_selector": fsel},
                            "The allow rule is dead — traffic it was meant to "
                            "admit is being dropped; fix the selector labels",
                        )
        if snap.network_policies and not covered.all():
            uncovered = [
                fs.pod_names[i] for i in np.nonzero(~covered)[0].tolist()
            ][:10]
            r.add_finding(
                f"Namespace/{snap.namespace}",
                f"{int((~covered).sum())} pod(s) not covered by any "
                "network policy",
                "low",
                {"examples": uncovered},
                "Extend policy coverage for a consistent security posture",
            )
