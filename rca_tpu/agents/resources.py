"""Resource agent: full-namespace sweep (the reference's ResourceAnalyzer).

Parity with reference: agents/resource_analyzer.py — service selector /
unhealthy-target checks :96-148, deployment ready<desired + selector drift
:150-196, statefulset/daemonset shortfalls :198-262, pod status bucketing
into groups with a per-group analyzer :275-351, :382-712, event correlation
attaching related events to findings or minting new ones :714-833,
``_is_pod_healthy`` :856-895.

The pod bucketing here is a set of boolean masks over the packed pod-feature
array — one vector op per bucket instead of a 12-way Python if/elif chain
per pod.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext, summarize
from rca_tpu.cluster.labels import selector_matches
from rca_tpu.features.schema import PodF

# event keyword classes for correlation (reference:
# agents/resource_analyzer.py:714-833 keyword-class matching)
EVENT_CLASSES = {
    "crash": ("BackOff", "Unhealthy", "Killing", "Failed"),
    "scheduling": ("FailedScheduling", "Preempted"),
    "volume": ("FailedMount", "FailedAttachVolume", "FailedBinding"),
    "image": ("Failed", "ErrImagePull", "BackOff", "InspectFailed"),
    "network": ("NetworkNotReady", "DNSConfigForming"),
    "resource": ("OOMKilling", "Evicted", "FailedCreate"),
}


class ResourceAgent(Agent):
    agent_type = "resources"

    def analyze(self, ctx: AnalysisContext) -> AgentResult:
        r = AgentResult(self.agent_type, as_of=ctx.snapshot.captured_at)
        snap = ctx.snapshot
        fs = ctx.features
        r.add_step(
            f"Swept namespace '{snap.namespace}': {len(snap.pods)} pods, "
            f"{len(snap.deployments)} deployments, {len(snap.services)} "
            f"services, {len(snap.events)} events.",
            "Pod buckets computed as vector masks over packed features.",
        )

        self._services(r, ctx)
        self._workloads(r, snap)
        self._pod_buckets(r, ctx)
        self._correlate_events(r, ctx)

        summarize(r, "resource")
        return r

    # -- services ----------------------------------------------------------
    @staticmethod
    def _services(r: AgentResult, ctx: AnalysisContext) -> None:
        fs = ctx.features
        snap = ctx.snapshot
        pf = fs.pod_features
        healthy = (
            (pf[:, PodF.PHASE_RUNNING] > 0)
            & (pf[:, PodF.NOT_READY] == 0)
            & (pf[:, PodF.WAIT_CRASHLOOP] == 0)
        )
        for j, svc in enumerate(snap.services):
            sname = svc.get("metadata", {}).get("name", "")
            sel = (svc.get("spec") or {}).get("selector") or {}
            if not sel:
                continue
            members = fs.service_members(j)
            if len(members) == 0:
                r.add_finding(
                    f"Service/{sname}",
                    "service selector matches no pods",
                    "high",
                    {"selector": sel},
                    "Deploy the backing workload or fix the selector labels",
                )
            elif not healthy[members].any():
                r.add_finding(
                    f"Service/{sname}",
                    "every pod behind this service is unhealthy",
                    "high",
                    {"pods": [fs.pod_names[i] for i in members.tolist()]},
                    "The service is effectively down — fix the backing pods",
                )

    # -- workloads ----------------------------------------------------------
    @staticmethod
    def _workloads(r: AgentResult, snap) -> None:
        for dep in snap.deployments:
            name = dep.get("metadata", {}).get("name", "")
            spec = dep.get("spec", {}) or {}
            status = dep.get("status", {}) or {}
            want = int(spec.get("replicas", 1) or 0)
            ready = int(status.get("readyReplicas", 0) or 0)
            if ready < want:
                r.add_finding(
                    f"Deployment/{name}",
                    f"{ready}/{want} replicas ready",
                    "high" if ready == 0 else "medium",
                    {"desired": want, "ready": ready,
                     "conditions": status.get("conditions", [])},
                    "Inspect the deployment's pods and recent events for why "
                    "replicas are not becoming ready",
                )
            sel = ((spec.get("selector") or {}).get("matchLabels")) or {}
            tlabels = (
                (spec.get("template") or {}).get("metadata", {}).get("labels")
                or {}
            )
            if sel and not selector_matches(sel, tlabels):
                r.add_finding(
                    f"Deployment/{name}",
                    "selector does not match the pod template labels",
                    "high",
                    {"selector": sel, "template_labels": tlabels},
                    "Align selector and template labels; the deployment "
                    "cannot adopt its own pods",
                )
        for kind, coll, ready_key, want_key in (
            ("StatefulSet", snap.statefulsets, "readyReplicas", "replicas"),
            ("DaemonSet", snap.daemonsets, "numberReady",
             "desiredNumberScheduled"),
        ):
            for w in coll:
                name = w.get("metadata", {}).get("name", "")
                status = w.get("status", {}) or {}
                want = int(
                    status.get(want_key, (w.get("spec", {}) or {}).get(
                        "replicas", 0)) or 0
                )
                ready = int(status.get(ready_key, 0) or 0)
                if want and ready < want:
                    r.add_finding(
                        f"{kind}/{name}",
                        f"{ready}/{want} replicas ready",
                        "high" if ready == 0 else "medium",
                        {"desired": want, "ready": ready},
                        f"Investigate the {kind.lower()}'s pods and events",
                    )

    # -- pod buckets --------------------------------------------------------
    @staticmethod
    def _pod_buckets(r: AgentResult, ctx: AnalysisContext) -> None:
        fs = ctx.features
        snap = ctx.snapshot
        pf = fs.pod_features

        buckets = [
            (
                "crashloop",
                pf[:, PodF.WAIT_CRASHLOOP] > 0,
                "pod stuck in CrashLoopBackOff",
                "critical",
                "Read the previous container logs and fix the crashing "
                "process; check liveness probes and required env/config",
            ),
            (
                "imagepull",
                pf[:, PodF.WAIT_IMAGEPULL] > 0,
                "pod cannot pull its container image",
                "high",
                "Verify image name/tag, registry reachability, and "
                "imagePullSecrets",
            ),
            (
                "config_error",
                pf[:, PodF.WAIT_CONFIG] > 0,
                "pod blocked on container configuration",
                "high",
                "Create the missing ConfigMap/Secret or fix its keys",
            ),
            (
                "init_failure",
                pf[:, PodF.INIT_FAILED] > 0,
                "pod blocked by a failing init container",
                "high",
                "Fix the init container; the main containers cannot start",
            ),
            (
                "oom",
                pf[:, PodF.TERM_OOM] > 0,
                "pod container was OOM-killed",
                "high",
                "Raise the memory limit or shrink the workload's footprint",
            ),
            (
                "failed",
                (pf[:, PodF.PHASE_FAILED] > 0)
                & (pf[:, PodF.WAIT_CRASHLOOP] == 0),
                "pod in Failed phase",
                "high",
                "Describe the pod for its termination reason and exit codes",
            ),
            (
                "pending",
                pf[:, PodF.PHASE_PENDING] > 0,
                "pod stuck Pending (unscheduled or not started)",
                "high",
                "Check scheduling events, node capacity, taints, and PVC "
                "binding",
            ),
            (
                "terminated_error",
                (pf[:, PodF.TERM_NONZERO] > 0)
                & (pf[:, PodF.WAIT_CRASHLOOP] == 0)
                & (pf[:, PodF.PHASE_FAILED] == 0),
                "container exited nonzero",
                "medium",
                "Inspect the exit code and last logs of the terminated "
                "container",
            ),
            (
                "not_ready",
                (pf[:, PodF.PHASE_RUNNING] > 0)
                & (pf[:, PodF.NOT_READY] > 0)
                & (pf[:, PodF.WAIT_CRASHLOOP] == 0)
                & (pf[:, PodF.WAIT_IMAGEPULL] == 0)
                & (pf[:, PodF.WAIT_CONFIG] == 0),
                "running pod not passing readiness",
                "medium",
                "Check the readiness probe and the app's startup/health state",
            ),
            (
                "restart_churn",
                (pf[:, PodF.RESTARTS] >= 3)
                & (pf[:, PodF.WAIT_CRASHLOOP] == 0),
                "pod restarting repeatedly",
                "medium",
                "Correlate restart times with probe failures and OOM events",
            ),
            (
                "unknown_phase",
                pf[:, PodF.PHASE_UNKNOWN] > 0,
                "pod phase Unknown (node unreachable?)",
                "high",
                "Check the pod's node health and kubelet connectivity",
            ),
        ]

        counts: Dict[str, int] = {}
        for key, mask, issue, sev, rec in buckets:
            idx = np.nonzero(mask)[0]
            counts[key] = int(len(idx))
            for i in idx.tolist():
                pod = snap.pod_by_name(fs.pod_names[i]) or {}
                status = pod.get("status", {}) or {}
                r.add_finding(
                    f"Pod/{fs.pod_names[i]}",
                    issue,
                    sev,
                    {
                        "phase": status.get("phase"),
                        "restarts": int(pf[i, PodF.RESTARTS]),
                        "containerStatuses": status.get(
                            "containerStatuses", []),
                    },
                    rec,
                    bucket=key,
                )
        r.data["pod_buckets"] = counts

    # -- event correlation ---------------------------------------------------
    @staticmethod
    def _correlate_events(r: AgentResult, ctx: AnalysisContext) -> None:
        snap = ctx.snapshot
        by_component: Dict[str, List[dict]] = {}
        for ev in snap.events:
            if ev.get("type") == "Normal":
                continue
            obj = ev.get("involvedObject", {}) or {}
            key = f"{obj.get('kind', 'Unknown')}/{obj.get('name', '')}"
            by_component.setdefault(key, []).append(
                {
                    "reason": ev.get("reason", ""),
                    "message": ev.get("message", ""),
                    "count": int(ev.get("count", 1) or 1),
                }
            )

        # attach to existing findings on the same component
        claimed = set()
        for f in r.findings:
            evs = by_component.get(f["component"])
            if evs:
                if isinstance(f["evidence"], dict):
                    f["evidence"].setdefault("related_events", evs[:5])
                claimed.add(f["component"])

        # mint findings from warning events on components nothing else flagged
        for key, evs in by_component.items():
            if key in claimed:
                continue
            total = sum(e["count"] for e in evs)
            reasons = sorted({e["reason"] for e in evs})
            r.add_finding(
                key,
                f"warning events ({', '.join(reasons)}) with no matching "
                "resource finding",
                "medium" if total > 3 else "low",
                {"events": evs[:5], "total": total},
                "Investigate these events — they flag a condition the "
                "resource sweep did not surface",
            )
