"""Deterministic signal agents (the parity oracle for the TPU engine).

Six agents mirroring the reference's signal coverage (reference: agents/ —
metrics, logs, events, topology, traces, resource_analyzer), each a stateless
``analyze(AnalysisContext) -> AgentResult`` over one shared snapshot +
packed-feature view.
"""

from rca_tpu.agents.base import Agent, AgentResult, AnalysisContext
from rca_tpu.agents.events import EventsAgent
from rca_tpu.agents.logs import LogsAgent
from rca_tpu.agents.metrics import MetricsAgent
from rca_tpu.agents.resources import ResourceAgent
from rca_tpu.agents.topology import TopologyAgent
from rca_tpu.agents.traces import TracesAgent

ALL_AGENT_TYPES = [
    "resources", "metrics", "logs", "events", "topology", "traces",
]


def make_agents():
    """All six signal agents in comprehensive-pipeline order (reference:
    agents/mcp_coordinator.py:637-645)."""
    return {
        "resources": ResourceAgent(),
        "metrics": MetricsAgent(),
        "logs": LogsAgent(),
        "events": EventsAgent(),
        "topology": TopologyAgent(),
        "traces": TracesAgent(),
    }


__all__ = [
    "Agent",
    "AgentResult",
    "AnalysisContext",
    "ALL_AGENT_TYPES",
    "EventsAgent",
    "LogsAgent",
    "MetricsAgent",
    "ResourceAgent",
    "TopologyAgent",
    "TracesAgent",
    "make_agents",
]
