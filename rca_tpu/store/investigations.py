"""File-locked JSON investigation store (checkpoint/resume for sessions).

Schema parity with the reference's DBHandler (reference:
utils/db_handler.py:48-62 — ``{id, title, namespace, context, created_at,
updated_at, summary, status, conversation[], evidence{}, agent_findings{},
next_actions[], accumulated_findings[]}``; append APIs :108-233; list+sort
:281-319; ``save_hypothesis`` :321) with one fix the reference lacked:
every read-modify-write holds an exclusive ``fcntl`` lock, so concurrent
sessions cannot race on the same investigation file (reference defect:
SURVEY.md §5 race row — ``db_handler.py:353`` had no locking anywhere).
"""

from __future__ import annotations

import contextlib
import datetime
import fcntl
import json
import os
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional

ACCUMULATED_FINDINGS_CAP = 20  # reference: chatbot_interface.py:514-516


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class InvestigationStore:
    def __init__(self, root: str = "logs"):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    # -- paths / locking ----------------------------------------------------
    def _path(self, investigation_id: str) -> Path:
        safe = "".join(
            c for c in investigation_id if c.isalnum() or c in "-_"
        )
        return self.root / f"{safe}.json"

    @contextlib.contextmanager
    def _locked(self, investigation_id: str):
        """Exclusive advisory lock around one investigation's file."""
        lock_path = self._path(investigation_id).with_suffix(".lock")
        with open(lock_path, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)

    def _read(self, investigation_id: str) -> Optional[Dict[str, Any]]:
        path = self._path(investigation_id)
        if not path.exists():
            return None
        try:
            return json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            return None

    def _write(self, inv: Dict[str, Any]) -> None:
        inv["updated_at"] = _now()
        path = self._path(inv["id"])
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(inv, indent=2, default=str))
        os.replace(tmp, path)  # atomic on POSIX

    # -- lifecycle -----------------------------------------------------------
    def create_investigation(
        self,
        title: str,
        namespace: str = "default",
        context: str = "",
        investigation_id: Optional[str] = None,
        recording_ref: Optional[str] = None,
    ) -> Dict[str, Any]:
        inv = {
            "id": investigation_id or str(uuid.uuid4()),
            "title": title,
            "namespace": namespace,
            "context": context,
            "created_at": _now(),
            "updated_at": _now(),
            "summary": "",
            "status": "active",
            "conversation": [],
            "evidence": {},
            "agent_findings": {},
            "next_actions": [],
            "accumulated_findings": [],
            # optional flight-recording path (rca_tpu/replay, REPLAY.md):
            # when set, this analysis can be re-driven deterministically
            # via `rca replay --investigation <id>`
            "recording_ref": recording_ref,
        }
        with self._locked(inv["id"]):
            self._write(inv)
        return inv

    def get_investigation(self, investigation_id: str) -> Optional[Dict[str, Any]]:
        return self._read(investigation_id)

    def list_investigations(self) -> List[Dict[str, Any]]:
        """Newest-first summaries (reference: db_handler.py:281-319)."""
        out = []
        for path in self.root.glob("*.json"):
            try:
                inv = json.loads(path.read_text())
            except (json.JSONDecodeError, OSError):
                continue
            if isinstance(inv, dict) and "id" in inv and "conversation" in inv:
                out.append(
                    {
                        "id": inv["id"],
                        "title": inv.get("title", ""),
                        "namespace": inv.get("namespace", ""),
                        "status": inv.get("status", ""),
                        "summary": inv.get("summary", ""),
                        "created_at": inv.get("created_at", ""),
                        "updated_at": inv.get("updated_at", ""),
                        "messages": len(inv.get("conversation", [])),
                        "replayable": bool(inv.get("recording_ref")),
                    }
                )
        out.sort(key=lambda r: r.get("updated_at", ""), reverse=True)
        return out

    def delete_investigation(self, investigation_id: str) -> bool:
        with self._locked(investigation_id):
            path = self._path(investigation_id)
            if path.exists():
                path.unlink()
                return True
        return False

    # -- append APIs ----------------------------------------------------------
    def _update(self, investigation_id: str, mutate) -> Optional[Dict[str, Any]]:
        with self._locked(investigation_id):
            inv = self._read(investigation_id)
            if inv is None:
                return None
            mutate(inv)
            self._write(inv)
            return inv

    def add_message(
        self, investigation_id: str, role: str, content: Any,
        **extra: Any,
    ) -> Optional[Dict[str, Any]]:
        def mutate(inv):
            inv["conversation"].append(
                {"role": role, "content": content, "timestamp": _now(), **extra}
            )

        return self._update(investigation_id, mutate)

    def set_next_actions(
        self, investigation_id: str, suggestions: List[dict]
    ) -> Optional[Dict[str, Any]]:
        return self._update(
            investigation_id,
            lambda inv: inv.__setitem__("next_actions", suggestions),
        )

    def add_evidence(
        self, investigation_id: str, key: str, value: Any
    ) -> Optional[Dict[str, Any]]:
        def mutate(inv):
            inv["evidence"][key] = value

        return self._update(investigation_id, mutate)

    def add_agent_findings(
        self, investigation_id: str, agent_type: str, findings: Any
    ) -> Optional[Dict[str, Any]]:
        def mutate(inv):
            inv["agent_findings"][agent_type] = findings

        return self._update(investigation_id, mutate)

    def add_accumulated_findings(
        self, investigation_id: str, findings: List[str]
    ) -> Optional[Dict[str, Any]]:
        """Append, dedup, cap at the last 20 (reference:
        chatbot_interface.py:509-516)."""

        def mutate(inv):
            acc = inv.get("accumulated_findings", [])
            for f in findings:
                if f and f not in acc:
                    acc.append(f)
            inv["accumulated_findings"] = acc[-ACCUMULATED_FINDINGS_CAP:]

        return self._update(investigation_id, mutate)

    def update_summary(
        self, investigation_id: str, summary: str
    ) -> Optional[Dict[str, Any]]:
        return self._update(
            investigation_id, lambda inv: inv.__setitem__("summary", summary)
        )

    def set_title(
        self, investigation_id: str, title: str
    ) -> Optional[Dict[str, Any]]:
        return self._update(
            investigation_id, lambda inv: inv.__setitem__("title", title)
        )

    def update_status(
        self, investigation_id: str, status: str
    ) -> Optional[Dict[str, Any]]:
        return self._update(
            investigation_id, lambda inv: inv.__setitem__("status", status)
        )

    def set_recording_ref(
        self, investigation_id: str, recording_ref: str
    ) -> Optional[Dict[str, Any]]:
        """Attach the flight recording that captured this investigation's
        served analyses — `rca replay --investigation <id>` resolves the
        log through this field."""
        return self._update(
            investigation_id,
            lambda inv: inv.__setitem__("recording_ref", recording_ref),
        )

    def get_recording_ref(self, investigation_id: str) -> Optional[str]:
        inv = self._read(investigation_id)
        return (inv or {}).get("recording_ref")

    def set_provenance(
        self, investigation_id: str, provenance: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Attach the LATEST causelens provenance block (ISSUE 14) —
        `rca why <id>` renders the blame tree from this field.  Last
        write wins: an investigation's attribution tracks its most
        recent explained ranking."""
        return self._update(
            investigation_id,
            lambda inv: inv.__setitem__("provenance", provenance),
        )

    def get_provenance(
        self, investigation_id: str
    ) -> Optional[Dict[str, Any]]:
        inv = self._read(investigation_id)
        return (inv or {}).get("provenance")

    def save_hypothesis(
        self, investigation_id: str, hypothesis: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        def mutate(inv):
            inv.setdefault("hypotheses", []).append(
                {**hypothesis, "saved_at": _now()}
            )

        return self._update(investigation_id, mutate)

    def record_chat_turn(
        self, investigation_id: str, query: str, out: Dict[str, Any]
    ) -> None:
        """Persist one ``process_user_query`` turn — the single protocol
        for what a turn writes (user + assistant messages, next actions,
        accumulated findings), shared by the UI chat tab and the CLI's
        ``chat --investigation`` so the two cannot drift."""
        self.add_message(investigation_id, "user", query)
        self.add_message(
            investigation_id, "assistant",
            {"response_data": out.get("response_data", {}),
             "summary": out.get("summary", "")},
        )
        self.set_next_actions(investigation_id, out.get("suggestions", []))
        self.add_accumulated_findings(
            investigation_id, out.get("key_findings", [])
        )
