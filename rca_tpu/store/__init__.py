"""Investigation persistence (file-locked JSON store)."""

from rca_tpu.store.investigations import (
    ACCUMULATED_FINDINGS_CAP,
    InvestigationStore,
)

__all__ = ["ACCUMULATED_FINDINGS_CAP", "InvestigationStore"]
