"""Gateway + canary: the wire front door over the serving plane, and
the replay-driven continuous regression canary (ISSUE 9, SERVING.md
§Gateway, REPLAY.md §Canary).

- :mod:`rca_tpu.gateway.wire`    JSON ⇄ serve-contract codec + the
  honest HTTP status map (queue_full→429, shed→503, degraded→200+flag);
- :mod:`rca_tpu.gateway.server`  :class:`GatewayServer`: stdlib-HTTP
  front over a started ``ServeLoop``/``ServePool`` (`rca serve
  --listen`) with tenant tagging from a header, chunked streaming tick
  subscriptions, `/metrics`, and breaker-fed `/healthz`;
- :mod:`rca_tpu.gateway.export`  the Prometheus text exposition;
- :mod:`rca_tpu.gateway.client`  :class:`GatewayClient`, the wire twin
  of the in-process ``ServeClient``;
- :mod:`rca_tpu.gateway.canary`  `rca canary`: sample live
  investigations into minted recordings, replay them against a
  candidate build/config, fail on ranking divergence with the exact
  bisected tick.
"""

from rca_tpu.gateway.canary import build_candidate_engine, run_canary
from rca_tpu.gateway.client import GatewayClient
from rca_tpu.gateway.export import render_metrics_text
from rca_tpu.gateway.server import GatewayMetrics, GatewayServer, TickHub
from rca_tpu.gateway.wire import (
    DEFAULT_TENANT,
    TENANT_HEADER,
    WireError,
    decode_analyze,
    encode_analyze,
    response_body,
    status_code_for,
)

__all__ = [
    "DEFAULT_TENANT",
    "TENANT_HEADER",
    "GatewayClient",
    "GatewayMetrics",
    "GatewayServer",
    "TickHub",
    "WireError",
    "build_candidate_engine",
    "decode_analyze",
    "encode_analyze",
    "render_metrics_text",
    "response_body",
    "run_canary",
    "status_code_for",
]
