"""The wire front door: stdlib HTTP over the serving plane (ISSUE 9).

Until this layer, nothing outside the process could reach the serve
queue — the pool is threads-in-one-process.  :class:`GatewayServer` puts
an HTTP/1.1 surface (no dependencies beyond the standard library, same
policy as ``cluster/trace_backend.py``) in front of any started
:class:`rca_tpu.serve.loop.ServeLoop` or :class:`rca_tpu.serve.pool.
ServePool`, mapped onto the existing ``ServeRequest``/``ServeResponse``
contract through :mod:`rca_tpu.gateway.wire`:

- ``POST /v1/analyze``   one analyze request; auth-less tenant tagging
  from the ``X-RCA-Tenant`` header; backpressure mapped honestly
  (queue_full→429+Retry-After, shed→503, degraded→200 with a
  ``degraded`` flag, error→500, gateway wait bound→504);
- ``GET /v1/subscribe``  chunked streaming tick subscription: one JSON
  line per response this gateway serves (optionally filtered to one
  tenant) — a live investigation watches its rankings arrive instead of
  polling;
- ``GET /metrics``       Prometheus text exposition of the serving
  plane's per-tenant/per-replica counters plus the gateway's own HTTP
  counters (one consistent snapshot each, see serve/metrics.py);
- ``GET /healthz``       breaker-fed liveness: 200 while the plane is
  routable (any live, non-open replica), 503 otherwise.

Concurrency discipline (gravelock, ANALYSIS.md): every connection thread
is spawned NAMED through :func:`rca_tpu.util.threads.spawn` (the server
overrides ``socketserver``'s anonymous-thread spawn), the listening
socket is built through the :mod:`rca_tpu.util.net` seam, gateway state
(:class:`GatewayMetrics`, :class:`TickHub`) is lock-guarded, and the
gateway never touches the device — requests park on ``req.result()``
like any in-process submitter, so fetch stays the serve path's only
sync point (tick-sync lint covers this package).  Timing goes through
the injectable ``clock`` seam (nondet-discipline: the gateway is
replay-adjacent — its recordings must stay host-independent).
"""

from __future__ import annotations

import collections
import hmac
import itertools
import json
import queue
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from rca_tpu.config import (
    gateway_max_body,
    gateway_port,
    gateway_tenant_rps,
    gateway_tls_client_ca,
    gateway_tls_files,
    gateway_tokens,
)
from rca_tpu.gateway.export import render_metrics_text
from rca_tpu.gateway.wire import (
    RETRY_AFTER_MS_HEADER,
    TENANT_HEADER,
    WireError,
    decode_analyze,
    response_body,
    status_code_for,
)
from rca_tpu.observability.export import chrome_trace, ndjson_spans
from rca_tpu.observability.spans import (
    TRACE_HEADER,
    SpanContext,
    default_tracer,
)
from rca_tpu.obslog.profiling import PhaseStats
from rca_tpu.serve.client import ServeClient
from rca_tpu.util.net import bound_address, make_server_socket
from rca_tpu.util.threads import make_lock, spawn

#: default gateway-side wait bound on one analyze request (504 past it);
#: generous — the scheduler's own deadline/shed machinery is the real
#: latency policy, this only bounds a wedged plane
DEFAULT_TIMEOUT_S = 60.0

#: idle poll while a subscriber waits for its next event (also the
#: shutdown-notice latency for parked streams)
_STREAM_POLL_S = 0.25


class GatewayMetrics:
    """The gateway's own HTTP counters (the serve plane's live in
    :class:`rca_tpu.serve.metrics.ServeMetrics`).  ``snapshot()`` returns
    one consistent copy for the exporter — same discipline as the serve
    metrics' summary."""

    def __init__(self) -> None:
        self._lock = make_lock("GatewayMetrics._lock")
        self._requests: Dict[Tuple[str, int], int] = {}
        self._latency = PhaseStats()   # one phase per route
        self._streams_opened = 0
        self._stream_events = 0
        self._body_rejections = 0
        self._rate_limited = 0
        self._auth_rejections = 0

    def response(self, route: str, code: int, ms: float) -> None:
        with self._lock:
            key = (route, int(code))
            self._requests[key] = self._requests.get(key, 0) + 1
            self._latency.record(route, float(ms))

    def stream_opened(self) -> None:
        with self._lock:
            self._streams_opened += 1

    def stream_event(self) -> None:
        with self._lock:
            self._stream_events += 1

    def body_rejected(self) -> None:
        with self._lock:
            self._body_rejections += 1

    def rate_limited(self) -> None:
        with self._lock:
            self._rate_limited += 1

    def auth_rejected(self) -> None:
        """One request refused at the authn door (401/403) — BEFORE the
        body was read or the serve queue was touched."""
        with self._lock:
            self._auth_rejections += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            requests = dict(self._requests)
            latency = self._latency.snapshot()
            streams_opened = self._streams_opened
            stream_events = self._stream_events
            body_rejections = self._body_rejections
            rate_limited = self._rate_limited
            auth_rejections = self._auth_rejections
        return {
            "requests": requests,
            "latency": {
                route: {
                    "p50": latency.quantile(route, 0.50),
                    "p99": latency.quantile(route, 0.99),
                }
                for route in latency.phases()
            },
            "streams_opened": streams_opened,
            "stream_events": stream_events,
            "body_rejections": body_rejections,
            "rate_limited": rate_limited,
            "auth_rejections": auth_rejections,
        }


class TenantRateLimiter:
    """Per-tenant token buckets at the wire (``RCA_GATEWAY_TENANT_RPS``,
    ISSUE 10 satellite).  Until now the only admission control was the
    GLOBAL serve-queue cap — one hot tenant could fill it before the
    scheduler's weighted-fair queuing ever saw anyone else.  Each tenant
    gets an independent bucket refilled at ``rps`` with one second's
    burst; an empty bucket answers with the seconds until the next token
    (the 429's Retry-After), and the request never touches the queue.

    Time comes from the injectable ``clock`` (monotonic seconds) —
    nondet-discipline, same seam as the rest of the gateway.  The tenant
    map is bounded: past ``max_tenants`` the stalest bucket (a full one,
    i.e. an idle tenant) is evicted."""

    MAX_TENANTS = 4096

    def __init__(self, rps: float, clock: Callable[[], float],
                 burst: Optional[float] = None,
                 max_tenants: int = MAX_TENANTS):
        self.rps = float(rps)
        self.burst = float(burst) if burst is not None else max(
            1.0, self.rps
        )
        self.clock = clock
        self.max_tenants = int(max_tenants)
        self._lock = make_lock("TenantRateLimiter._lock")
        # tenant -> [tokens, last_refill_ts]
        self._buckets: Dict[str, list] = {}
        self.rejected = 0

    def admit(self, tenant: str) -> float:
        """0.0 = admitted (one token consumed); positive = rejected, the
        value is the seconds until a token exists (Retry-After)."""
        now = self.clock()
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                if len(self._buckets) >= self.max_tenants:
                    # evict the fullest (stalest) bucket; a returning
                    # evictee simply starts with a fresh full burst
                    victim = max(
                        self._buckets,
                        key=lambda t: self._buckets[t][0],
                    )
                    del self._buckets[victim]
                b = [self.burst, now]
                self._buckets[tenant] = b
            tokens, last = b
            tokens = min(self.burst, tokens + (now - last) * self.rps)
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                b[1] = now
                return 0.0
            b[0] = tokens
            b[1] = now
            self.rejected += 1
            return (1.0 - tokens) / self.rps


class TickHub:
    """Pub/sub of served responses for streaming subscriptions.

    The analyze path publishes every terminal response it delivers; each
    subscriber owns a bounded queue.  A slow subscriber DROPS events
    (``queue.Full`` is swallowed) rather than ever back-pressuring the
    serving plane — the stream is observability, not the system of
    record."""

    #: events a parked subscriber may lag before drops start
    QUEUE_CAP = 1024

    def __init__(self) -> None:
        self._lock = make_lock("TickHub._lock")
        self._subs: Dict[int, Tuple[Optional[str], "queue.Queue"]] = {}
        self._counter = itertools.count()
        self.dropped = 0

    def subscribe(
        self, tenant: Optional[str] = None
    ) -> Tuple[int, "queue.Queue"]:
        q: "queue.Queue" = queue.Queue(maxsize=self.QUEUE_CAP)
        with self._lock:
            sid = next(self._counter)
            self._subs[sid] = (tenant, q)
        return sid, q

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def publish(self, event: Dict[str, Any]) -> None:
        with self._lock:
            subs = list(self._subs.values())
        for tenant, q in subs:
            if tenant is not None and tenant != event.get("tenant"):
                continue
            try:
                q.put_nowait(event)
            except queue.Full:
                with self._lock:
                    self.dropped += 1


class _GatewayHTTPServer(HTTPServer):
    """HTTPServer over a seam-built socket, spawning NAMED connection
    threads (socketserver's ThreadingMixIn spawns anonymous raw threads,
    which the thread-discipline rule exists to prevent)."""

    daemon_threads = True

    def __init__(self, sock, handler_cls, gateway: "GatewayServer"):
        addr = bound_address(sock)
        super().__init__(addr, handler_cls, bind_and_activate=False)
        # TCPServer pre-built an unbound socket; replace it with the
        # seam's listening one
        self.socket.close()
        self.socket = sock
        self.server_name, self.server_port = addr
        self.gateway = gateway
        self._conn_counter = itertools.count()

    def process_request(self, request, client_address) -> None:
        spawn(
            self._process_request_thread,
            name=f"rca-gateway-conn{next(self._conn_counter)}",
            daemon=True,
            args=(request, client_address),
        )

    def _process_request_thread(self, request, client_address) -> None:
        from rca_tpu.resilience.policy import suppressed

        # a client hanging up mid-response (BrokenPipe, reset) is normal
        # wire weather, not a server fault; record it in the bounded
        # fault log, never crash the acceptor or spam stderr
        with suppressed("gateway.connection"):
            if self.gateway.tls_context is not None:
                # TLS handshake happens HERE, on the connection thread —
                # never on the acceptor (a slow or plaintext client must
                # not block accept).  A failed handshake (plaintext to a
                # TLS gateway, bad protocol, missing/untrusted client
                # cert under mTLS) raises, is recorded in the fault log,
                # and the connection dies having touched nothing:
                # rejected before the serve queue by construction.
                try:
                    request = self.gateway.tls_context.wrap_socket(
                        request, server_side=True
                    )
                except (OSError, ValueError):
                    # under mTLS a handshake failure IS an authn
                    # rejection (no/untrusted client cert) — count it
                    # with the other refused credentials, then let
                    # suppressed() log the fault
                    if self.gateway.tls_client_ca is not None:
                        self.gateway.metrics.auth_rejected()
                    raise
            self.finish_request(request, client_address)
        self.shutdown_request(request)

    def handle_error(self, request, client_address) -> None:  # pragma: no cover
        pass


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "rca-gateway/1"

    # BaseHTTPRequestHandler logs every request to stderr by default;
    # the gateway's observability surface is /metrics, not chatter
    def log_message(self, fmt, *args) -> None:  # noqa: D401
        pass

    @property
    def gateway(self) -> "GatewayServer":
        return self.server.gateway

    # -- response plumbing ---------------------------------------------------
    def _send_json(
        self, code: int, body: Dict[str, Any],
        retry_after: Optional[int] = None,
        trace: Optional[str] = None,
        www_authenticate: bool = False,
    ) -> None:
        payload = json.dumps(body).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        if retry_after is not None:
            # seeded jitter (ISSUE 15 small fix): a constant Retry-After
            # resynchronizes every shed client onto the same retry
            # instant — the NEXT shed storm arrives as one wave.  The
            # standard header stays integer seconds; the ms header
            # carries the jittered value GatewayClient honors.
            seconds, ms = self.gateway.jittered_retry_after(retry_after)
            self.send_header("Retry-After", str(seconds))
            self.send_header(RETRY_AFTER_MS_HEADER, str(ms))
        if www_authenticate:
            self.send_header("WWW-Authenticate", "Bearer")
        if trace is not None:
            # the header contract: context in, context out — the caller
            # can stitch its own spans onto the gateway's
            self.send_header(TRACE_HEADER, trace)
        self.end_headers()
        self.wfile.write(payload)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; charset=utf-8") -> None:
        payload = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _authorize(self) -> Tuple[Optional[int], Optional[str]]:
        """The authn door (ISSUE 15): with ``RCA_GATEWAY_TOKENS`` set,
        every route except ``/healthz`` needs ``Authorization: Bearer``.

        Returns ``(already_sent_code | None, bound_tenant | None)``.
        Runs BEFORE any body read — a rejected request costs the
        gateway headers only, and the connection is closed (the unread
        body would desynchronize keep-alive).  Token comparison is
        constant-time against EVERY configured token, no early exit.
        The matched token's tenant BINDS the request: an
        ``X-RCA-Tenant`` header naming anyone else is a spoof (403)."""
        gw = self.gateway
        if not gw.tokens:
            return None, None
        header = self.headers.get("Authorization") or ""
        token = header[7:] if header.startswith("Bearer ") else ""
        bound: Optional[Tuple[str, Optional[float]]] = None
        matched = False
        for tok, binding in gw.tokens.items():
            if hmac.compare_digest(
                token.encode("utf-8"), tok.encode("utf-8")
            ):
                matched = True
                bound = binding
        if not matched:
            gw.metrics.auth_rejected()
            self.close_connection = True
            self._send_json(401, {
                "status": "error",
                "detail": "missing or invalid bearer token "
                          "(RCA_GATEWAY_TOKENS)",
            }, www_authenticate=True)
            return 401, None
        tenant, expires = bound  # type: ignore[misc]
        if expires is not None and gw.wall() >= expires:
            gw.metrics.auth_rejected()
            self.close_connection = True
            self._send_json(401, {
                "status": "error", "detail": "token expired",
            }, www_authenticate=True)
            return 401, None
        hdr = self.headers.get(TENANT_HEADER)
        if hdr and hdr != tenant:
            gw.metrics.auth_rejected()
            self.close_connection = True
            self._send_json(403, {
                "status": "error",
                "detail": f"token is bound to tenant {tenant!r}; "
                          f"X-RCA-Tenant {hdr!r} is not yours to claim",
            })
            return 403, None
        return None, tenant

    def _route(self, handler: Callable[[], int], route: str) -> None:
        gw = self.gateway
        t0 = gw.clock()
        try:
            code = handler()
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-write; nothing left to answer
            self.close_connection = True
            return
        except Exception as exc:  # noqa: BLE001 - the wire must answer
            code = 500
            try:
                self._send_json(500, {
                    "status": "error",
                    "detail": f"gateway:{type(exc).__name__}",
                })
            except OSError:
                self.close_connection = True
        gw.metrics.response(route, code, (gw.clock() - t0) * 1e3)

    # -- routes --------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        parts = urlsplit(self.path)
        path = parts.path
        if path == "/v1/analyze":
            self._route(
                lambda: self._post_analyze(parse_qs(parts.query)),
                "analyze",
            )
        else:
            self._route(
                lambda: (self._send_json(
                    404, {"status": "error", "detail": f"no route {path}"}
                ) or 404),
                "unknown",
            )

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        parts = urlsplit(self.path)
        if parts.path == "/healthz":
            self._route(self._get_healthz, "healthz")
        elif parts.path == "/metrics":
            self._route(self._get_metrics, "metrics")
        elif parts.path == "/v1/traces":
            self._route(
                lambda: self._get_traces(parse_qs(parts.query)),
                "traces",
            )
        elif parts.path.startswith("/v1/explain/"):
            self._route(
                lambda: self._get_explain(
                    parts.path[len("/v1/explain/"):]
                ),
                "explain",
            )
        elif parts.path == "/v1/subscribe":
            self._route(
                lambda: self._get_subscribe(parse_qs(parts.query)),
                "subscribe",
            )
        else:
            self._route(
                lambda: (self._send_json(
                    404,
                    {"status": "error", "detail": f"no route {parts.path}"},
                ) or 404),
                "unknown",
            )

    def _post_analyze(self, query: Optional[Dict[str, list]] = None) -> int:
        gw = self.gateway
        # authn FIRST (ISSUE 15): a 401/403 costs headers only — the
        # body stays unread, the serve queue untouched
        auth_code, bound_tenant = self._authorize()
        if auth_code is not None:
            return auth_code
        t0 = gw.clock()
        # trace context enters here (ISSUE 11): parse the caller's
        # X-RCA-Trace (malformed = absent), mint THIS request's gateway
        # span as its child (or a fresh trace), echo the context back —
        # even when the body is later rejected, the caller can correlate
        wire_ctx = SpanContext.from_wire(self.headers.get(TRACE_HEADER))
        gctx = gw.tracer.new_context(parent=wire_ctx)
        echo = (gctx or wire_ctx).to_wire() if (gctx or wire_ctx) else None

        def _finish(code: int, body: Dict[str, Any],
                    retry_after: Optional[int] = None,
                    status: str = "error") -> int:
            if gctx is not None:
                gw.tracer.record(
                    "gateway.analyze", t0, gw.clock(),
                    parent=wire_ctx, context=gctx,
                    attrs={"code": code, "status": status,
                           "tenant": body.get("tenant", "")},
                )
                body.setdefault("trace_id", gctx.trace_id)
            self._send_json(code, body, retry_after=retry_after,
                            trace=echo)
            return code

        length = int(self.headers.get("Content-Length") or 0)
        if length > gw.max_body:
            # refuse BEFORE reading the flood: backpressure that only
            # engages after parsing the payload is not backpressure
            gw.metrics.body_rejected()
            self.close_connection = True
            return _finish(413, {
                "status": "error",
                "detail": f"body {length} B over the "
                f"{gw.max_body} B cap (RCA_GATEWAY_MAX_BODY)",
            })
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw.decode("utf-8"))
            kwargs = decode_analyze(
                # the token's tenant binds the request when authn is on
                # (the spoof case already 403'd in _authorize); auth-less
                # gateways keep the ISSUE-9 header tagging
                body, header_tenant=(
                    bound_tenant or self.headers.get(TENANT_HEADER)
                ),
            )
        except (WireError, UnicodeDecodeError,
                json.JSONDecodeError) as exc:
            return _finish(400, {"status": "error", "detail": str(exc)})
        if query and (query.get("explain") or [""])[0] in ("1", "true",
                                                           "on"):
            # ?explain=1 (ISSUE 14): query-param twin of the body's
            # "explain": true — curl ergonomics for the common case
            kwargs["explain"] = True
        if gw.limiter is not None:
            wait = gw.limiter.admit(kwargs.get("tenant", ""))
            if wait > 0.0:
                # refused at the door: the request never touches the
                # serve queue, so one hot tenant cannot fill the global
                # cap ahead of everyone else's fair share
                gw.metrics.rate_limited()
                return _finish(429, {
                    "status": "rate_limited",
                    "tenant": kwargs.get("tenant", ""),
                    "detail": (
                        "per-tenant rate limit "
                        f"({gw.limiter.rps:g} req/s, "
                        "RCA_GATEWAY_TENANT_RPS) exceeded"
                    ),
                }, retry_after=max(1, int(wait + 0.999)),
                    status="rate_limited")
        req = gw.client.submit(trace_parent=gctx, **kwargs)
        try:
            resp = req.result(gw.timeout_s)
        except TimeoutError:
            return _finish(504, {
                "status": "error", "request_id": req.request_id,
                "tenant": req.tenant,
                "detail": f"not completed within {gw.timeout_s}s",
            }, status="timeout")
        out = response_body(resp)
        if req.trace is not None:
            out["trace_id"] = req.trace.trace_id
        if resp.provenance is not None:
            # retained for GET /v1/explain/<trace_id> (falls back to the
            # request id when tracing is off — the body names both)
            gw.remember_explain(
                out.get("trace_id"), resp.request_id, {
                    "request_id": resp.request_id,
                    "tenant": resp.tenant,
                    "trace_id": out.get("trace_id"),
                    "provenance": resp.provenance,
                },
            )
        gw.hub.publish(out)
        code, retry_after = status_code_for(resp.status)
        return _finish(code, out, retry_after=retry_after,
                       status=resp.status)

    def _get_healthz(self) -> int:
        health = self.gateway.health()
        code = 200 if health["ok"] else 503
        self._send_json(code, health)
        return code

    def _get_metrics(self) -> int:
        auth_code, _ = self._authorize()
        if auth_code is not None:
            return auth_code
        gw = self.gateway
        scope_fn = getattr(gw.loop, "kernelscope_summary", None)
        text = render_metrics_text(
            gw.loop.metrics.summary(),
            gateway=gw.metrics.snapshot(),
            # kernelscope rows (ISSUE 12): recompiles, device memory,
            # per-shape kernel registry — planes without the surface
            # (stub loops in tests) simply omit the families
            kernelscope=scope_fn() if callable(scope_fn) else None,
            healthy=gw.health()["ok"],
            # proper exposition format (ISSUE 11 satellite): gauges carry
            # a millisecond timestamp so a scraper knows WHEN the point
            # was true; the wall read goes through the injectable seam
            now_ms=int(gw.wall() * 1e3),
        )
        self._send_text(200, text,
                        content_type="text/plain; version=0.0.4")
        return 200

    def _get_traces(self, query: Dict[str, list]) -> int:
        """``GET /v1/traces`` (ISSUE 11): the tracer's span buffer on
        the wire.  ``trace_id`` filters to one trace; ``max`` keeps the
        newest N (default 1000); ``format=chrome`` returns one
        Perfetto-loadable Chrome trace JSON object instead of NDJSON.
        With ``RCA_TRACE=0`` the buffer is empty — 200 with zero lines,
        plus an X-RCA-Trace-Enabled header saying why."""
        auth_code, _ = self._authorize()
        if auth_code is not None:
            return auth_code
        gw = self.gateway
        trace_id = (query.get("trace_id") or [None])[0]
        fmt = (query.get("format") or ["ndjson"])[0]
        try:
            limit = int((query.get("max") or ["1000"])[0])
        except ValueError:
            self._send_json(400, {
                "status": "error", "detail": "max must be an integer",
            })
            return 400
        spans = gw.tracer.spans(trace_id=trace_id, limit=limit)
        if fmt == "chrome":
            payload = json.dumps(chrome_trace(spans)).encode("utf-8")
            content_type = "application/json"
        else:
            payload = ndjson_spans(spans).encode("utf-8")
            content_type = "application/x-ndjson"
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("X-RCA-Trace-Enabled",
                         "1" if gw.tracer.enabled else "0")
        self.end_headers()
        self.wfile.write(payload)
        return 200

    def _get_explain(self, key: str) -> int:
        """``GET /v1/explain/<trace_id>`` (ISSUE 14): the retained
        causelens provenance for a recently explained analyze request —
        keyed by trace id (or request id when tracing was off).  The
        cache is bounded (oldest drop), so a 404 means expired OR never
        explained; the analyze response body carried the block either
        way."""
        auth_code, bound_tenant = self._authorize()
        if auth_code is not None:
            return auth_code
        record = self.gateway.lookup_explain(key)
        if (record is not None and bound_tenant is not None
                and record.get("tenant") != bound_tenant):
            # a token sees only its OWN tenant's provenance
            record = None
        if record is None:
            self._send_json(404, {
                "status": "error",
                "detail": f"no retained explanation for {key!r} "
                "(expired, or the request was not sent with explain)",
            })
            return 404
        self._send_json(200, record)
        return 200

    def _get_subscribe(self, query: Dict[str, list]) -> int:
        """Chunked stream: one JSON line per served response.  ``tenant``
        filters; ``max`` (default 0 = unbounded) ends the stream after N
        events; ``idle_s`` (default 30) ends it after that long with no
        event.  The stream also ends when the gateway shuts down."""
        auth_code, bound_tenant = self._authorize()
        if auth_code is not None:
            return auth_code
        gw = self.gateway
        tenant = (query.get("tenant") or [None])[0]
        if bound_tenant is not None:
            # an authenticated subscriber sees its OWN tenant's events
            # only — the token binds the filter, not the query string
            tenant = bound_tenant
        try:
            max_events = int((query.get("max") or ["0"])[0])
            idle_s = float((query.get("idle_s") or ["30"])[0])
        except ValueError:
            self._send_json(400, {
                "status": "error",
                "detail": "max/idle_s must be numeric",
            })
            return 400
        sid, q = gw.hub.subscribe(tenant)
        gw.metrics.stream_opened()
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        sent = 0
        idle = 0.0
        try:
            while not gw.closing.is_set():
                try:
                    event = q.get(timeout=_STREAM_POLL_S)
                except queue.Empty:
                    idle += _STREAM_POLL_S
                    if idle >= idle_s:
                        break
                    continue
                idle = 0.0
                self._write_chunk(
                    json.dumps(event).encode("utf-8") + b"\n"
                )
                gw.metrics.stream_event()
                sent += 1
                if max_events and sent >= max_events:
                    break
            self._write_chunk(b"")   # terminal zero-length chunk
        finally:
            gw.hub.unsubscribe(sid)
            self.close_connection = True
        return 200

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        if data:
            self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()


class GatewayServer:
    """The front door over one started serving plane.

    ``loop`` is a started :class:`ServeLoop` or :class:`ServePool` (the
    gateway does not own its lifecycle — N gateways can front one
    plane, which is the multi-process stepping stone ROADMAP item 2
    names).  ``port`` 0 binds an ephemeral port; read ``self.port``."""

    def __init__(
        self,
        loop,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        max_body: Optional[int] = None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        clock: Callable[[], float] = time.monotonic,
        tenant_rps: Optional[float] = None,
        tracer=None,
        wall: Callable[[], float] = time.time,
        tls: Optional[Tuple[str, str]] = None,
        tls_client_ca: Optional[str] = None,
        tokens: Optional[Dict[str, Tuple[str, Optional[float]]]] = None,
        retry_jitter_s: float = 2.0,
        retry_jitter_seed: Optional[int] = None,
    ):
        self.loop = loop
        self.client = ServeClient(loop)
        self.clock = clock
        # TLS + authn front door (ISSUE 15).  ``tls`` is a (cert, key)
        # PEM pair — default from RCA_GATEWAY_TLS_CERT/KEY; the context
        # is built once through the util/net seam and each connection
        # handshakes on its own thread.  ``tokens`` maps bearer token →
        # (tenant, expires) — default from RCA_GATEWAY_TOKENS; empty =
        # authn off (the ISSUE-9 auth-less behavior, loopback territory).
        tls_pair = tls if tls is not None else gateway_tls_files()
        # mTLS (ISSUE 16): a client-CA file upgrades the listener to
        # REQUIRE and verify client certificates at handshake —
        # rejection happens before a byte of HTTP, counted in
        # auth_rejections like every other refused credential
        client_ca = (
            tls_client_ca if tls_client_ca is not None
            else (gateway_tls_client_ca() if tls is None else None)
        )
        if client_ca and tls_pair is None:
            raise ValueError(
                "gateway: tls_client_ca requires a TLS cert/key pair "
                "(mTLS without server TLS is not a thing)"
            )
        self.tls_client_ca = client_ca or None
        if tls_pair is not None:
            from rca_tpu.util.net import make_tls_server_context

            self.tls_context = make_tls_server_context(
                "gateway", tls_pair[0], tls_pair[1],
                client_ca=self.tls_client_ca,
            )
        else:
            self.tls_context = None
        self.tokens = dict(tokens) if tokens is not None else (
            gateway_tokens()
        )
        # seeded Retry-After jitter (ISSUE 15 small fix): deterministic
        # per gateway, different ACROSS gateways (the default seed is
        # the bound port), so a shed storm's retries de-synchronize
        # instead of arriving back as one wave
        self._retry_jitter_s = float(retry_jitter_s)
        self._retry_lock = make_lock("GatewayServer._retry_lock")
        self._retry_seed = retry_jitter_seed
        # wall-clock seam for /metrics gauge timestamps (exposition
        # format wants ms-since-epoch; the injectable reference keeps
        # nondet-discipline — no direct wall read on any handler path)
        self.wall = wall
        # the serving plane's tracer and the gateway's must be ONE
        # tracer for a wire request to read as one connected trace;
        # default both to the process tracer, prefer the plane's own
        self.tracer = (
            tracer if tracer is not None
            else getattr(loop, "tracer", None) or default_tracer()
        )
        self.max_body = int(max_body) if max_body is not None \
            else gateway_max_body()
        self.timeout_s = float(timeout_s)
        rps = gateway_tenant_rps() if tenant_rps is None else float(
            tenant_rps
        )
        # per-tenant token buckets (RCA_GATEWAY_TENANT_RPS; 0 = off)
        self.limiter = (
            TenantRateLimiter(rps, clock) if rps > 0.0 else None
        )
        self.metrics = GatewayMetrics()
        self.hub = TickHub()
        # causelens (ISSUE 14): recently served provenance blocks, keyed
        # by trace_id AND request_id, bounded LRU — GET /v1/explain/<id>
        # reads them back after the analyze response was consumed
        self._explains_lock = make_lock("GatewayServer._explains_lock")
        self._explains: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )
        self.closing = threading.Event()
        sock = make_server_socket(
            "gateway", host, port if port is not None else gateway_port()
        )
        self.host, self.port = bound_address(sock)
        self._retry_rng = random.Random(
            self._retry_seed if self._retry_seed is not None else self.port
        )
        self._httpd = _GatewayHTTPServer(sock, _Handler, self)
        self._thread = None

    def jittered_retry_after(self, base_s: int) -> Tuple[int, int]:
        """``(retry_after_seconds, retry_after_ms)`` for one 429/503:
        base + a seeded uniform draw in [0, retry_jitter_s).  The ms
        value is the honest hint; the seconds value is its ceiling so
        standard clients never retry EARLIER than our own."""
        with self._retry_lock:
            jitter = self._retry_rng.uniform(0.0, self._retry_jitter_s)
        total = float(base_s) + jitter
        return max(1, int(total + 0.999)), max(1, int(total * 1000.0))

    #: explained responses retained for GET /v1/explain/<id> (per key)
    EXPLAIN_CACHE_CAP = 256

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- causelens retention (ISSUE 14) --------------------------------------
    def remember_explain(self, trace_id: Optional[str], request_id: str,
                         record: Dict[str, Any]) -> None:
        with self._explains_lock:
            for key in (trace_id, request_id):
                if key:
                    self._explains[str(key)] = record
                    self._explains.move_to_end(str(key))
            while len(self._explains) > self.EXPLAIN_CACHE_CAP:
                self._explains.popitem(last=False)

    def lookup_explain(self, key: str) -> Optional[Dict[str, Any]]:
        with self._explains_lock:
            return self._explains.get(str(key))

    # -- health (breaker-fed, ISSUE 9) ---------------------------------------
    def health(self) -> Dict[str, Any]:
        """Liveness from breaker state: a federation is healthy while
        ANY worker process holds a live lease; a pool while ANY replica
        is routable (alive, breaker not open); a single loop while its
        breaker is not open."""
        loop = self.loop
        if hasattr(loop, "workers") and hasattr(loop, "health"):
            # federation plane (ISSUE 15): lease-fed liveness
            return loop.health()
        if hasattr(loop, "replicas"):
            states = {
                str(r.replica_id): (
                    r.breaker.state if r.alive() else "dead"
                )
                for r in loop.replicas
            }
            ok = any(r.routable() for r in loop.replicas)
            return {
                "ok": bool(ok), "replicas": states,
                "queue_depth": len(loop.queue),
                "occupancy": round(loop.occupancy(), 4),
            }
        state = loop.breaker.state
        return {
            "ok": state != "open", "breaker": state,
            "queue_depth": len(loop.queue),
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "GatewayServer":
        if self._thread is None or not self._thread.is_alive():
            self.closing.clear()
            self._thread = spawn(
                self._httpd.serve_forever, name="rca-gateway-accept",
                daemon=True,
            )
        return self

    def close(self) -> None:
        self.closing.set()           # parked subscribers end their streams
        if self._thread is not None:
            # shutdown() parks on serve_forever's exit event — only
            # meaningful while the acceptor is actually running
            self._httpd.shutdown()
            self._thread.join(10.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "GatewayServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
