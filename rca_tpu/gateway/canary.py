"""Replay-driven continuous regression canary (ISSUE 9, `rca canary`).

PR 5 made incidents replayable; this module makes replay a RELEASE GATE.
A canary run has two phases:

1. **Sample**: drive live investigations — streaming sessions and/or
   serve waves over seeded synthetic worlds — with the flight recorder
   attached, at ``RCA_CANARY_SAMPLE_RATE`` (a seeded Bernoulli draw per
   round, so production can trade corpus freshness for record
   overhead).  Each sampled recording is minted into a one-file corpus
   fixture (:func:`rca_tpu.replay.mint_recording`) and stamped into the
   investigation store via ``recording_ref`` — the same replayable-by-id
   plumbing served investigations already carry.

2. **Replay against a candidate**: every minted (or supplied) recording
   re-drives through a CANDIDATE engine — a different build, a perturbed
   scoring config (``--candidate-decay`` etc.), a different engine kind
   — and the run fails on ANY ranking divergence.  For stream
   recordings, :func:`rca_tpu.replay.bisect_divergence` names the exact
   first divergent tick (and dumps both sides' tensors); serve
   recordings name the first divergent request index.

That turns the replay corpus from a static fixture set into a
self-refreshing regression stream (ROADMAP item 5): recordings minted
from today's traffic are the parity oracle tomorrow's candidate must
pass before it ships.  LogGD (PAPERS.md) validates on recorded event
streams rather than live clusters for exactly this reproducibility.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: report lists every recording, but caps the divergence detail it
#: inlines (the dump file carries the full tensors)
_DIVERGENCE_DETAIL_CAP = 8


def build_candidate_engine(
    kind: str = "auto",
    weights: Optional[str] = None,
    decay: Optional[float] = None,
    explain_strength: Optional[float] = None,
    impact_bonus: Optional[float] = None,
) -> Tuple[Optional[object], Dict[str, Any]]:
    """The candidate the corpus replays against.

    With everything defaulted the candidate IS the current build (the
    replayer picks each recording's recorded engine kind) — that is the
    CI shape: yesterday's recordings gate today's tree.  Any override
    builds an explicit engine: ``kind`` forces single/sharded,
    ``weights`` loads a checkpoint, and the three scalar knobs perturb
    the scoring params (which is also how the tests plant a divergence
    the bisect must localize)."""
    overrides = {
        key: value for key, value in (
            ("decay", decay),
            ("explain_strength", explain_strength),
            ("impact_bonus", impact_bonus),
        ) if value is not None
    }
    info: Dict[str, Any] = {"kind": kind, "weights": weights,
                            "param_overrides": overrides}
    if kind == "auto" and weights is None and not overrides:
        info["note"] = "current build, recorded engine kind"
        return None, info
    from rca_tpu.config import RCAConfig
    from rca_tpu.engine.runner import GraphEngine, resolve_params

    params = None
    if weights:
        from rca_tpu.engine.train import load_params

        params = load_params(weights)
    base = resolve_params(RCAConfig.from_env(), params)
    if overrides:
        base = dataclasses.replace(base, **overrides)
    if kind == "sharded":
        from rca_tpu.engine.sharded_runner import ShardedGraphEngine

        return ShardedGraphEngine(params=base), info
    return GraphEngine(params=base), info


# -- sampling ----------------------------------------------------------------

def _sample_stream(tmp: str, out_path: str, ticks: int, services: int,
                   seed: int, k: int) -> Dict[str, Any]:
    """One recorded streaming investigation, minted to ``out_path``."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder, mint_recording

    world = synthetic_cascade_world(services, n_roots=1, seed=seed)
    recorder = Recorder(os.path.join(tmp, "stream"), mode="stream")
    session = LiveStreamingSession(
        MockClusterClient(world), "synthetic", k=k,
        topology_check_every=10, recorder=recorder,
    )
    rng = np.random.default_rng(seed)
    for t in range(ticks):
        if t % 3 == 0:
            # journaled churn so the recording carries real deltas, not
            # an all-quiet tape
            i = int(rng.integers(0, services))
            name = f"pod-svc-{i:05d}" if services > 5 else "pod-0"
            world.touch("pod_metrics", "synthetic", name)
        session.poll()
    recorder.close()
    stats = mint_recording(recorder.path, out_path)
    return {"mode": "stream", "ticks": stats["ticks"]}


def _sample_multicluster(tmp: str, out_path: str, ticks: int,
                         services: int, seed: int, k: int,
                         clusters: int = 3) -> Dict[str, Any]:
    """One recorded streaming investigation over a MERGED multi-cluster
    world (ISSUE 17): ``clusters`` synthetic member worlds behind one
    :class:`~rca_tpu.cluster.clusterset.MergedClusterClient`, captured
    through the live columnar adapter with cluster-prefixed names and
    cluster-local service edges.  The minted recording carries merged
    frames — committing one puts the federation path under the
    permanent corpus gate."""
    from rca_tpu.cluster.clusterset import ClusterSet
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder, mint_recording

    worlds = {
        f"c{j}": synthetic_cascade_world(
            services, n_roots=1, seed=seed + j
        )
        for j in range(int(clusters))
    }
    cset = ClusterSet({
        cid: MockClusterClient(w) for cid, w in worlds.items()
    })
    merged = cset.merged_client()
    recorder = Recorder(os.path.join(tmp, "multicluster"), mode="stream")
    session = LiveStreamingSession(
        merged, "synthetic", k=k,
        topology_check_every=10, recorder=recorder,
    )
    rng = np.random.default_rng(seed)
    cids = sorted(worlds)
    for t in range(ticks):
        if t % 3 == 0:
            # churn lands in a different member each time — merged
            # frames must interleave cluster-prefixed deltas
            cid = cids[t // 3 % len(cids)]
            i = int(rng.integers(0, services))
            name = f"pod-svc-{i:05d}" if services > 5 else "pod-0"
            worlds[cid].touch("pod_metrics", "synthetic", name)
        session.poll()
    recorder.close()
    merged.close()
    stats = mint_recording(recorder.path, out_path)
    return {"mode": "multicluster", "clusters": int(clusters),
            "ticks": stats["ticks"]}


def _sample_gateway(tmp: str, out_path: str, url: str, requests: int,
                    services: int, seed: int, k: int,
                    token: Optional[str] = None,
                    ca_file: Optional[str] = None) -> Dict[str, Any]:
    """One serve wave sampled THROUGH a RUNNING gateway (ISSUE 15, PR
    9's named leftover) instead of an in-process loop — so the live
    plane behind that gateway (a pool, a whole federation) is what
    minted the rankings.  The canary itself writes the serve frames:
    it knows the exact inputs it sent and the rankings that came back,
    and a serve frame is self-contained by design (PR 5), so the minted
    recording replays against a candidate exactly like an in-process
    one — the federation path now feeds the regression corpus too."""
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.gateway.client import GatewayClient
    from rca_tpu.replay import Recorder, mint_recording
    from rca_tpu.serve.request import ServeRequest

    client = GatewayClient.from_url(url, token=token, ca_file=ca_file)
    case = synthetic_cascade_arrays(services, n_roots=1, seed=seed)
    rng = np.random.default_rng(seed)
    recorder = Recorder(os.path.join(tmp, "gateway"), mode="serve")
    sampled = 0
    statuses: Dict[str, int] = {}
    for i in range(requests):
        feats = np.clip(
            case.features + rng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32),
            0, 1,
        )
        code, body, _hdrs = client.analyze(
            feats, case.dep_src, case.dep_dst, names=case.names, k=k,
            tenant=None if token else f"canary-{i % 2}", retries=2,
        )
        status = str(body.get("status", f"http_{code}"))
        statuses[status] = statuses.get(status, 0) + 1
        if code == 200 and status == "ok":
            # a local ServeRequest twin of what went over the wire: the
            # arrays are bit-identical (float32→JSON→float32 identity)
            req = ServeRequest(
                tenant=str(body.get("tenant") or "canary"),
                features=feats, dep_src=case.dep_src,
                dep_dst=case.dep_dst, names=case.names, k=k,
            )
            recorder.record_serve(req, [dict(r) for r in body["ranked"]])
            sampled += 1
    recorder.close()
    if sampled == 0:
        raise RuntimeError(
            f"gateway canary: no ok responses from {url} "
            f"({statuses}) — nothing to mint"
        )
    stats = mint_recording(recorder.path, out_path)
    return {"mode": "gateway", "url": url, "requests": stats["serve"],
            "statuses": statuses}


def _sample_serve(tmp: str, out_path: str, requests: int, services: int,
                  seed: int, k: int) -> Dict[str, Any]:
    """One recorded serve wave, minted to ``out_path``."""
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.replay import Recorder, mint_recording
    from rca_tpu.serve import ServeClient, ServeLoop

    case = synthetic_cascade_arrays(services, n_roots=1, seed=seed)
    rng = np.random.default_rng(seed)
    recorder = Recorder(os.path.join(tmp, "serve"), mode="serve")
    loop = ServeLoop(engine=GraphEngine(), recorder=recorder)
    with loop:
        client = ServeClient(loop)
        reqs = [
            client.submit(
                np.clip(
                    case.features + rng.uniform(
                        0, 0.05, case.features.shape
                    ).astype(np.float32),
                    0, 1,
                ),
                case.dep_src, case.dep_dst, names=case.names,
                tenant=f"canary-{i % 2}", k=k,
            )
            for i in range(requests)
        ]
        for r in reqs:
            r.result(120.0)
    recorder.close()
    stats = mint_recording(recorder.path, out_path)
    return {"mode": "serve", "requests": stats["serve"]}


# -- replay gate -------------------------------------------------------------

def _replay_one(path: str, engine) -> Dict[str, Any]:
    """Replay one recording against the candidate; on stream divergence,
    bisect to the exact tick."""
    from rca_tpu.replay import (
        bisect_divergence,
        load_recording,
        replay_serve,
        replay_stream,
    )

    rec = load_recording(path)
    entry: Dict[str, Any] = {"recording": str(path), "mode": rec.mode}
    if rec.mode == "serve":
        report = replay_serve(path, engine=engine)
        entry["requests"] = report["requests_recorded"]
        entry["parity_ok"] = bool(report["parity_ok"])
        entry["first_divergent_index"] = report["first_divergent_index"]
        return entry
    report = replay_stream(path, engine=engine)
    entry["ticks"] = report["ticks_replayed"]
    entry["parity_ok"] = bool(report["parity_ok"])
    entry["engine_replayed"] = report["engine_replayed"]
    if not report["parity_ok"]:
        # bisect names the EXACT first divergent tick (fresh-session
        # probes; REPLAY.md) and dumps both sides' tensors for diffing
        bisect = bisect_divergence(path, engine=engine)
        entry["first_divergent_tick"] = bisect["first_divergent_tick"]
        entry["probes"] = bisect["probes"]
        entry["dump"] = bisect.get("dump")
    return entry


def run_canary(
    out_dir: str,
    rounds: int = 2,
    ticks: int = 12,
    services: int = 20,
    seed: int = 0,
    sample_rate: Optional[float] = None,
    mode: str = "stream",
    k: int = 5,
    candidate=None,
    candidate_info: Optional[Dict[str, Any]] = None,
    corpus: Optional[List[str]] = None,
    store=None,
    serve_requests: int = 8,
    listen_url: Optional[str] = None,
    token: Optional[str] = None,
    ca_file: Optional[str] = None,
) -> Dict[str, Any]:
    """Sample → mint → replay-against-candidate; ``ok`` iff every
    replayed recording holds bit parity.

    ``mode``: ``stream`` | ``serve`` | ``both`` — what each sampling
    round records.  ``listen_url`` (``rca canary --listen-url``,
    ISSUE 15) points sampling at a RUNNING gateway instead of an
    in-process plane: every round samples real wire traffic (``token``
    / ``ca_file`` for TLS+authn gateways), so a federated plane's
    answers mint the regression corpus too.  ``corpus`` adds
    pre-existing recordings (e.g. minted by an earlier canary, or a
    recorded gateway session) to the replay gate without re-sampling
    them.  ``store`` (an :class:`rca_tpu.store.InvestigationStore`)
    gets one investigation per sampled recording with its
    ``recording_ref`` pointing at the minted file — the corpus is
    replayable by investigation id."""
    if mode not in ("stream", "serve", "both", "multicluster"):
        raise ValueError(
            f"mode must be stream|serve|both|multicluster, got {mode!r}"
        )
    if listen_url is not None:
        mode = "gateway"
    if sample_rate is None:
        from rca_tpu.config import canary_sample_rate

        sample_rate = canary_sample_rate()
    os.makedirs(out_dir, exist_ok=True)
    sampler = random.Random(seed)
    sampled: List[Dict[str, Any]] = []
    skipped = 0
    minted: List[str] = []
    for i in range(int(rounds)):
        # the seeded Bernoulli draw is consumed every round regardless
        # of outcome, so (seed, round) always addresses the same draw
        take = sampler.random() < sample_rate
        if not take:
            skipped += 1
            continue
        legs = ("stream", "serve") if mode == "both" else (mode,)
        for leg in legs:
            out_path = os.path.join(
                out_dir, f"canary-{leg}-{seed}-{i}.rcz"
            )
            tmp = tempfile.mkdtemp(prefix="rca_canary_")
            try:
                if leg == "stream":
                    info = _sample_stream(
                        tmp, out_path, ticks=ticks, services=services,
                        seed=seed + i, k=k,
                    )
                elif leg == "multicluster":
                    info = _sample_multicluster(
                        tmp, out_path, ticks=ticks, services=services,
                        seed=seed + i, k=k,
                    )
                elif leg == "gateway":
                    info = _sample_gateway(
                        tmp, out_path, listen_url,
                        requests=serve_requests, services=services,
                        seed=seed + i, k=k, token=token, ca_file=ca_file,
                    )
                else:
                    info = _sample_serve(
                        tmp, out_path, requests=serve_requests,
                        services=services, seed=seed + i, k=k,
                    )
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            info["recording"] = out_path
            if store is not None:
                inv = store.create_investigation(
                    f"canary {leg} round {i} (seed {seed + i})",
                    namespace="synthetic",
                    recording_ref=out_path,
                )
                info["investigation_id"] = inv["id"]
            sampled.append(info)
            minted.append(out_path)

    results = [
        _replay_one(path, candidate)
        for path in list(minted) + list(corpus or [])
    ]
    divergent = [r for r in results if not r["parity_ok"]]
    first: Optional[Dict[str, Any]] = None
    if divergent:
        d = divergent[0]
        first = {
            "recording": d["recording"],
            **({"tick": d["first_divergent_tick"]}
               if "first_divergent_tick" in d else {}),
            **({"index": d["first_divergent_index"]}
               if d.get("first_divergent_index") is not None else {}),
        }
    return {
        "ok": not divergent,
        "mode": mode,
        "rounds": int(rounds),
        "sample_rate": float(sample_rate),
        "sampled": len(sampled),
        "skipped": skipped,
        "candidate": candidate_info or {
            "kind": "auto", "note": "current build",
        },
        "recordings": results,
        "divergent": [
            r["recording"] for r in divergent[:_DIVERGENCE_DETAIL_CAP]
        ],
        "first_divergence": first,
    }
