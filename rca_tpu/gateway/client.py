"""Wire client: the stdlib-HTTP twin of the in-process ``ServeClient``.

Used by the tests (loopback bit-parity vs in-process submission), the
bench's ``gateway`` section (wire-vs-in-process overhead), and any
out-of-process caller that wants a typed surface instead of raw curl.
One :class:`GatewayClient` holds no connection state between calls —
each request opens, speaks, and closes (HTTP keep-alive is a transport
optimization the parity and backpressure contracts must not depend on).
"""

from __future__ import annotations

import http.client
import json
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple
from urllib.parse import urlsplit

from rca_tpu.gateway.wire import (
    RETRY_AFTER_MS_HEADER,
    TENANT_HEADER,
    encode_analyze,
)
from rca_tpu.observability.spans import TRACE_HEADER


class GatewayClient:
    """``tls=True`` speaks HTTPS; ``ca_file`` pins/verifies the server
    cert (self-signed deployments pass their own cert), without it the
    connection is encrypted but UNverified — loopback test territory.
    ``cert_file``/``key_file`` present this client's certificate to an
    mTLS gateway (``RCA_GATEWAY_TLS_CLIENT_CA``); without them such a
    gateway rejects the handshake.  ``token`` rides every request as
    ``Authorization: Bearer`` for gateways with ``RCA_GATEWAY_TOKENS``
    set.  ``sleeper`` is the injectable delay seam the retry path uses
    (tests pass a recorder)."""

    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 tls: bool = False, ca_file: Optional[str] = None,
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None,
                 token: Optional[str] = None,
                 sleeper: Callable[[float], None] = time.sleep):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.tls = bool(tls)
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file
        self.token = token
        self.sleeper = sleeper

    @classmethod
    def from_url(cls, url: str, **kwargs: Any) -> "GatewayClient":
        """``http(s)://host:port`` → a client (the ``rca canary
        --listen-url`` entry point)."""
        parts = urlsplit(url if "//" in url else f"//{url}")
        scheme = parts.scheme or "http"
        if scheme not in ("http", "https"):
            raise ValueError(f"gateway url {url!r}: scheme must be "
                             "http or https")
        if parts.hostname is None or parts.port is None:
            raise ValueError(f"gateway url {url!r}: want host:port")
        kwargs.setdefault("tls", scheme == "https")
        return cls(parts.hostname, parts.port, **kwargs)

    def _conn(self, timeout_s: Optional[float] = None
              ) -> http.client.HTTPConnection:
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        if self.tls:
            from rca_tpu.util.net import make_tls_client_context

            return http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout,
                context=make_tls_client_context(
                    "gateway-client", self.ca_file,
                    cert_file=self.cert_file, key_file=self.key_file,
                ),
            )
        return http.client.HTTPConnection(
            self.host, self.port, timeout=timeout,
        )

    def _auth(self, headers: Dict[str, str]) -> Dict[str, str]:
        if self.token is not None:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    # -- analyze -------------------------------------------------------------
    @staticmethod
    def retry_delay_s(headers: Dict[str, str]) -> float:
        """The server's backoff hint: the jittered millisecond header
        when present (ISSUE 15 — every client honoring the INTEGER
        Retry-After re-synchronizes the herd onto the same instant),
        else Retry-After seconds, else 1s."""
        ms = headers.get(RETRY_AFTER_MS_HEADER)
        if ms is not None:
            try:
                return max(0.0, float(ms) / 1000.0)
            except ValueError:
                pass
        try:
            return max(0.0, float(headers.get("Retry-After") or 1.0))
        except ValueError:
            return 1.0

    def analyze(
        self,
        features, dep_src, dep_dst,
        names=None, tenant: Optional[str] = None, k: int = 5,
        priority: str = "normal", deadline_ms: Optional[float] = None,
        investigation_id: Optional[str] = None,
        trace: Optional[str] = None,
        retries: int = 0,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """One analyze request over the wire.  Returns ``(http_code,
        body, headers)`` — the caller maps 429/503 to its own backoff
        using the ``Retry-After`` header, exactly as an external load
        balancer would.  ``retries`` > 0 does that here: on 429/503 the
        client sleeps the server's JITTERED hint (see
        :meth:`retry_delay_s`) and resubmits, up to ``retries`` times —
        a shed storm's survivors come back spread out, not as one wave.
        ``trace`` (an ``X-RCA-Trace`` wire value, ``trace_id-span_id``)
        parents the gateway's spans onto the caller's; absent, the
        gateway starts a fresh trace and echoes its id in the response
        headers either way."""
        body = json.dumps(encode_analyze(
            features, dep_src, dep_dst, names=names, k=k,
            priority=priority, deadline_ms=deadline_ms,
            investigation_id=investigation_id,
        )).encode("utf-8")
        headers = self._auth({"Content-Type": "application/json"})
        if tenant is not None:
            headers[TENANT_HEADER] = tenant
        if trace is not None:
            headers[TRACE_HEADER] = trace
        attempts = max(0, int(retries)) + 1
        for attempt in range(attempts):
            conn = self._conn()
            try:
                conn.request("POST", "/v1/analyze", body=body,
                             headers=headers)
                resp = conn.getresponse()
                payload = json.loads(resp.read().decode("utf-8"))
                out = resp.status, payload, dict(resp.getheaders())
            finally:
                conn.close()
            if out[0] not in (429, 503) or attempt + 1 >= attempts:
                return out
            self.sleeper(self.retry_delay_s(out[2]))
        return out  # pragma: no cover - loop always returns

    # -- streaming subscription ----------------------------------------------
    def subscribe(
        self,
        tenant: Optional[str] = None,
        max_events: int = 0,
        idle_s: float = 30.0,
        timeout_s: Optional[float] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Yield served-response events as they stream (chunked NDJSON).
        Ends after ``max_events`` (0 = server default/unbounded), after
        ``idle_s`` with no event, or when the gateway shuts down."""
        query = f"/v1/subscribe?idle_s={idle_s}"
        if tenant is not None:
            query += f"&tenant={tenant}"
        if max_events:
            query += f"&max={int(max_events)}"
        conn = self._conn(timeout_s)
        try:
            conn.request("GET", query, headers=self._auth({}))
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(
                    f"subscribe: HTTP {resp.status}: "
                    f"{resp.read(256)!r}"
                )
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if line:
                    yield json.loads(line.decode("utf-8"))
        finally:
            conn.close()

    # -- observability endpoints ---------------------------------------------
    def metrics_text(self) -> str:
        conn = self._conn()
        try:
            conn.request("GET", "/metrics", headers=self._auth({}))
            resp = conn.getresponse()
            return resp.read().decode("utf-8")
        finally:
            conn.close()

    def traces(
        self,
        trace_id: Optional[str] = None,
        max_spans: int = 1000,
        fmt: str = "ndjson",
    ):
        """``GET /v1/traces``: the span buffer — a list of span dicts
        (NDJSON decoded), or the Chrome trace object with
        ``fmt="chrome"``."""
        query = f"/v1/traces?max={int(max_spans)}&format={fmt}"
        if trace_id is not None:
            query += f"&trace_id={trace_id}"
        conn = self._conn()
        try:
            conn.request("GET", query, headers=self._auth({}))
            resp = conn.getresponse()
            raw = resp.read().decode("utf-8")
            if resp.status != 200:
                raise RuntimeError(f"traces: HTTP {resp.status}: {raw!r}")
            if fmt == "chrome":
                return json.loads(raw)
            return [
                json.loads(line) for line in raw.splitlines() if line
            ]
        finally:
            conn.close()

    def healthz(self) -> Tuple[int, Dict[str, Any]]:
        conn = self._conn()
        try:
            conn.request("GET", "/healthz", headers=self._auth({}))
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode("utf-8"))
        finally:
            conn.close()
