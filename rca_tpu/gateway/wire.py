"""Wire vocabulary: JSON ⇄ the serve contract, and the HTTP status map.

One module owns both directions of the translation so the server and the
client cannot drift: a request body decodes into exactly the kwargs
:meth:`rca_tpu.serve.client.ServeClient.submit` takes, and a
:class:`rca_tpu.serve.request.ServeResponse` encodes into the body the
client hands back.

**Bit parity across the wire.**  Feature matrices travel as nested JSON
lists.  Every float32 converts EXACTLY to a Python float (float64), JSON
serializes float64 round-trippably (`repr` shortest-form), and the
server re-narrows to float32 — so ``float32 → JSON → float32`` is the
identity and a request submitted over loopback produces the same
ranking bits as the same arrays submitted in process (gated by
``tests/test_gateway.py``).

**Honest backpressure** (the status map, SERVING.md §Gateway): the serve
contract's five outcomes surface as HTTP codes the edge can act on —

=============  ====  =============================================
serve status   HTTP  semantics on the wire
=============  ====  =============================================
``ok``          200  ranking served (``degraded: false``)
``degraded``    200  LAST-KNOWN ranking, ``degraded: true`` — the
                     caller decides what staleness means
``queue_full``  429  admission rejected; ``Retry-After`` carries the
                     suggested backoff
``shed``        503  deadline expired before a device slot;
                     ``Retry-After`` set
``error``       500  device path failed with no last-known ranking
(timeout)       504  the gateway's own wait bound expired
=============  ====  =============================================
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from rca_tpu.serve.request import (
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    ServeResponse,
)

#: tenant tagging header.  Auth-less by default (ISSUE 9); with
#: ``RCA_GATEWAY_TOKENS`` set (ISSUE 15) the bearer token BINDS the
#: tenant and a mismatching header is a spoof attempt (403)
TENANT_HEADER = "X-RCA-Tenant"
DEFAULT_TENANT = "default"

#: Retry-After seconds suggested on 429/503 — queue pressure on this
#: scheduler drains in well under a second; 1s is the floor HTTP allows
RETRY_AFTER_S = 1

#: millisecond-precision jittered retry hint (ISSUE 15 small fix): the
#: integer Retry-After header resynchronizes every shed client onto the
#: same retry instant; this companion header carries the seeded-jitter
#: delay our GatewayClient honors, defeating the thundering herd while
#: the standard header stays spec-shaped for everyone else
RETRY_AFTER_MS_HEADER = "X-RCA-Retry-After-Ms"

_PRIORITIES = {
    "high": PRIORITY_HIGH, "normal": PRIORITY_NORMAL,
    "batch": PRIORITY_BATCH,
}


class WireError(ValueError):
    """A malformed request body — the server answers 400 with the text."""


def _require(body: Dict[str, Any], key: str) -> Any:
    if key not in body:
        raise WireError(f"missing required field {key!r}")
    return body[key]


def _array(body: Dict[str, Any], key: str, dtype, ndim: int) -> np.ndarray:
    try:
        arr = np.asarray(_require(body, key), dtype=dtype)
    except (TypeError, ValueError) as exc:
        raise WireError(f"field {key!r}: not a numeric array ({exc})")
    if arr.ndim != ndim:
        raise WireError(
            f"field {key!r}: expected {ndim}-d array, got shape "
            f"{list(arr.shape)}"
        )
    return arr


def decode_analyze(body: Dict[str, Any],
                   header_tenant: Optional[str] = None) -> Dict[str, Any]:
    """A ``POST /v1/analyze`` JSON body → ``ServeClient.submit`` kwargs.

    The tenant header wins over any body field (the header is the wire's
    tagging surface; a body tenant is accepted for curl convenience).
    Raises :class:`WireError` on anything malformed — the server maps
    that to 400 without touching the scheduler."""
    if not isinstance(body, dict):
        raise WireError("request body must be a JSON object")
    features = _array(body, "features", np.float32, 2)
    dep_src = _array(body, "dep_src", np.int32, 1)
    dep_dst = _array(body, "dep_dst", np.int32, 1)
    if len(dep_src) != len(dep_dst):
        raise WireError("dep_src and dep_dst must have equal length")
    names = body.get("names")
    if names is not None:
        if not isinstance(names, list) or not all(
            isinstance(n, str) for n in names
        ):
            raise WireError("field 'names': expected a list of strings")
    priority = body.get("priority", "normal")
    if priority not in _PRIORITIES:
        raise WireError(
            f"field 'priority': expected one of {sorted(_PRIORITIES)}, "
            f"got {priority!r}"
        )
    deadline_ms = body.get("deadline_ms")
    if deadline_ms is not None and not isinstance(
        deadline_ms, (int, float)
    ):
        raise WireError("field 'deadline_ms': expected a number")
    k = body.get("k", 5)
    if not isinstance(k, int) or k < 1:
        raise WireError("field 'k': expected a positive integer")
    tenant = header_tenant or body.get("tenant") or DEFAULT_TENANT
    if not isinstance(tenant, str) or not tenant:
        raise WireError("tenant must be a non-empty string")
    inv = body.get("investigation_id")
    if inv is not None and not isinstance(inv, str):
        raise WireError("field 'investigation_id': expected a string")
    explain = body.get("explain", False)
    if not isinstance(explain, bool):
        raise WireError("field 'explain': expected a boolean")
    return {
        "features": features, "dep_src": dep_src, "dep_dst": dep_dst,
        "names": names, "tenant": tenant, "k": k,
        "priority": _PRIORITIES[priority],
        "deadline_ms": float(deadline_ms) if deadline_ms is not None
        else None,
        "investigation_id": inv,
        "explain": explain,
    }


def encode_analyze(
    features, dep_src, dep_dst,
    names=None, tenant: Optional[str] = None, k: int = 5,
    priority: str = "normal", deadline_ms: Optional[float] = None,
    investigation_id: Optional[str] = None,
    explain: bool = False,
) -> Dict[str, Any]:
    """Client-side twin of :func:`decode_analyze`: arrays → the JSON
    body.  ``tolist()`` converts float32 → exact float64, which JSON
    round-trips — see the module docstring's parity argument."""
    body: Dict[str, Any] = {
        "features": np.asarray(features, np.float32).tolist(),
        "dep_src": np.asarray(dep_src, np.int32).tolist(),
        "dep_dst": np.asarray(dep_dst, np.int32).tolist(),
        "k": int(k),
        "priority": priority,
    }
    if names is not None:
        body["names"] = list(names)
    if tenant is not None:
        body["tenant"] = tenant
    if deadline_ms is not None:
        body["deadline_ms"] = float(deadline_ms)
    if investigation_id is not None:
        body["investigation_id"] = investigation_id
    if explain:
        body["explain"] = True
    return body


def response_body(resp: ServeResponse) -> Dict[str, Any]:
    """A :class:`ServeResponse` → the JSON body both the analyze reply
    and the subscription stream carry."""
    out = {
        "status": resp.status,
        "request_id": resp.request_id,
        "tenant": resp.tenant,
        "ranked": resp.ranked,
        "degraded": resp.status == "degraded",
        "detail": resp.detail,
        "queue_ms": resp.queue_ms,
        "batch_size": resp.batch_size,
        "deadline_missed": bool(resp.deadline_missed),
        "engine": getattr(resp.result, "engine", None),
    }
    if getattr(resp, "provenance", None) is not None:
        # causelens (ISSUE 14): the attribution rides the body only for
        # requests that asked (?explain=1 / "explain": true)
        out["provenance"] = resp.provenance
    return out


def status_code_for(status: str) -> Tuple[int, Optional[int]]:
    """serve status → ``(http_code, retry_after_s | None)`` — the honest
    backpressure map in the module docstring."""
    if status in ("ok", "degraded"):
        return 200, None
    if status == "queue_full":
        return 429, RETRY_AFTER_S
    if status == "shed":
        return 503, RETRY_AFTER_S
    if status == "error":
        return 500, None
    return 500, None
