"""`/metrics` text exposition: ServeMetrics + gateway counters.

Prometheus text format (``# TYPE`` lines + ``name{labels} value``), built
from two CONSISTENT snapshots — :meth:`rca_tpu.serve.metrics.ServeMetrics.
summary` (one lock-guarded copy of the whole serving plane: per-tenant
counters, per-replica rows, cache events) and the gateway's own HTTP
counters — so a scrape never interleaves with the replicas mutating the
live accumulators (ISSUE 9's snapshot-consistency fix).

ISSUE 11 brings the exposition up to proper format: gauges carry a
millisecond timestamp (``name{labels} value ts`` — a scraped gauge
without one is a point with no WHEN), and per-tenant request durations
export as a real ``histogram`` family (``rca_request_duration_seconds``
with cumulative ``le`` buckets + ``_sum``/``_count``) next to the SLO
burn counters — burn rate is then one PromQL division away, which
quantile gauges could never give a scraper.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_TENANT_COUNTERS = (
    "submitted", "answered", "shed", "rejected", "degraded", "errors",
)


def _esc(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _line(out: List[str], name: str, value, ts: Optional[int] = None,
          **labels) -> None:
    if value is None:
        return
    suffix = f" {ts}" if ts is not None else ""
    if labels:
        lab = ",".join(
            f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
        )
        out.append(f"{name}{{{lab}}} {value}{suffix}")
    else:
        out.append(f"{name} {value}{suffix}")


def _head(out: List[str], name: str, kind: str, help_: str) -> None:
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} {kind}")


def render_metrics_text(
    serve_summary: Dict[str, Any],
    gateway: Optional[Dict[str, Any]] = None,
    healthy: Optional[bool] = None,
    now_ms: Optional[int] = None,
    kernelscope: Optional[Dict[str, Any]] = None,
) -> str:
    """The full exposition body (text/plain; version=0.0.4).
    ``now_ms`` (ms since epoch, from the gateway's wall seam) stamps
    every GAUGE sample; counters stay timestamp-free per convention.
    ``kernelscope`` (ISSUE 12) is the plane's
    ``kernelscope_summary()``: recompile counters, the device-memory
    sample, and the per-shape kernel-registry rows."""
    out: List[str] = []

    if kernelscope is not None:
        _head(out, "rca_recompiles_total", "counter",
              "post-warmup XLA compilations of already-compiled "
              "signatures on the serve path (kernelscope watchdog)")
        _line(out, "rca_recompiles_total",
              kernelscope.get("recompiles", 0))
        _head(out, "rca_compiles_total", "counter",
              "XLA compilations observed since the plane started")
        _line(out, "rca_compiles_total", kernelscope.get("compiles", 0))
        mem = kernelscope.get("device_memory") or {}
        if mem:
            _head(out, "rca_device_bytes_in_use", "gauge",
                  "device memory in use (allocator stats where the "
                  "backend reports them, else the live-buffer total)")
            _line(out, "rca_device_bytes_in_use",
                  mem.get("bytes_in_use"), ts=now_ms)
            for dev, rec in sorted((mem.get("devices") or {}).items()):
                _line(out, "rca_device_bytes_in_use",
                      rec.get("bytes_in_use"), ts=now_ms, device=dev)
            _head(out, "rca_device_live_buffers", "gauge",
                  "live jax.Array buffers in the process")
            _line(out, "rca_device_live_buffers",
                  mem.get("live_buffers"), ts=now_ms)
        rows = kernelscope.get("kernel_registry") or []
        if rows:
            _head(out, "rca_kernel_winner_info", "gauge",
                  "1 for the engaged kernel per padded shape "
                  "(engine/registry.py — the dispatch seam)")
            for row in rows:
                _line(out, "rca_kernel_winner_info", 1, ts=now_ms,
                      n_pad=str(row["n_pad"]), variant=row["variant"],
                      kernel=row["winner"], source=row["source"])
            _head(out, "rca_kernel_cost_flops", "gauge",
                  "XLA cost analysis of the winner executable per shape "
                  "(captured at compile time; absent until captured)")
            for row in rows:
                cost = row.get("cost") or {}
                _line(out, "rca_kernel_cost_flops", cost.get("flops"),
                      ts=now_ms, n_pad=str(row["n_pad"]),
                      variant=row["variant"])
            _head(out, "rca_kernel_cost_bytes_accessed", "gauge",
                  "bytes accessed per winner executable per shape")
            for row in rows:
                cost = row.get("cost") or {}
                _line(out, "rca_kernel_cost_bytes_accessed",
                      cost.get("bytes_accessed"), ts=now_ms,
                      n_pad=str(row["n_pad"]), variant=row["variant"])
            _head(out, "rca_kernel_peak_temp_bytes", "gauge",
                  "peak temp memory of the winner executable per shape")
            for row in rows:
                cost = row.get("cost") or {}
                _line(out, "rca_kernel_peak_temp_bytes",
                      cost.get("peak_temp_bytes"), ts=now_ms,
                      n_pad=str(row["n_pad"]), variant=row["variant"])

    _head(out, "rca_serve_requests_total", "counter",
          "serve outcomes per tenant")
    tenants = serve_summary.get("tenants", {})
    for tenant, rec in sorted(tenants.items()):
        for key in _TENANT_COUNTERS:
            _line(out, "rca_serve_requests_total", rec.get(key, 0),
                  tenant=tenant, outcome=key)

    _head(out, "rca_serve_queue_ms", "gauge",
          "per-tenant time-in-queue quantiles (ms)")
    for tenant, rec in sorted(tenants.items()):
        _line(out, "rca_serve_queue_ms", rec.get("queue_ms_p50"),
              ts=now_ms, tenant=tenant, quantile="0.5")
        _line(out, "rca_serve_queue_ms", rec.get("queue_ms_p99"),
              ts=now_ms, tenant=tenant, quantile="0.99")

    # per-tenant duration histogram + SLO burn (ISSUE 11): proper
    # cumulative le buckets so burn rate / latency SLIs are PromQL
    duration = serve_summary.get("duration") or {}
    if duration:
        _head(out, "rca_request_duration_seconds", "histogram",
              "submit-to-completion request duration per tenant")
        for tenant, hist in sorted(duration.items()):
            for le, n in hist.get("buckets", {}).items():
                _line(out, "rca_request_duration_seconds_bucket", n,
                      tenant=tenant, le=le)
            _line(out, "rca_request_duration_seconds_bucket",
                  hist.get("count", 0), tenant=tenant, le="+Inf")
            _line(out, "rca_request_duration_seconds_sum",
                  hist.get("sum_s", 0.0), tenant=tenant)
            _line(out, "rca_request_duration_seconds_count",
                  hist.get("count", 0), tenant=tenant)
    breaches = serve_summary.get("slo_breaches")
    if breaches is not None:
        _head(out, "rca_slo_breaches_total", "counter",
              "completions over RCA_SLO_MS (or failed) per tenant")
        for tenant, n in sorted(breaches.items()):
            _line(out, "rca_slo_breaches_total", n, tenant=tenant)
    if serve_summary.get("slo_ms") is not None:
        _head(out, "rca_slo_target_ms", "gauge",
              "the configured per-request latency SLO (RCA_SLO_MS)")
        _line(out, "rca_slo_target_ms", serve_summary["slo_ms"],
              ts=now_ms)

    _head(out, "rca_serve_resident_delta_requests_total", "counter",
          "requests served via the resident delta path, per tenant")
    for tenant, rec in sorted(tenants.items()):
        _line(out, "rca_serve_resident_delta_requests_total",
              rec.get("resident_delta_requests", 0), tenant=tenant)

    _head(out, "rca_explain_requests_total", "counter",
          "requests served with a causelens attribution "
          "(ServeRequest.explain / ?explain=1 — ISSUE 14)")
    for tenant, rec in sorted(tenants.items()):
        _line(out, "rca_explain_requests_total",
              rec.get("explain_requests", 0), tenant=tenant)

    _head(out, "rca_serve_batches_total", "counter",
          "device batches dispatched")
    _line(out, "rca_serve_batches_total", serve_summary.get("batches", 0))
    _head(out, "rca_serve_dispatched_requests_total", "counter",
          "requests that rode a device batch")
    _line(out, "rca_serve_dispatched_requests_total",
          serve_summary.get("dispatched_requests", 0))
    _head(out, "rca_serve_queue_depth_peak", "gauge",
          "peak queue depth observed at admission")
    _line(out, "rca_serve_queue_depth_peak",
          serve_summary.get("queue_depth_peak", 0), ts=now_ms)

    _head(out, "rca_serve_graph_cache_events_total", "counter",
          "prepared-graph cache events")
    for event, n in sorted(
        (serve_summary.get("graph_cache") or {}).items()
    ):
        _line(out, "rca_serve_graph_cache_events_total", n, event=event)

    replicas = serve_summary.get("replicas") or {}
    if replicas:
        _head(out, "rca_serve_replica_batches_total", "counter",
              "device batches fetched OK per replica")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_batches_total",
                  rec.get("batches", 0), replica=rid)
        _head(out, "rca_serve_replica_requests_total", "counter",
              "requests served per replica")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_requests_total",
                  rec.get("requests", 0), replica=rid)
        _head(out, "rca_serve_replica_stolen_total", "counter",
              "work-steal moves per replica and direction")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_stolen_total",
                  rec.get("stolen_from", 0), replica=rid,
                  direction="from")
            _line(out, "rca_serve_replica_stolen_total",
                  rec.get("stolen_to", 0), replica=rid, direction="to")
        _head(out, "rca_serve_replica_state", "gauge",
              "1 for the replica's current breaker/liveness state")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_state", 1, ts=now_ms, replica=rid,
                  state=str(rec.get("state", "closed")))
        _head(out, "rca_serve_replica_occupancy", "gauge",
              "per-replica occupancy quantiles (staged + in flight)")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_occupancy",
                  rec.get("occupancy_p50"), ts=now_ms, replica=rid,
                  quantile="0.5")
            _line(out, "rca_serve_replica_occupancy",
                  rec.get("occupancy_max"), ts=now_ms, replica=rid,
                  quantile="1.0")

    if gateway is not None:
        _head(out, "rca_gateway_requests_total", "counter",
              "gateway HTTP responses by route and code")
        for (route, code), n in sorted(gateway.get("requests", {}).items()):
            _line(out, "rca_gateway_requests_total", n, route=route,
                  code=str(code))
        _head(out, "rca_gateway_request_ms", "gauge",
              "gateway request latency quantiles (ms) by route")
        for route, rec in sorted(gateway.get("latency", {}).items()):
            _line(out, "rca_gateway_request_ms", rec.get("p50"),
                  ts=now_ms, route=route, quantile="0.5")
            _line(out, "rca_gateway_request_ms", rec.get("p99"),
                  ts=now_ms, route=route, quantile="0.99")
        _head(out, "rca_gateway_streams_opened_total", "counter",
              "tick subscriptions opened")
        _line(out, "rca_gateway_streams_opened_total",
              gateway.get("streams_opened", 0))
        _head(out, "rca_gateway_stream_events_total", "counter",
              "tick events delivered to subscribers")
        _line(out, "rca_gateway_stream_events_total",
              gateway.get("stream_events", 0))
        _head(out, "rca_gateway_body_rejections_total", "counter",
              "requests refused for exceeding RCA_GATEWAY_MAX_BODY")
        _line(out, "rca_gateway_body_rejections_total",
              gateway.get("body_rejections", 0))
        _head(out, "rca_gateway_rate_limited_total", "counter",
              "requests refused by the per-tenant token bucket "
              "(RCA_GATEWAY_TENANT_RPS)")
        _line(out, "rca_gateway_rate_limited_total",
              gateway.get("rate_limited", 0))

    if healthy is not None:
        _head(out, "rca_gateway_up", "gauge",
              "1 while the serving plane is routable")
        _line(out, "rca_gateway_up", 1 if healthy else 0, ts=now_ms)

    return "\n".join(out) + "\n"
