"""`/metrics` text exposition: ServeMetrics + gateway counters.

Prometheus text format (``# TYPE`` lines + ``name{labels} value``), built
from two CONSISTENT snapshots — :meth:`rca_tpu.serve.metrics.ServeMetrics.
summary` (one lock-guarded copy of the whole serving plane: per-tenant
counters, per-replica rows, cache events) and the gateway's own HTTP
counters — so a scrape never interleaves with the replicas mutating the
live accumulators (ISSUE 9's snapshot-consistency fix).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_TENANT_COUNTERS = (
    "submitted", "answered", "shed", "rejected", "degraded", "errors",
)


def _esc(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _line(out: List[str], name: str, value, **labels) -> None:
    if value is None:
        return
    if labels:
        lab = ",".join(
            f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
        )
        out.append(f"{name}{{{lab}}} {value}")
    else:
        out.append(f"{name} {value}")


def _head(out: List[str], name: str, kind: str, help_: str) -> None:
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} {kind}")


def render_metrics_text(
    serve_summary: Dict[str, Any],
    gateway: Optional[Dict[str, Any]] = None,
    healthy: Optional[bool] = None,
) -> str:
    """The full exposition body (text/plain; version=0.0.4)."""
    out: List[str] = []

    _head(out, "rca_serve_requests_total", "counter",
          "serve outcomes per tenant")
    tenants = serve_summary.get("tenants", {})
    for tenant, rec in sorted(tenants.items()):
        for key in _TENANT_COUNTERS:
            _line(out, "rca_serve_requests_total", rec.get(key, 0),
                  tenant=tenant, outcome=key)

    _head(out, "rca_serve_queue_ms", "gauge",
          "per-tenant time-in-queue quantiles (ms)")
    for tenant, rec in sorted(tenants.items()):
        _line(out, "rca_serve_queue_ms", rec.get("queue_ms_p50"),
              tenant=tenant, quantile="0.5")
        _line(out, "rca_serve_queue_ms", rec.get("queue_ms_p99"),
              tenant=tenant, quantile="0.99")

    _head(out, "rca_serve_resident_delta_requests_total", "counter",
          "requests served via the resident delta path, per tenant")
    for tenant, rec in sorted(tenants.items()):
        _line(out, "rca_serve_resident_delta_requests_total",
              rec.get("resident_delta_requests", 0), tenant=tenant)

    _head(out, "rca_serve_batches_total", "counter",
          "device batches dispatched")
    _line(out, "rca_serve_batches_total", serve_summary.get("batches", 0))
    _head(out, "rca_serve_dispatched_requests_total", "counter",
          "requests that rode a device batch")
    _line(out, "rca_serve_dispatched_requests_total",
          serve_summary.get("dispatched_requests", 0))
    _head(out, "rca_serve_queue_depth_peak", "gauge",
          "peak queue depth observed at admission")
    _line(out, "rca_serve_queue_depth_peak",
          serve_summary.get("queue_depth_peak", 0))

    _head(out, "rca_serve_graph_cache_events_total", "counter",
          "prepared-graph cache events")
    for event, n in sorted(
        (serve_summary.get("graph_cache") or {}).items()
    ):
        _line(out, "rca_serve_graph_cache_events_total", n, event=event)

    replicas = serve_summary.get("replicas") or {}
    if replicas:
        _head(out, "rca_serve_replica_batches_total", "counter",
              "device batches fetched OK per replica")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_batches_total",
                  rec.get("batches", 0), replica=rid)
        _head(out, "rca_serve_replica_requests_total", "counter",
              "requests served per replica")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_requests_total",
                  rec.get("requests", 0), replica=rid)
        _head(out, "rca_serve_replica_stolen_total", "counter",
              "work-steal moves per replica and direction")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_stolen_total",
                  rec.get("stolen_from", 0), replica=rid,
                  direction="from")
            _line(out, "rca_serve_replica_stolen_total",
                  rec.get("stolen_to", 0), replica=rid, direction="to")
        _head(out, "rca_serve_replica_state", "gauge",
              "1 for the replica's current breaker/liveness state")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_state", 1, replica=rid,
                  state=str(rec.get("state", "closed")))
        _head(out, "rca_serve_replica_occupancy", "gauge",
              "per-replica occupancy quantiles (staged + in flight)")
        for rid, rec in sorted(replicas.items()):
            _line(out, "rca_serve_replica_occupancy",
                  rec.get("occupancy_p50"), replica=rid, quantile="0.5")
            _line(out, "rca_serve_replica_occupancy",
                  rec.get("occupancy_max"), replica=rid, quantile="1.0")

    if gateway is not None:
        _head(out, "rca_gateway_requests_total", "counter",
              "gateway HTTP responses by route and code")
        for (route, code), n in sorted(gateway.get("requests", {}).items()):
            _line(out, "rca_gateway_requests_total", n, route=route,
                  code=str(code))
        _head(out, "rca_gateway_request_ms", "gauge",
              "gateway request latency quantiles (ms) by route")
        for route, rec in sorted(gateway.get("latency", {}).items()):
            _line(out, "rca_gateway_request_ms", rec.get("p50"),
                  route=route, quantile="0.5")
            _line(out, "rca_gateway_request_ms", rec.get("p99"),
                  route=route, quantile="0.99")
        _head(out, "rca_gateway_streams_opened_total", "counter",
              "tick subscriptions opened")
        _line(out, "rca_gateway_streams_opened_total",
              gateway.get("streams_opened", 0))
        _head(out, "rca_gateway_stream_events_total", "counter",
              "tick events delivered to subscribers")
        _line(out, "rca_gateway_stream_events_total",
              gateway.get("stream_events", 0))
        _head(out, "rca_gateway_body_rejections_total", "counter",
              "requests refused for exceeding RCA_GATEWAY_MAX_BODY")
        _line(out, "rca_gateway_body_rejections_total",
              gateway.get("body_rejections", 0))
        _head(out, "rca_gateway_rate_limited_total", "counter",
              "requests refused by the per-tenant token bucket "
              "(RCA_GATEWAY_TENANT_RPS)")
        _line(out, "rca_gateway_rate_limited_total",
              gateway.get("rate_limited", 0))

    if healthy is not None:
        _head(out, "rca_gateway_up", "gauge",
              "1 while the serving plane is routable")
        _line(out, "rca_gateway_up", 1 if healthy else 0)

    return "\n".join(out) + "\n"
