"""Topology construction: snapshot → typed COO/CSR arrays.

Array-native replacement for the reference's networkx DiGraph topology
(reference: agents/topology_agent.py:94-260) — same edge semantics
(selects / routes / mounts / env_from / env_var / depends_on), emitted as
index arrays so the engine can propagate on device, plus deterministic
analyses (cycles, longest chain, SPOF, isolated nodes) reimplemented on the
array form with better asymptotics.
"""

from rca_tpu.graph.build import (
    EdgeType,
    NodeType,
    TypedGraph,
    build_typed_graph,
    service_dependency_edges,
)
from rca_tpu.graph.analysis import (
    betweenness_centrality,
    find_cycles,
    isolated_nodes,
    longest_dependency_chain,
)

__all__ = [
    "EdgeType",
    "NodeType",
    "TypedGraph",
    "build_typed_graph",
    "service_dependency_edges",
    "betweenness_centrality",
    "find_cycles",
    "isolated_nodes",
    "longest_dependency_chain",
]
