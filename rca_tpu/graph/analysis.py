"""Deterministic graph analyses on the array representation.

Replaces the reference's networkx calls with better asymptotics
(reference: agents/topology_agent.py — ``nx.simple_cycles`` :268, all-pairs
``nx.all_simple_paths`` longest chain :294-305 (O(V²)·paths, its hot spot),
betweenness-centrality SPOF :329-346, isolated nodes :363):

- cycle detection via Kahn peeling (O(V+E)) + one DFS to report a witness,
- longest dependency chain via topological-order DP (O(V+E)),
- Brandes betweenness centrality (exact, O(V·E)) with a size gate,
- isolated nodes via degree counting.

All take COO edge arrays (src depends-on dst) over n nodes.
"""

from __future__ import annotations

from collections import deque
from functools import lru_cache as _lru_cache
from typing import List, Optional, Tuple

import numpy as np


def _adjacency(n: int, src: np.ndarray, dst: np.ndarray) -> List[List[int]]:
    adj: List[List[int]] = [[] for _ in range(n)]
    for s, d in zip(src.tolist(), dst.tolist()):
        adj[s].append(d)
    return adj


def _kahn_order(n: int, src: np.ndarray, dst: np.ndarray):
    """Topological peel. Returns (order, on_cycle_mask)."""
    indeg = np.zeros(n, dtype=np.int64)
    np.add.at(indeg, dst, 1)
    adj = _adjacency(n, src, dst)
    stack = [i for i in range(n) if indeg[i] == 0]
    order: List[int] = []
    while stack:
        u = stack.pop()
        order.append(u)
        for v in adj[u]:
            indeg[v] -= 1
            if indeg[v] == 0:
                stack.append(v)
    on_cycle = np.ones(n, dtype=bool)
    on_cycle[order] = False
    return order, on_cycle


def find_cycles(
    n: int, src: np.ndarray, dst: np.ndarray, max_cycles: int = 10
) -> List[List[int]]:
    """Nodes trapped on cycles, reported as witness cycles (node indices)."""
    _, on_cycle = _kahn_order(n, src, dst)
    if not on_cycle.any():
        return []
    adj = _adjacency(n, src, dst)
    # restrict once to the cycle-trapped subgraph
    cyc_adj: List[List[int]] = [
        [v for v in adj[u] if on_cycle[v]] if on_cycle[u] else []
        for u in range(n)
    ]
    cycles: List[List[int]] = []
    visited = np.zeros(n, dtype=bool)
    for start in np.nonzero(on_cycle)[0]:
        if len(cycles) >= max_cycles:
            break
        if visited[start]:
            continue
        # iterative DFS restricted to cycle nodes, tracking the path
        path: List[int] = []
        pos = {}
        stack: List[Tuple[int, int]] = [(int(start), 0)]
        while stack and len(cycles) < max_cycles:
            u, ei = stack[-1]
            if ei == 0:
                pos[u] = len(path)
                path.append(u)
                visited[u] = True
            nbrs = cyc_adj[u]
            if ei < len(nbrs):
                stack[-1] = (u, ei + 1)
                v = nbrs[ei]
                if v in pos:
                    cycles.append(path[pos[v]:] + [v])
                elif not visited[v]:
                    stack.append((v, 0))
            else:
                stack.pop()
                path.pop()
                del pos[u]
    return cycles


def longest_dependency_chain(
    n: int, src: np.ndarray, dst: np.ndarray
) -> List[int]:
    """Longest path in the acyclic part, via topological DP (O(V+E))."""
    order, on_cycle = _kahn_order(n, src, dst)
    adj = _adjacency(n, src, dst)
    dist = np.zeros(n, dtype=np.int64)
    nxt = np.full(n, -1, dtype=np.int64)
    # process in reverse topological order: dist[u] = 1 + max dist[v]
    for u in reversed(order):
        best, arg = 0, -1
        for v in adj[u]:
            if on_cycle[v]:
                continue
            if dist[v] + 1 > best:
                best, arg = dist[v] + 1, v
        dist[u] = best
        nxt[u] = arg
    if n == 0 or dist.max() == 0:
        return []
    u = int(dist.argmax())
    chain = [u]
    while nxt[u] >= 0:
        u = int(nxt[u])
        chain.append(u)
    return chain


def isolated_nodes(n: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    deg = np.zeros(n, dtype=np.int64)
    np.add.at(deg, src, 1)
    np.add.at(deg, dst, 1)
    return np.nonzero(deg == 0)[0]


@_lru_cache(maxsize=8)
def _bc_kernel(n: int):
    """jit-compiled ALL-SOURCES Brandes for an [n, n] dense adjacency.

    The per-source Python BFS + accumulation is the topology agent's host
    hot spot (~1.7 s at 2k services).  Unweighted Brandes is
    level-synchronous, so every source advances one BFS level per step —
    which makes each step two [n, n] matmuls that XLA tiles onto the MXU:

    forward (σ = shortest-path counts, one row per source):
        paths    = (σ ⊙ frontier) @ A        # arrivals via current level
        newly    = (paths > 0) ∧ (dist < 0)
        σ       += paths ⊙ newly ;  dist[newly] = level+1
    backward (δ = dependency accumulation, levels descending):
        X        = [dist = d] ⊙ (1 + δ) / σ
        δ       += σ ⊙ (X @ Aᵀ) ⊙ [dist = d-1]
    bc[v] = Σ_s δ[s, v] (v ≠ s)

    An edge (u, v) with dist_u = d-1, dist_v = d is exactly a Brandes
    predecessor pair under BFS, so the masked matmul reproduces the exact
    algorithm (parity vs the Python loop: max |Δ| ≈ 1e-7 at 2k).  Runs in
    fp32: the kernel also returns a finiteness flag — path COUNTS can
    overflow fp32 on extremely path-dense graphs, and the caller falls
    back to the float64 Python implementation then.  Measured at 2k
    services: 1.7 s host Brandes → 0.74 s end-to-end through the tunneled
    chip (the [n,n] upload + RTT dominates; device compute is tens of ms,
    so a host-attached chip sees the full ~20x)."""
    import jax
    import jax.numpy as jnp

    def fn(src, dst, mask):
        # adjacency is scattered ON DEVICE from the COO arrays: uploading
        # the dense [n,n] matrix instead cost 16 MB per call at 2k nodes —
        # through the ~100 ms-RTT tunnel that upload dominated the whole
        # kernel.  Padded edge slots carry mask 0 (a max-scatter of 0 is a
        # no-op), real duplicates collapse to 1.
        A = jnp.zeros((n, n), dtype=jnp.float32).at[src, dst].max(mask)
        eye = jnp.eye(n, dtype=jnp.float32)

        def fwd_cond(state):
            frontier, _, _, _ = state
            return frontier.sum() > 0

        def fwd_body(state):
            frontier, sigma, dist, level = state
            paths = (sigma * frontier) @ A
            newly = (paths > 0) & (dist < 0)
            sigma = sigma + jnp.where(newly, paths, 0.0)
            dist = jnp.where(newly, level + 1, dist)
            return newly.astype(jnp.float32), sigma, dist, level + 1

        dist0 = jnp.where(eye > 0, 0, -1).astype(jnp.int32)
        _, sigma, dist, levels = jax.lax.while_loop(
            fwd_cond, fwd_body, (eye, eye, dist0, jnp.int32(0))
        )

        def bwd_cond(state):
            _, d = state
            return d > 0

        def bwd_body(state):
            delta, d = state
            mask_v = (dist == d).astype(jnp.float32)
            x = mask_v * (1.0 + delta) / jnp.maximum(sigma, 1.0)
            contrib = x @ A.T
            delta = delta + sigma * contrib * (dist == d - 1)
            return delta, d - 1

        delta, _ = jax.lax.while_loop(
            bwd_cond, bwd_body, (jnp.zeros_like(A), levels)
        )
        bc = (delta * (1.0 - eye)).sum(axis=0)
        finite = jnp.isfinite(sigma).all() & jnp.isfinite(delta).all()
        return bc, finite

    return jax.jit(fn)


# device path pays a per-size jit compile plus one [n,n] upload per call;
# through the tunneled chip (~100 ms RTT) the measured crossover vs the
# Python loop sits near ~1.3k nodes — the floor matches it.  The ceiling
# bounds the dense [n,n] materialization (several same-shape device
# buffers): callers that disable the degree-approximation gate
# (max_nodes=None) keep the O(V+E)-memory Python loop beyond it
_BC_DEVICE_MIN_NODES = 1280
_BC_DEVICE_MAX_NODES = 4096


def betweenness_centrality(
    n: int,
    src: np.ndarray,
    dst: np.ndarray,
    normalized: bool = True,
    max_nodes: Optional[int] = 4096,
) -> np.ndarray:
    """Exact Brandes betweenness (directed). Gated by ``max_nodes`` — beyond
    it the SPOF analysis falls back to degree centrality (documented
    approximation for 10k+ graphs).  Mid-size graphs
    (``_BC_DEVICE_MIN_NODES``..``_BC_DEVICE_MAX_NODES`` — a ceiling
    independent of ``max_nodes``) run the all-sources matmul formulation
    on the accelerator (:func:`_bc_kernel`); smaller graphs, larger
    graphs under ``max_nodes=None``, and fp32-overflow cases use the
    float64 Python loop."""
    bc = np.zeros(n, dtype=np.float64)
    if n == 0 or len(src) == 0:
        return bc
    if max_nodes is not None and n > max_nodes:
        deg = np.zeros(n, dtype=np.float64)
        np.add.at(deg, src, 1.0)
        np.add.at(deg, dst, 1.0)
        return deg / max(1.0, deg.max())
    if _BC_DEVICE_MIN_NODES <= n <= _BC_DEVICE_MAX_NODES:
        # BOTH axes are tiered so jit compiles once per (node-tier,
        # edge-tier), not per exact size — a live cluster's service count
        # drifts across analyses and per-n recompiles would cost more
        # than the Python loop.  Padding nodes are isolated (no edges):
        # unreachable from every real source, bc 0, on no real shortest
        # path; the result slices back to n
        e = len(src)
        e_pad = 1 << max(int(np.ceil(np.log2(max(e, 1)))), 0)
        n_pad = -(-n // 256) * 256
        src_p = np.zeros(e_pad, np.int32)
        dst_p = np.zeros(e_pad, np.int32)
        mask_p = np.zeros(e_pad, np.float32)
        src_p[:e] = src
        dst_p[:e] = dst
        mask_p[:e] = 1.0
        bc_dev, finite = _bc_kernel(n_pad)(src_p, dst_p, mask_p)
        if bool(finite):
            bc = np.asarray(bc_dev, dtype=np.float64)[:n]
            if normalized and n > 2:
                bc /= (n - 1) * (n - 2)
            return bc
        # fp32 path counts overflowed: fall through to the float64 loop
    return _betweenness_python(n, src, dst, normalized)


def _betweenness_python(
    n: int,
    src: np.ndarray,
    dst: np.ndarray,
    normalized: bool = True,
) -> np.ndarray:
    bc = np.zeros(n, dtype=np.float64)
    adj = _adjacency(n, src, dst)
    for s in range(n):
        if not adj[s]:
            continue
        # BFS (unweighted shortest paths)
        sigma = np.zeros(n)
        sigma[s] = 1.0
        dist = np.full(n, -1)
        dist[s] = 0
        order: List[int] = []
        queue = deque([s])
        preds: List[List[int]] = [[] for _ in range(n)]
        while queue:
            u = queue.popleft()
            order.append(u)
            for v in adj[u]:
                if dist[v] < 0:
                    dist[v] = dist[u] + 1
                    queue.append(v)
                if dist[v] == dist[u] + 1:
                    sigma[v] += sigma[u]
                    preds[v].append(u)
        delta = np.zeros(n)
        for v in reversed(order):
            for u in preds[v]:
                delta[u] += sigma[u] / sigma[v] * (1.0 + delta[v])
            if v != s:
                bc[v] += delta[v]
    if normalized and n > 2:
        bc /= (n - 1) * (n - 2)
    return bc
