"""Typed resource-graph builder.

Node/edge taxonomy mirrors the reference's topology agent (reference:
agents/topology_agent.py:94-260): nodes are services / workloads / ingresses
/ configmaps / secrets; edges are

- ``SELECTS``     service → workload   (service selector ⊆ pod-template labels)
- ``ROUTES``      ingress → service    (ingress backend)
- ``MOUNTS``      workload → configmap (volume mount)
- ``ENV_FROM``    workload → configmap/secret (envFrom)
- ``ENV_VAR``     workload → configmap/secret (env valueFrom)
- ``DEPENDS_ON``  workload → service   (service DNS name in env values)

plus the service-level condensation ``service_dependency_edges`` the causal
engine consumes: service A depends on service B when A's backing workload
carries a DEPENDS_ON edge to B, or the trace backend reports the dependency.
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from rca_tpu.cluster.labels import SelectorIndex
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.features.extract import FeatureSet


class NodeType(enum.IntEnum):
    SERVICE = 0
    WORKLOAD = 1
    INGRESS = 2
    CONFIGMAP = 3
    SECRET = 4


class EdgeType(enum.IntEnum):
    SELECTS = 0
    ROUTES = 1
    MOUNTS = 2
    ENV_FROM = 3
    ENV_VAR = 4
    DEPENDS_ON = 5


@dataclasses.dataclass
class TypedGraph:
    node_names: List[str]          # qualified "<type>/<name>"
    node_types: np.ndarray         # int8 [N]
    edge_src: np.ndarray           # int32 [E]
    edge_dst: np.ndarray           # int32 [E]
    edge_types: np.ndarray         # int8 [E]
    # bookkeeping for findings / viz
    missing_refs: List[dict] = dataclasses.field(default_factory=list)

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    @property
    def num_edges(self) -> int:
        return len(self.edge_src)

    def to_dict(self) -> dict:
        """{nodes, edges} export for visualization (reference:
        agents/topology_agent.py:657-693)."""
        type_names = {t.value: t.name.lower() for t in NodeType}
        edge_names = {t.value: t.name.lower() for t in EdgeType}
        return {
            "nodes": [
                {"id": n, "type": type_names[int(t)]}
                for n, t in zip(self.node_names, self.node_types)
            ],
            "edges": [
                {
                    "source": self.node_names[int(s)],
                    "target": self.node_names[int(d)],
                    "relation": edge_names[int(t)],
                }
                for s, d, t in zip(self.edge_src, self.edge_dst, self.edge_types)
            ],
        }


class _Builder:
    def __init__(self) -> None:
        self.names: List[str] = []
        self.types: List[int] = []
        self.index: Dict[str, int] = {}
        self.src: List[int] = []
        self.dst: List[int] = []
        self.et: List[int] = []
        self.missing: List[dict] = []

    def node(self, ntype: NodeType, name: str) -> int:
        key = f"{ntype.name.lower()}/{name}"
        if key not in self.index:
            self.index[key] = len(self.names)
            self.names.append(key)
            self.types.append(int(ntype))
        return self.index[key]

    def maybe(self, ntype: NodeType, name: str) -> Optional[int]:
        return self.index.get(f"{ntype.name.lower()}/{name}")

    def edge(self, src: int, dst: int, etype: EdgeType) -> None:
        self.src.append(src)
        self.dst.append(dst)
        self.et.append(int(etype))

    def build(self) -> TypedGraph:
        # dedup: pods restate their workload template, producing repeats
        triples = sorted(set(zip(self.src, self.dst, self.et)))
        src = [t[0] for t in triples]
        dst = [t[1] for t in triples]
        et = [t[2] for t in triples]
        seen = set()
        missing = []
        for m in self.missing:
            key = (m["kind"], m["from"], m["missing"])
            if key not in seen:
                seen.add(key)
                missing.append(m)
        return TypedGraph(
            node_names=self.names,
            node_types=np.asarray(self.types, dtype=np.int8),
            edge_src=np.asarray(src, dtype=np.int32),
            edge_dst=np.asarray(dst, dtype=np.int32),
            edge_types=np.asarray(et, dtype=np.int8),
            missing_refs=missing,
        )


def _workloads(snapshot: ClusterSnapshot) -> List[Tuple[str, dict]]:
    out = []
    for coll in (snapshot.deployments, snapshot.statefulsets, snapshot.daemonsets):
        for w in coll:
            out.append((w.get("metadata", {}).get("name", ""), w))
    return out


def _dns_service_names(value: str, svc_set: set, namespace: str):
    """Service DNS inference from env values (reference:
    agents/topology_agent.py:228-260): match a bare '<svc>' host or a
    qualified '<svc>.<ns>[.svc...]' host.  The namespace component must be
    THIS namespace — '<svc>.<other-ns>.svc' points at a different cluster
    tenant and must not create a local dependency edge.

    ``svc_set`` is a prebuilt set: this runs once per container env var, so
    building the set here made graph construction O(S²) — 17.6 s at 10k
    services, vs ~1 s with the set hoisted to the per-graph caller."""
    hits = set()
    hosts = re.findall(r"[a-z0-9][a-z0-9.-]*", value.lower())
    for host in hosts:
        parts = host.split(".")
        if parts[0] in svc_set:
            if len(parts) == 1 or parts[1] == namespace:
                hits.add(parts[0])
    return hits


def build_typed_graph(snapshot: ClusterSnapshot) -> TypedGraph:
    b = _Builder()
    service_names = snapshot.service_names()
    svc_set = set(service_names)
    for name in service_names:
        b.node(NodeType.SERVICE, name)
    cm_names = {c.get("metadata", {}).get("name", "") for c in snapshot.configmaps}
    sec_names = {s.get("metadata", {}).get("name", "") for s in snapshot.secrets}
    for name in sorted(cm_names):
        b.node(NodeType.CONFIGMAP, name)
    for name in sorted(sec_names):
        b.node(NodeType.SECRET, name)

    # inverted selector index: O(labels) per workload instead of O(services)
    svc_selector_index = SelectorIndex(
        [(s.get("spec") or {}).get("selector") or {}
         for s in snapshot.services]
    )

    workloads = _workloads(snapshot)
    for wname, w in workloads:
        widx = b.node(NodeType.WORKLOAD, wname)
        spec = w.get("spec", {}) or {}
        template = (spec.get("template") or {})
        tlabels = (template.get("metadata") or {}).get("labels", {}) or {}
        tspec = template.get("spec") or {}

        # SELECTS: service selector ⊆ template labels
        for j in svc_selector_index.matches(tlabels):
            b.edge(
                b.node(NodeType.SERVICE, service_names[j]),
                widx,
                EdgeType.SELECTS,
            )

        # MOUNTS: volumes referencing configmaps/secrets
        for vol in tspec.get("volumes", []) or []:
            _volume_edges(b, widx, wname, vol, cm_names, sec_names)

        _scan_containers(
            b, widx, wname, tspec.get("containers", []) or [],
            cm_names, sec_names, svc_set, snapshot.namespace,
        )

    # Pods restate their workload's template; scanning them too catches
    # references when workload objects weren't captured (edges dedup below).
    for pod in snapshot.pods:
        app = (pod.get("metadata", {}).get("labels") or {}).get("app")
        if app is None:
            continue
        widx = b.maybe(NodeType.WORKLOAD, app)
        if widx is None:
            continue
        pspec = pod.get("spec", {}) or {}
        for vol in pspec.get("volumes", []) or []:
            _volume_edges(b, widx, app, vol, cm_names, sec_names)
        _scan_containers(
            b, widx, app, pspec.get("containers", []) or [],
            cm_names, sec_names, svc_set, snapshot.namespace,
        )

    # ROUTES: ingress backends (missing backends recorded, reference:
    # agents/topology_agent.py:525-533)
    for ing in snapshot.ingresses:
        iname = ing.get("metadata", {}).get("name", "")
        iidx = b.node(NodeType.INGRESS, iname)
        for rule in (ing.get("spec") or {}).get("rules", []) or []:
            for path in ((rule.get("http") or {}).get("paths", []) or []):
                svc = (((path.get("backend") or {}).get("service")) or {}).get("name")
                if not svc:
                    continue
                if svc in svc_set:
                    b.edge(iidx, b.node(NodeType.SERVICE, svc), EdgeType.ROUTES)
                else:
                    b.missing.append(
                        {"kind": "ingress_backend", "from": iname, "missing": svc}
                    )

    return b.build()


def _volume_edges(b: "_Builder", widx: int, wname: str, vol: dict,
                  cm_names: set, sec_names: set) -> None:
    cm = (vol.get("configMap") or {}).get("name")
    if cm:
        _config_edge(b, widx, NodeType.CONFIGMAP, cm, cm_names,
                     EdgeType.MOUNTS, wname)
    sec = (vol.get("secret") or {}).get("secretName")
    if sec:
        _config_edge(b, widx, NodeType.SECRET, sec, sec_names,
                     EdgeType.MOUNTS, wname)


def _scan_containers(
    b: "_Builder", widx: int, wname: str, containers: list,
    cm_names: set, sec_names: set, svc_set: set, namespace: str,
) -> None:
    for c in containers:
        for ef in c.get("envFrom", []) or []:
            cm = (ef.get("configMapRef") or {}).get("name")
            if cm:
                _config_edge(b, widx, NodeType.CONFIGMAP, cm, cm_names,
                             EdgeType.ENV_FROM, wname)
            sec = (ef.get("secretRef") or {}).get("name")
            if sec:
                _config_edge(b, widx, NodeType.SECRET, sec, sec_names,
                             EdgeType.ENV_FROM, wname)
        for env in c.get("env", []) or []:
            vf = env.get("valueFrom") or {}
            cm = (vf.get("configMapKeyRef") or {}).get("name")
            if cm:
                _config_edge(b, widx, NodeType.CONFIGMAP, cm, cm_names,
                             EdgeType.ENV_VAR, wname)
            sec = (vf.get("secretKeyRef") or {}).get("name")
            if sec:
                _config_edge(b, widx, NodeType.SECRET, sec, sec_names,
                             EdgeType.ENV_VAR, wname)
            value = env.get("value")
            if value:
                for dep in _dns_service_names(
                    str(value), svc_set, namespace
                ):
                    b.edge(widx, b.node(NodeType.SERVICE, dep),
                           EdgeType.DEPENDS_ON)


def _config_edge(b: _Builder, widx: int, ntype: NodeType, name: str,
                 existing: set, etype: EdgeType, wname: str) -> None:
    if name in existing:
        b.edge(widx, b.node(ntype, name), etype)
    else:
        b.missing.append(
            {"kind": f"missing_{ntype.name.lower()}", "from": wname, "missing": name}
        )


def service_dependency_edges(
    snapshot: ClusterSnapshot,
    features: FeatureSet,
    graph: Optional[TypedGraph] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Service-level dependency COO aligned with ``features.service_names``.

    Edge (s, d): service s depends on service d.  Union of env-DNS-inferred
    workload dependencies (via the typed graph) and trace-reported
    dependencies; self-edges and duplicates removed.
    """
    if graph is None:
        graph = build_typed_graph(snapshot)
    svc_index = {n: i for i, n in enumerate(features.service_names)}

    # workload -> owning service(s) via SELECTS edges
    workload_services: Dict[int, List[int]] = {}
    for s, d, t in zip(graph.edge_src, graph.edge_dst, graph.edge_types):
        if t == EdgeType.SELECTS:
            svc_name = graph.node_names[int(s)].split("/", 1)[1]
            if svc_name in svc_index:
                workload_services.setdefault(int(d), []).append(svc_index[svc_name])

    pairs = set()
    for s, d, t in zip(graph.edge_src, graph.edge_dst, graph.edge_types):
        if t != EdgeType.DEPENDS_ON:
            continue
        dep_name = graph.node_names[int(d)].split("/", 1)[1]
        if dep_name not in svc_index:
            continue
        for owner in workload_services.get(int(s), []):
            if owner != svc_index[dep_name]:
                pairs.add((owner, svc_index[dep_name]))

    deps = (snapshot.traces or {}).get("dependencies") or {}
    for src_name, dst_names in deps.items():
        if src_name not in svc_index:
            continue
        for dst_name in dst_names or []:
            if dst_name in svc_index and dst_name != src_name:
                pairs.add((svc_index[src_name], svc_index[dst_name]))

    if not pairs:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    arr = np.asarray(sorted(pairs), dtype=np.int32)
    return arr[:, 0].copy(), arr[:, 1].copy()
