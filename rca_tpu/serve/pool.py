"""ServePool: the multi-replica, multi-device serving plane (ISSUE 8).

One shared :class:`rca_tpu.serve.queue.RequestQueue` (admission,
weighted-fair order, priorities, deadline shedding — unchanged) feeds N
:class:`rca_tpu.serve.replica.ReplicaWorker` engine replicas, a
configurable dense/sharded mix each owning a device group carved from
the mesh.  Aggregate throughput scales with replicas instead of being
capped by the one-engine :class:`rca_tpu.serve.loop.ServeLoop`.

**Routing** (shape-bucket aware): a popped request's graph key is looked
up in this order —

1. **home stickiness**: the replica this bucket was last routed to,
   while it is routable and has stage room;
2. **resident stickiness**: any routable replica whose dispatcher
   already pins this graph's prepared state + resident feature base
   (``BatchDispatcher.has_graph``) — hot buckets keep their O(changed
   rows) delta path instead of re-staging on a cold replica;
3. **least-occupied**: cold buckets go to the routable replica holding
   the fewest requests (staged + in flight).

**Failover** (work-stealing rebalance): when a replica's worker dies
(any exception escaping its scheduling iteration, or the chaos
:meth:`ReplicaWorker.kill` seam) or its circuit breaker opens, its
staged requests are taken back and re-placed on surviving replicas, and
a dead replica's in-flight batch is claimed atomically and fetched by
the stealer (results exist on device; claiming is first-taker-wins, so
completion stays exactly-once — ``CompletionSink.double_completions``
stays 0 by construction, asserted under chaos in the tests).  With no
survivor — or ``RCA_SERVE_STEAL=0`` — stolen requests ride the existing
degradation ladder (last-known ranking, else ``error``) instead of
hanging.

Threading: each replica worker loops *route → schedule own replica*; the
route step is serialized by ``ServePool._route_lock`` so two workers
never place one request twice.  The pool also runs single-threaded under
a fake clock (:meth:`run_once`) for deterministic policy tests, exactly
like :class:`ServeLoop`.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

from rca_tpu.config import ServeConfig
from rca_tpu.observability.spans import default_tracer
from rca_tpu.serve.metrics import ServeMetrics
from rca_tpu.serve.queue import RequestQueue
from rca_tpu.serve.replica import (
    CompletionSink,
    ReplicaWorker,
    build_replica_engines,
)
from rca_tpu.serve.request import GraphKey, ServeRequest, ServeResponse
from rca_tpu.util.threads import make_lock

#: idle park time when a worker finds no routing or replica work
_IDLE_WAIT_S = 0.05


class ServePool:
    def __init__(
        self,
        engines=None,
        config: Optional[ServeConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        store=None,
        fault_hook: Optional[Callable[[str], None]] = None,
        recorder=None,
        devices=None,
        dispatchers: Optional[Sequence] = None,
        breakers: Optional[Sequence] = None,
        tracer=None,
        kernelscope: Optional[bool] = None,
    ):
        """``engines``: optional replica engines — either bare engine
        objects (dense, device placement left to the engine) or
        ``(kind, engine, device)`` triples as built by
        :func:`rca_tpu.serve.replica.build_replica_engines`.  When
        omitted, the replica set comes from ``config.replica_specs()``
        (``RCA_SERVE_REPLICAS`` / ``RCA_SERVE_REPLICA_MIX``) over the
        visible ``devices``.  ``dispatchers`` (tests) builds one stub
        replica per entry instead."""
        self.config = config or ServeConfig.from_env()
        self.clock = clock
        self.queue = RequestQueue(self.config.queue_cap, clock=clock)
        self.metrics = ServeMetrics()
        # kernelscope (ISSUE 12): ONE pool-wide recompile watchdog (the
        # compile log is process-global; per-replica monitors would
        # double count); armed start→stop, RCA_KERNELSCOPE=0 disables
        from rca_tpu.observability.kernelscope import RecompileMonitor

        self.recompile_monitor = RecompileMonitor(enabled=kernelscope)
        # one tracer for the whole plane (ISSUE 11): admission mints the
        # root context, the router records queue/steal spans, replicas
        # record batch/dispatch/fetch, the sink closes the root
        self.tracer = tracer if tracer is not None else default_tracer()
        self.sink = CompletionSink(
            self.metrics, clock, store=store, recorder=recorder,
            tracer=self.tracer,
        )
        self.steal = bool(self.config.steal)
        self._route_lock = make_lock("ServePool._route_lock")
        self._home: dict = {}          # GraphKey -> replica_id (sticky)
        self.replicas: List[ReplicaWorker] = []
        if dispatchers is not None:
            triples = [("stub", None, None)] * len(dispatchers)
        elif engines is not None:
            triples = [
                e if isinstance(e, tuple) else ("dense", e, None)
                for e in engines
            ]
        else:
            triples = build_replica_engines(
                self.config.replica_specs(), devices=devices,
            )
        for i, (kind, engine, device) in enumerate(triples):
            self.replicas.append(ReplicaWorker(
                i, engine=engine, kind=kind, device=device,
                config=self.config, clock=clock, sink=self.sink,
                metrics=self.metrics, fault_hook=fault_hook,
                dispatcher=(
                    dispatchers[i] if dispatchers is not None else None
                ),
                breaker=(
                    breakers[i] if breakers is not None else None
                ),
                pool=self,
                tracer=self.tracer,
            ))
        self.recorder = recorder
        if recorder is not None:
            recorder.begin_session({
                "replicas": len(self.replicas),
                "mix": [r.kind for r in self.replicas],
                "max_batch": self.config.max_batch,
                "max_wait_us": self.config.max_wait_us,
                "queue_cap": self.config.queue_cap,
            })

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServePool":
        self.recompile_monitor.start()
        for r in self.replicas:
            r.start()
        return self

    def kernelscope_summary(self) -> dict:
        """Pool twin of :meth:`rca_tpu.serve.loop.ServeLoop.
        kernelscope_summary`: recompile counts + a device-memory sample
        + the live kernel-registry rows."""
        from rca_tpu.engine.registry import kernel_set_hash, kernel_table
        from rca_tpu.observability.kernelscope import sample_device_memory

        out = dict(self.recompile_monitor.snapshot())
        out["device_memory"] = (
            sample_device_memory() if out["enabled"] else None
        )
        out["kernel_registry"] = kernel_table()
        # the grown kernel-set source hash (ISSUE 13): the winner-cache
        # invalidation key, exported so a scrape can tell WHICH kernel
        # set a plane's rows were timed under
        out["kernel_set"] = kernel_set_hash()
        return out

    def stop(self, timeout: float = 10.0) -> None:
        self.recompile_monitor.stop()
        for r in self.replicas:
            r.request_stop()
        self.queue.kick()
        for r in self.replicas:
            r.join(timeout)
        # single-threaded now: complete everything still in the system —
        # in-flight batches fetch normally (results exist), the rest
        # errors out; a stopped pool must not leave submitters parked
        for r in self.replicas:
            r.drain_inflight()
        leftovers: List[ServeRequest] = []
        while True:
            req = self.queue.pop()
            if req is None:
                break
            leftovers.append(req)
        for r in self.replicas:
            leftovers.extend(r.take_staged())
        for req in leftovers:
            self.sink.error(req, "serve pool stopped")

    def __enter__(self) -> "ServePool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def park(self, timeout: Optional[float] = None) -> None:
        """Worker idle wait: parked on the shared queue's condition so a
        submit (or shutdown kick) wakes everyone."""
        self.queue.wait_for_work(
            min(timeout if timeout is not None else _IDLE_WAIT_S,
                _IDLE_WAIT_S)
        )

    @property
    def device_batches(self) -> int:
        return sum(r.device_batches for r in self.replicas)

    def occupancy(self) -> float:
        """Fraction of the pool's staging capacity in use (replica
        occupancy counts over the per-replica stage-ahead cap) — the
        SAME load signal the elasticmesh autoscaler reads off a
        federation (ISSUE 16), exported here so /healthz shows it for
        pools too (pools resize via RCA_SERVE_REPLICAS, but the
        operator's dial is one signal)."""
        from rca_tpu.serve.replica import STAGE_AHEAD_BATCHES

        live = [r for r in self.replicas if r.alive()]
        if not live:
            return 1.0
        cap = max(
            1, self.config.max_batch * STAGE_AHEAD_BATCHES * len(live)
        )
        return min(1.0, sum(r.occupancy() for r in live) / cap)

    # -- admission (same contract as ServeLoop.submit) -----------------------
    def submit(self, req: ServeRequest) -> bool:
        """Admit one request; either way the request WILL be completed
        (``queue_full``/``shed`` are delivered synchronously here), so
        ``req.result()`` always terminates."""
        now = self.clock()
        if self.tracer.enabled and req.trace is None:
            # mint the request's root-span identity at admission: every
            # span recorded on its way through (queue, batch, dispatch,
            # fetch, steal) parents onto it; the sink records the span
            # itself at completion
            req.trace = self.tracer.new_context(parent=req.trace_parent)
        if req.expired(now):
            self.sink.shed(req, detail="expired_at_admission")
            return False
        if not self.queue.submit(req):
            self.metrics.rejected(req.tenant)
            req.complete(ServeResponse(
                status="queue_full", request_id=req.request_id,
                tenant=req.tenant,
                detail=f"queue at capacity ({self.queue.cap})",
            ))
            return False
        self.metrics.submitted(req.tenant, len(self.queue))
        return True

    # -- routing -------------------------------------------------------------
    def route_once(self, now: Optional[float] = None) -> bool:
        """Drain the shared queue into replica batchers (serialized: one
        router at a time).  Stops while every routable replica's staging
        window is full — backpressure stays in the shared queue where
        admission accounting lives."""
        if now is None:
            now = self.clock()
        worked = False
        with self._route_lock:
            for req in self.queue.shed_expired(now):
                self.sink.shed(req, detail="expired_in_queue")
                worked = True
            while True:
                routable = [r for r in self.replicas if r.routable()]
                if routable and not any(
                    r.has_room() for r in routable
                ):
                    # every live replica's staging window is full:
                    # backpressure stays in the shared queue
                    break
                req = self.queue.pop()
                if req is None:
                    break
                if self.tracer.enabled and req.trace is not None:
                    # the fair-queue wait ends here (route time)
                    self.tracer.record(
                        "serve.queue", req.enqueued_at, self.clock(),
                        parent=req.trace,
                        attrs={"tenant": req.tenant,
                               "priority": req.priority},
                    )
                # with NOTHING routable the pop continues: queued
                # requests ride the degradation ladder (in _place)
                # instead of parking forever behind dead replicas
                self._place(req)
                worked = True
        return worked

    def _replica_for(
        self, key: GraphKey, live: List[ReplicaWorker]
    ) -> Optional[ReplicaWorker]:
        """Sticky → resident → least-occupied (module docstring)."""
        by_id = {r.replica_id: r for r in live}
        home = by_id.get(self._home.get(key))
        if home is not None and home.has_room():
            return home
        for r in live:
            if r.has_room() and r.has_graph(key):
                self._home[key] = r.replica_id
                return r
        cands = [r for r in live if r.has_room()] or live
        if not cands:
            return None
        target = min(
            cands, key=lambda r: (r.occupancy(), r.replica_id)
        )
        self._home[key] = target.replica_id
        return target

    def _place(
        self, req: ServeRequest,
        exclude: Optional[ReplicaWorker] = None,
    ) -> Optional[ReplicaWorker]:
        """Offer one (already-popped) request to a replica; called under
        the route lock.  A replica dying between the liveness check and
        the offer just retries; with nothing routable left, the request
        rides the degradation ladder instead of hanging."""
        for _ in range(len(self.replicas) + 1):
            live = [
                r for r in self.replicas
                if r.routable() and r is not exclude
            ]
            target = self._replica_for(req.graph_key, live)
            if target is None:
                break
            if target.offer(req):
                return target
            self._home.pop(req.graph_key, None)
        self.sink.degraded(req, detail="no_replica_available")
        return None

    # -- work-stealing rebalance ---------------------------------------------
    def redistribute(
        self,
        batch: List[ServeRequest],
        exclude: Optional[ReplicaWorker] = None,
        reason: str = "",
    ) -> None:
        """Re-place an already-formed batch (a replica refused it at the
        breaker gate) onto other replicas."""
        with self._route_lock:
            for req in batch:
                target = self._place(req, exclude=exclude)
                if target is not None and exclude is not None:
                    self.metrics.stolen(
                        exclude.replica_id, target.replica_id, 1
                    )
                    self._steal_span(req, exclude, target, reason)

    def rebalance_from(self, replica: ReplicaWorker, reason: str) -> int:
        """Steal a dead/open replica's work: staged requests re-place on
        survivors; a dead replica's in-flight batch is claimed (atomic,
        first-taker-wins) and fetched here — its results exist, only its
        owner died.  Returns how many requests were re-placed.  With
        stealing disabled the same requests ride the degradation ladder
        — answered-or-shed holds either way."""
        dead = not replica.alive()
        if dead:
            orphan = replica.take_inflight()
            if orphan is not None:
                # fetch through the victim's own guarded path: success
                # completes ok, failure degrades — never drops
                replica._fetch_guarded(orphan)
        stolen = replica.take_staged()
        if not stolen:
            return 0
        if not self.steal:
            for req in stolen:
                self.sink.degraded(
                    req, detail=f"replica_unavailable:{reason}"
                )
            return 0
        moved = 0
        with self._route_lock:
            self._home = {
                k: rid for k, rid in self._home.items()
                if rid != replica.replica_id
            }
            for req in stolen:
                target = self._place(req, exclude=replica)
                if target is not None:
                    self.metrics.stolen(
                        replica.replica_id, target.replica_id, 1
                    )
                    self._steal_span(req, replica, target, reason)
                    moved += 1
        return moved

    def _steal_span(
        self, req: ServeRequest, victim: ReplicaWorker,
        target: ReplicaWorker, reason: str,
    ) -> None:
        """A zero-duration steal marker on the request's OWN trace — a
        stolen request keeps its trace, and the marker names both ends
        of the move (the test asserts the trace stays connected through
        a kill)."""
        if self.tracer.enabled and req.trace is not None:
            self.tracer.event(
                "serve.steal", self.clock(), parent=req.trace,
                attrs={
                    "from_replica": victim.replica_id,
                    "to_replica": target.replica_id,
                    "reason": reason,
                },
            )

    # -- single-threaded driver (fake-clock policy tests) --------------------
    def run_once(self, now: Optional[float] = None) -> bool:
        """One pool iteration: route, then one scheduling iteration per
        replica, with death → rebalance handled inline (the threaded
        path does the same from each worker's crash handler)."""
        if now is None:
            now = self.clock()
        worked = self.route_once(now)
        for r in self.replicas:
            if not r.alive():
                r.mark_dead()
                worked |= self.rebalance_from(r, "replica_death") > 0
                continue
            try:
                worked |= r.run_once(now)
            except Exception as exc:
                r.mark_dead(exc)
                self.rebalance_from(r, "replica_death")
                worked = True
        return worked
