"""Shape-bucket batcher: group compatible requests, flush by size or age.

The staging area between the fair queue and the device: requests popped
in service order land here grouped by :func:`rca_tpu.serve.request.
graph_key` — the identity that guarantees one coalesced ``analyze_batch``
dispatch returns bit-identical per-lane results (same padded node/edge
bucket, same edge arrays, same compiled executable from the engine's
shape-bucketed jit cache).

Flush policy (the continuous-batching core):

- a group that reaches ``max_batch`` flushes immediately (a full device
  batch is never held back);
- a group whose OLDEST member has been in the system longer than
  ``max_wait_us`` flushes at whatever width it reached — the wait bound
  is how long a request may be held hoping for batchmates;
- when the device is idle and the queue is drained (``drain=True``), the
  oldest group flushes immediately — an idle engine never sits out the
  wait window, so a lone request's latency is one dispatch, not
  ``max_wait_us`` plus one dispatch.  ``max_wait_us`` therefore only
  shapes behavior under load, which is exactly when batching pays.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from rca_tpu.serve.request import GraphKey, ServeRequest


class ShapeBucketBatcher:
    def __init__(
        self,
        max_batch: int = 16,
        max_wait_us: int = 2000,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_us < 0:
            raise ValueError(f"max_wait_us must be >= 0, got {max_wait_us}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_us) / 1e6
        self.clock = clock
        # insertion-ordered groups; each group FIFO by service order
        self._groups: Dict[GraphKey, List[ServeRequest]] = {}
        self._staged = 0

    # -- staging -------------------------------------------------------------
    def offer(self, req: ServeRequest) -> None:
        # batch-wait accounting (ISSUE 11): the serve.batch span runs
        # from here to batch formation; a re-offer (work steal) restamps,
        # so the span measures time on the replica that actually served it
        req.staged_at = self.clock()
        self._groups.setdefault(req.graph_key, []).append(req)
        self._staged += 1

    def staged(self) -> int:
        return self._staged

    def group_count(self) -> int:
        return sum(1 for g in self._groups.values() if g)

    # -- flush policy --------------------------------------------------------
    def _age(self, group: List[ServeRequest], now: float) -> float:
        # group is FIFO: [0] is the oldest member; age counts from
        # ADMISSION, not staging — the wait bound covers the whole queue
        return now - group[0].enqueued_at

    def _take(self, key: GraphKey, width: int) -> List[ServeRequest]:
        group = self._groups[key]
        batch, rest = group[:width], group[width:]
        if rest:
            self._groups[key] = rest
        else:
            del self._groups[key]
        self._staged -= len(batch)
        return batch

    def take_ready(
        self, now: Optional[float] = None, drain: bool = False
    ) -> Optional[List[ServeRequest]]:
        """The next batch to dispatch, or None while every group is still
        worth holding for batchmates (see module docstring for policy)."""
        if now is None:
            now = self.clock()
        oldest_key = None
        oldest_age = -1.0
        for key, group in self._groups.items():
            if not group:
                continue
            if len(group) >= self.max_batch:
                return self._take(key, self.max_batch)
            age = self._age(group, now)
            if age > oldest_age:
                oldest_age = age
                oldest_key = key
        if oldest_key is None:
            return None
        if oldest_age >= self.max_wait_s or drain:
            return self._take(oldest_key, self.max_batch)
        return None

    def next_ready_in(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the oldest staged group matures past the wait
        bound (a parked worker's wake-up timeout); None when empty."""
        if now is None:
            now = self.clock()
        ages = [
            self._age(g, now) for g in self._groups.values() if g
        ]
        if not ages:
            return None
        return max(0.0, self.max_wait_s - max(ages))

    # -- deadline shedding ---------------------------------------------------
    def shed_expired(self, now: Optional[float] = None) -> List[ServeRequest]:
        """Remove (and return) staged requests whose deadline has passed
        — same contract as the queue's shed: no device slot, ever."""
        if now is None:
            now = self.clock()
        shed: List[ServeRequest] = []
        for key in list(self._groups):
            group = self._groups[key]
            keep = [r for r in group if not r.expired(now)]
            if len(keep) != len(group):
                shed.extend(r for r in group if r.expired(now))
                if keep:
                    self._groups[key] = keep
                else:
                    del self._groups[key]
        self._staged -= len(shed)
        return shed
