"""Batched device dispatch for the serving scheduler (dispatch/fetch split).

One :class:`BatchDispatcher` turns a batcher flush — requests sharing one
:func:`rca_tpu.serve.request.graph_key` — into a single device dispatch of
the engine's batched executable, and renders per-request
:class:`rca_tpu.engine.runner.EngineResult` objects at fetch time.

The split mirrors the PR-2 streaming tick pipeline: :meth:`dispatch`
packs, pads, and ENQUEUES (JAX dispatch is async — it returns in
microseconds with a :class:`BatchHandle` over the in-flight device
values), and :meth:`fetch` is THE designated sync point of the whole
serve path (enforced by tools/lint_tick_sync.py) — the serve loop
dispatches batch N, assembles batch N+1 from the queue, and only then
fetches batch N, hiding the device round trip behind host scheduling
work.

Round 7 (ISSUE 6) makes the staging DEVICE-RESIDENT per graph: the
prepared-graph cache additionally pins a resident base feature buffer,
and a hot graph's dispatch uploads each lane as O(changed rows) scatter
deltas against that base (``_propagate_ranked_batch_delta``) instead of
restaging the full [b_pad, n_pad, C] stack; the fetch moves only the
[B, 4, k] top-k diagnostic gather + the top-k pair — the full stack
stays on device behind each result's lazy diagnostics.  Cache hits /
misses / evictions and per-tenant delta reuse flow into
:class:`rca_tpu.serve.metrics.ServeMetrics`.

Parity contract: a request served at any batch width is bit-identical to
the same request served alone, because every width runs the SAME
propagation body (``_ranked_lanes`` — a vmap of the same ``propagate``
the one-shot path runs) over the same padded graph, whether the lanes
were staged full or as deltas (base + changed rows reconstructs the
exact request features); batch width is padded to a power of two so the
executable count stays bounded per shape bucket (pad lanes are dropped
at render).  Sharded engines ride
:func:`rca_tpu.parallel.sharded.stage_batch_ranked` with the batch
padded to the mesh's dp multiple instead.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, List, Optional

import numpy as np

from rca_tpu.config import bucket_for, serve_graph_cache_cap
from rca_tpu.serve.request import GraphKey, K_CAP, ServeRequest
from rca_tpu.util.threads import make_lock

@dataclasses.dataclass
class _PreparedGraph:
    """Per-graph staging state shared by every dispatch over that graph."""

    n: int
    n_pad: int
    n_edges: int
    edges_j: object = None        # [2, e_pad] device buffer (dense engine)
    down_seg: object = None
    up_seg: object = None
    up_ell: object = None
    dbl: object = None            # engine.doubling.DoublingLayout
    n_live: object = None
    sharded_graph: object = None  # ShardedGraph (sharded engine)
    kk: int = 0
    # resident base feature buffer (ISSUE 6): the last full staging's
    # lane-0 features, pinned on device as the delta-scatter base.  Only
    # a FINITE base engages the delta path — a NaN row in the base would
    # leak into pad lanes' sanitize count
    base_host: object = None      # np [n_pad, C] raw mirror (diff base)
    base_dev: object = None       # device [n_pad, C]
    base_clean: bool = False
    # the kernel THIS padded shape engages (ISSUE 11/13: a KERNELS
    # member per shape, not per round) — stamped into dispatch span
    # attributes so a kernel regression names a shape bucket
    kernel: str = "xla"


@dataclasses.dataclass
class BatchHandle:
    """One in-flight coalesced batch: the device values the async
    dispatch left behind plus what fetch needs to render each lane.
    ``stacked`` is never fetched here — it backs the per-result lazy
    diagnostics; ``diag`` is the [b_pad, 4, kk] top-k gather the fetch
    actually moves."""

    requests: List[ServeRequest]
    stacked: object               # [b_pad, 4, n_pad] device values
    diag: object                  # [b_pad, 4, kk] device values
    vals: object                  # [b_pad, kk]
    idx: object                   # [b_pad, kk]
    n_bad: object                 # sanitized-row count (device or host int)
    n: int                        # real (unpadded) service count
    engine_tag: str
    dispatch_ms: float
    dispatched_at: float          # scheduler-clock stamp at dispatch
    kernel: str = "xla"           # engaged combine path for this shape
    resident_delta: bool = False  # lanes rode the delta-scatter path


class BatchDispatcher:
    """Coalesced analyze dispatch over one engine (dense or sharded)."""

    def __init__(
        self,
        engine=None,
        fault_hook: Optional[Callable[[str], None]] = None,
        cache_cap: Optional[int] = None,
        clock: Callable[[], float] = time.perf_counter,
        metrics=None,
    ):
        from rca_tpu.engine.runner import GraphEngine

        self.engine = engine if engine is not None else GraphEngine()
        # injectable monotonic timer (nondet-discipline: the serve path's
        # latency stamps never read the clock module directly)
        self._clock = clock
        # chaos surface (tests / `rca serve --selftest --chaos`): called
        # with "dispatch"/"fetch" before the device work; a raise here
        # exercises the serve loop's breaker + degraded-response path
        self.fault_hook = fault_hook
        self._cache_cap = max(
            1,
            int(cache_cap) if cache_cap is not None
            else serve_graph_cache_cap(),
        )
        # cache + resident-reuse observability (ISSUE 6 satellite); the
        # serve loop points this at its ServeMetrics
        self.metrics = metrics
        # the prepared-graph cache is read by the serve pool's router
        # (bucket stickiness asks "is this graph resident HERE?") while
        # the owning replica worker stages into it — one lock covers the
        # lookup/insert/evict triple (ISSUE 8)
        self._graphs_lock = make_lock("BatchDispatcher._graphs_lock")
        self._graphs: "collections.OrderedDict[GraphKey, _PreparedGraph]" = (
            collections.OrderedDict()
        )
        from rca_tpu.engine.sharded_runner import ShardedGraphEngine

        self._sharded = isinstance(self.engine, ShardedGraphEngine)
        self.engine_tag = (
            f"serve+{self.engine.engine_tag}" if self._sharded
            else "serve+single"
        )

    # -- per-graph staging ---------------------------------------------------
    def has_graph(self, key: GraphKey) -> bool:
        """Is this graph's staging state (edges + layouts + resident
        base) already pinned here?  The serve pool's router uses this for
        bucket stickiness — a resident bucket keeps dispatching to the
        replica that holds its base."""
        with self._graphs_lock:
            return key in self._graphs

    def _prepared(self, req: ServeRequest) -> _PreparedGraph:
        key = req.graph_key
        with self._graphs_lock:
            gs = self._graphs.get(key)
            if gs is not None:
                self._graphs.move_to_end(key)
        if gs is not None:
            if self.metrics is not None:
                self.metrics.graph_cache("hit")
            return gs
        if self.metrics is not None:
            self.metrics.graph_cache("miss")
        n = req.features.shape[0]
        if self._sharded:
            from rca_tpu.engine.registry import engaged_kernel

            graph = self.engine._shard(n, req.dep_src, req.dep_dst)
            gs = _PreparedGraph(
                n=n, n_pad=graph.n_pad, n_edges=len(req.dep_src),
                sharded_graph=graph,
                kk=min(K_CAP + 8, graph.n_pad),
                # the registry's sharded row (xla, or segscan when the
                # per-block twin engages), recorded so the table shows
                # the shape was served
                kernel=engaged_kernel(
                    graph.n_pad, graph.src_local.shape[1], sharded=True,
                ),
            )
        else:
            import jax.numpy as jnp

            from rca_tpu.engine.runner import kernel_plan

            cfg = self.engine.config
            n_pad = bucket_for(n + 1, cfg.shape_buckets)
            e_pad = bucket_for(max(len(req.dep_src), 1), cfg.shape_buckets)
            dummy = n_pad - 1
            s = np.full(e_pad, dummy, np.int32)
            d = np.full(e_pad, dummy, np.int32)
            s[: len(req.dep_src)] = req.dep_src
            d[: len(req.dep_dst)] = req.dep_dst
            # kernel + layouts from the one dispatch seam (ISSUE 12/13)
            plan = kernel_plan(
                n_pad, e_pad, req.dep_src, req.dep_dst,
                steps=self.engine.params.steps,
            )
            gs = _PreparedGraph(
                n=n, n_pad=n_pad, n_edges=len(req.dep_src),
                edges_j=jnp.asarray(np.stack([s, d])),
                down_seg=plan.down_seg, up_seg=plan.up_seg,
                up_ell=plan.up_ell, dbl=plan.dbl,
                n_live=jnp.asarray(n, jnp.int32),
                kk=min(K_CAP + 8, n_pad),
                kernel=plan.kernel,
            )
        evictions = 0
        with self._graphs_lock:
            self._graphs[key] = gs
            while len(self._graphs) > self._cache_cap:
                self._graphs.popitem(last=False)
                evictions += 1
        for _ in range(evictions):
            if self.metrics is not None:
                self.metrics.graph_cache("eviction")
        return gs

    def _b_pad(self, b: int) -> int:
        """Padded batch width: power of two (bounded executable count per
        shape bucket); sharded batches additionally round to a dp
        multiple so the hypothesis axis tiles the mesh."""
        b_pad = 1 << max(0, (b - 1).bit_length())
        if self._sharded:
            dp = self.engine.dp
            b_pad = -(-b_pad // dp) * dp
        return b_pad

    # -- delta staging (ISSUE 6) ---------------------------------------------
    def _lane_deltas(
        self, gs: _PreparedGraph, batch: List[ServeRequest],
    ) -> Optional[List[np.ndarray]]:
        """Per-lane changed-row sets against the resident base, or None
        when delta staging does not pay: no (finite) base yet, or the
        batch has drifted so far from it that scattering moves no fewer
        bytes than restaging.  NaN rows always diff as changed (NaN !=
        NaN), so poisoned requests re-upload raw and sanitize on device —
        bit-parity with full staging holds."""
        if gs.base_host is None or not gs.base_clean:
            return None
        base = gs.base_host[: gs.n]
        deltas = [
            np.flatnonzero(np.any(req.features != base, axis=1))
            for req in batch
        ]
        # the scatter ships a common padded width per lane: worth it only
        # while the widest lane stays well under the full matrix
        u_max = max((len(d) for d in deltas), default=0)
        if 2 * u_max >= gs.n_pad:
            return None
        return deltas

    # -- the split -----------------------------------------------------------
    def dispatch(
        self, batch: List[ServeRequest], now: Optional[float] = None
    ) -> BatchHandle:
        """Stack, pad, and ENQUEUE one coalesced batch; returns without
        synchronizing.  All requests must share a graph_key (the batcher
        guarantees it)."""
        if not batch:
            raise ValueError("empty batch")
        if any(r.graph_key != batch[0].graph_key for r in batch[1:]):
            raise ValueError("batch members must share a graph_key")
        if self.fault_hook is not None:
            self.fault_hook("dispatch")
        t0 = self._clock()
        gs = self._prepared(batch[0])
        b = len(batch)
        b_pad = self._b_pad(b)
        deltas = None
        if self._sharded:
            from rca_tpu.engine.runner import finite_mask_rows_np
            from rca_tpu.parallel.sharded import stage_batch_ranked

            fb = np.zeros(
                (b_pad, gs.n_pad, batch[0].features.shape[1]), np.float32
            )
            for i, req in enumerate(batch):
                fb[i, : gs.n] = req.features
            # host-side guard, same semantics as the sharded engine's
            # analyze_batch (features are being staged from host anyway)
            fb, n_bad = finite_mask_rows_np(fb)
            stacked, diag, vals, idx = stage_batch_ranked(
                self.engine.mesh, fb, gs.sharded_graph, self.engine.params,
                gs.kk,
            )
        else:
            deltas = self._lane_deltas(gs, batch)
            if deltas is not None:
                stacked, diag, vals, idx, n_bad = self._dispatch_delta(
                    gs, batch, b_pad, deltas,
                )
            else:
                stacked, diag, vals, idx, n_bad = self._dispatch_full(
                    gs, batch, b_pad,
                )
        delta_path = not self._sharded and deltas is not None
        return BatchHandle(
            requests=list(batch), stacked=stacked, diag=diag, vals=vals,
            idx=idx, n_bad=n_bad, n=gs.n, engine_tag=self.engine_tag,
            dispatch_ms=(self._clock() - t0) * 1e3,
            # direct (loop-less) callers get a self-consistent stamp; the
            # serve loop always passes its scheduler clock's ``now``
            dispatched_at=now if now is not None else self._clock(),
            kernel=gs.kernel, resident_delta=delta_path,
        )

    def _dispatch_full(
        self, gs: _PreparedGraph, batch: List[ServeRequest], b_pad: int,
    ):
        """Full staging: upload the whole [b_pad, n_pad, C] stack, and
        refresh the resident base from lane 0 so the NEXT dispatch over
        this graph can go delta."""
        import jax.numpy as jnp

        from rca_tpu.engine.runner import _propagate_ranked_batch, batch_kernel

        fb = np.zeros(
            (b_pad, gs.n_pad, batch[0].features.shape[1]), np.float32
        )
        for i, req in enumerate(batch):
            fb[i, : gs.n] = req.features
        p = self.engine.params
        out = _propagate_ranked_batch(
            jnp.asarray(fb), gs.edges_j,
            self.engine._aw, self.engine._hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus,
            gs.kk, gs.n_live, gs.up_ell, gs.down_seg, gs.up_seg,
            error_contrast=p.error_contrast,
            kernel=batch_kernel(gs.kernel), dbl=gs.dbl,
        )
        gs.base_host = fb[0].copy()
        gs.base_dev = jnp.asarray(gs.base_host)
        gs.base_clean = bool(np.isfinite(gs.base_host).all())
        return out

    def _dispatch_delta(
        self,
        gs: _PreparedGraph,
        batch: List[ServeRequest],
        b_pad: int,
        deltas: List[np.ndarray],
    ):
        """Delta staging against the resident base: per lane one [U]
        index block + one [U, C] row block, scattered on device — the
        full feature stack never crosses the host boundary.  Pad slots
        (and whole pad lanes) aim zero rows at the dummy row."""
        import jax.numpy as jnp

        from rca_tpu.engine.runner import (
            _propagate_ranked_batch_delta,
            batch_kernel,
        )

        C = batch[0].features.shape[1]
        u_max = max((len(d) for d in deltas), default=0)
        u_pad = 1 << max(0, (max(u_max, 1) - 1).bit_length())
        dummy = gs.n_pad - 1
        idx_b = np.full((b_pad, u_pad), dummy, np.int32)
        rows_b = np.zeros((b_pad, u_pad, C), np.float32)
        for i, (req, changed) in enumerate(zip(batch, deltas)):
            u = len(changed)
            idx_b[i, :u] = changed
            rows_b[i, :u] = req.features[changed]
            if self.metrics is not None:
                self.metrics.resident_reuse(req.tenant, gs.n - u)
        p = self.engine.params
        return _propagate_ranked_batch_delta(
            gs.base_dev, jnp.asarray(idx_b), jnp.asarray(rows_b),
            gs.edges_j, self.engine._aw, self.engine._hw,
            p.steps, p.decay, p.explain_strength, p.impact_bonus,
            gs.kk, gs.n_live, gs.up_ell, gs.down_seg, gs.up_seg,
            error_contrast=p.error_contrast,
            kernel=batch_kernel(gs.kernel), dbl=gs.dbl,
        )

    def fetch(self, handle: BatchHandle) -> List[object]:
        """Block on an in-flight batch and render one EngineResult per
        request (lane order = request order; pad lanes dropped).

        THE designated device-sync point of the serve path
        (tools/lint_tick_sync.py forbids device_get/block_until_ready
        anywhere else in it) — async dispatch errors also surface here,
        which is why the serve loop's breaker wraps the fetch.  Moves
        only top-k-sized values: the [b_pad, 4, kk] diagnostic gather,
        the top-k pair, and the sanitized-row scalar — the full stack
        stays on device behind each result's lazy diagnostics."""
        import jax

        from rca_tpu.engine.runner import make_attribution_ctx, render_result

        if self.fault_hook is not None:
            self.fault_hook("fetch")
        t1 = self._clock()
        diag, vals, idx, n_bad = jax.device_get(
            (handle.diag, handle.vals, handle.idx, handle.n_bad)
        )
        fetch_ms = (self._clock() - t1) * 1e3
        per_req_ms = (handle.dispatch_ms + fetch_ms) / len(handle.requests)
        results = []
        for b, req in enumerate(handle.requests):
            results.append(render_result(
                diag[b], vals[b], idx[b], req.names, handle.n, req.k,
                per_req_ms, int(len(req.dep_src)),
                engine=handle.engine_tag,
                # batch-wide count, as in analyze_batch: a poisoned row
                # poisons every hypothesis built from the same snapshot
                sanitized_rows=int(n_bad),
                stacked_dev=handle.stacked[b],
                # causelens (ISSUE 14): the request's own copied arrays
                # back the lazy attribution — computed only when the
                # request asked to be explained (ServeRequest.explain)
                attribution_ctx=make_attribution_ctx(
                    req.features, req.dep_src, req.dep_dst,
                    self.engine.params, req.names,
                    self.engine.config.shape_buckets,
                ),
            ))
        return results
