"""Request/response vocabulary for the serving scheduler.

A :class:`ServeRequest` is one tenant's analyze call — a feature matrix
over a dependency graph — carried through admission, the weighted-fair
queue, the shape-bucket batcher, and one coalesced device dispatch.  The
submitting thread parks on :meth:`ServeRequest.result`; the serve worker
completes the request exactly once with a :class:`ServeResponse` whose
``status`` is the serving contract (SERVING.md):

- ``ok``          served from a (possibly width-1) coalesced batch;
                  rankings are bit-identical to a solo analysis;
- ``shed``        the deadline expired while the request was QUEUED — it
                  never consumed a device slot;
- ``queue_full``  rejected at admission (the queue is at capacity;
                  backpressure belongs at the edge, not in an unbounded
                  queue);
- ``degraded``    the device path failed (or the circuit breaker is
                  open) and the response carries the LAST KNOWN ranking
                  for this graph — stale by contract, never fabricated;
- ``error``       the device path failed and no last-known ranking
                  exists for this graph.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import uuid
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: the serving contract's response states (documented above / SERVING.md)
STATUSES = ("ok", "shed", "queue_full", "degraded", "error")

#: per-request top-k cap: the batched executable's candidate count is a
#: STATIC jit argument, so it must depend only on the shape bucket — k is
#: clamped here and the executable always ranks K_CAP + 8 candidates
K_CAP = 16

#: priority classes: lower value = served first (strict priority across
#: tenants; weighted-fair order breaks ties within a class)
PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_BATCH = 0, 1, 2

GraphKey = Tuple[int, int, int, str]


def graph_key(
    features: np.ndarray, dep_src: np.ndarray, dep_dst: np.ndarray
) -> GraphKey:
    """Identity of the computation graph a request runs over:
    ``(n_services, n_channels, n_edges, edge-digest)``.  Requests sharing
    a key run the SAME padded executable over the SAME edge arrays, so
    they can coalesce into one batched dispatch with bit-identical
    per-lane results (names are render-only and deliberately excluded)."""
    digest = hashlib.sha1(
        dep_src.tobytes() + b"|" + dep_dst.tobytes()
    ).hexdigest()[:16]
    return (
        int(features.shape[0]), int(features.shape[1]),
        int(len(dep_src)), digest,
    )


@dataclasses.dataclass
class ServeResponse:
    status: str                  # one of STATUSES
    request_id: str
    tenant: str
    ranked: List[dict] = dataclasses.field(default_factory=list)
    detail: str = ""             # why (shed/queue_full/degraded/error)
    queue_ms: float = 0.0        # admission -> batch dispatch
    batch_size: int = 0          # occupancy of the batch this request rode
    deadline_missed: bool = False  # served, but past its deadline
    result: Optional[object] = None  # EngineResult for ok responses
    # causelens provenance (ISSUE 14): present only when the request set
    # ``explain`` and was served ok — the schema-versioned attribution
    # block (or an ``{"error": ...}`` stub when attribution itself failed;
    # an explain failure must never fail the ranking)
    provenance: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"


@dataclasses.dataclass
class ServeRequest:
    """One queued analyze request.  Arrays are copied at construction —
    callers may reuse scratch buffers, and a queued request must not
    mutate under the scheduler."""

    tenant: str
    features: np.ndarray         # float32 [S, C]
    dep_src: np.ndarray          # int32 [E]
    dep_dst: np.ndarray          # int32 [E]
    names: Optional[Sequence[str]] = None
    k: int = 5
    priority: int = PRIORITY_NORMAL
    deadline_s: Optional[float] = None  # absolute, scheduler clock domain
    cost: float = 1.0            # weighted-fair-queue charge
    investigation_id: Optional[str] = None  # optional store append target
    # causelens (ISSUE 14): serve this request WITH its attribution — the
    # sink computes the provenance block after the fetch (one extra fused
    # dispatch, charged to the explaining request only) and rides it on
    # the response; per-tenant explain counts land in ServeMetrics
    explain: bool = False
    # distributed tracing (ISSUE 11): ``trace_parent`` is the caller's
    # span context (the gateway's request span, or whatever rode in on
    # X-RCA-Trace); ``trace`` is THIS request's root-span identity,
    # minted at admission when tracing is on — every span the scheduler
    # records for this request (queue, batch, dispatch, fetch, steal)
    # parents onto it, so a stolen request keeps its trace
    trace_parent: Optional[object] = None   # observability SpanContext
    trace: Optional[object] = None          # observability SpanContext
    request_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12]
    )
    # filled by the scheduler
    enqueued_at: float = 0.0
    staged_at: float = 0.0       # batcher offer time (batch-wait spans)
    vtag: float = 0.0            # WFQ virtual finish tag
    seq: int = 0                 # admission order (total tie-break)

    def __post_init__(self) -> None:
        self.features = np.array(self.features, np.float32)
        self.dep_src = np.asarray(self.dep_src, np.int32).copy()
        self.dep_dst = np.asarray(self.dep_dst, np.int32).copy()
        if self.features.ndim != 2:
            raise ValueError(
                f"features must be [S, C], got shape {self.features.shape}"
            )
        if len(self.dep_src) != len(self.dep_dst):
            raise ValueError("dep_src and dep_dst must have equal length")
        # clamp instead of reject: the batched executable's candidate
        # count is static per shape bucket (see K_CAP)
        self.k = max(1, min(int(self.k), K_CAP))
        self.names = list(self.names) if self.names is not None else None
        self._graph_key: GraphKey = graph_key(
            self.features, self.dep_src, self.dep_dst
        )
        self._done = threading.Event()
        self.response: Optional[ServeResponse] = None

    @property
    def graph_key(self) -> GraphKey:
        return self._graph_key

    def expired(self, now: float) -> bool:
        return self.deadline_s is not None and now >= self.deadline_s

    # -- completion plumbing -------------------------------------------------
    def complete(self, response: ServeResponse) -> bool:
        """Deliver the response (first writer wins; idempotent)."""
        if self._done.is_set():
            return False
        self.response = response
        self._done.set()
        return True

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResponse:
        """Block until the scheduler completes this request."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"serve request {self.request_id} ({self.tenant}) not "
                f"completed within {timeout}s"
            )
        assert self.response is not None
        return self.response
