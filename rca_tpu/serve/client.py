"""In-process serving client + the EngineAPI adapter + the selftest.

:class:`ServeClient` is how code inside the process talks to a
:class:`rca_tpu.serve.loop.ServeLoop`: submit returns the request (a
future — ``req.result(timeout)`` parks the caller), ``analyze`` is the
blocking convenience, ``submit_many`` fans a hypothesis sweep into
requests that naturally coalesce into one batch (same graph → same
bucket).

:meth:`ServeClient.as_engine` returns an :class:`rca_tpu.engine.runner.
EngineAPI` facade, which is how the coordinator uses the scheduler: a
``RCACoordinator(serve=client)`` routes its correlation analyses through
the shared serving queue instead of owning the device exclusively — two
concurrent investigations batch instead of serializing.

:func:`serve_selftest` is the end-to-end smoke behind
``rca serve --selftest`` (and the tier-1 suite): mixed-tenant requests
over several shape buckets, concurrent submitters, optional chaos, and a
bit-parity check of coalesced vs. solo rankings.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from rca_tpu.engine.runner import EngineAPI, EngineResult
from rca_tpu.serve.loop import ServeLoop
from rca_tpu.serve.request import PRIORITY_NORMAL, ServeRequest, ServeResponse
from rca_tpu.util.threads import make_thread

DEFAULT_TIMEOUT_S = 60.0


class ServeClient:
    """Thin submission surface over one (started) ServeLoop."""

    def __init__(self, loop: Optional[ServeLoop] = None, **loop_kwargs):
        self._own = loop is None
        self.loop = loop if loop is not None else ServeLoop(**loop_kwargs)
        if self._own:
            self.loop.start()

    def close(self) -> None:
        if self._own:
            self.loop.stop()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission ----------------------------------------------------------
    def submit(
        self,
        features: np.ndarray,
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        names: Optional[Sequence[str]] = None,
        tenant: str = "default",
        k: int = 5,
        priority: int = PRIORITY_NORMAL,
        deadline_ms: Optional[float] = None,
        investigation_id: Optional[str] = None,
        trace_parent=None,
        explain: bool = False,
    ) -> ServeRequest:
        """Queue one analyze request; returns immediately with the
        request future (``queue_full``/``shed`` outcomes are already
        completed on it).  ``trace_parent`` (an observability
        ``SpanContext``) parents the request's trace onto the caller's
        span — the gateway passes its request span here so one wire call
        reads as one connected trace."""
        deadline_s = (
            self.loop.clock() + deadline_ms / 1e3
            if deadline_ms is not None else None
        )
        req = ServeRequest(
            tenant=tenant, features=features, dep_src=dep_src,
            dep_dst=dep_dst, names=names, k=k, priority=priority,
            deadline_s=deadline_s, investigation_id=investigation_id,
            trace_parent=trace_parent, explain=explain,
        )
        self.loop.submit(req)
        return req

    def submit_many(
        self, features_batch: Sequence[np.ndarray], dep_src, dep_dst,
        **kwargs,
    ) -> List[ServeRequest]:
        """A hypothesis sweep as individual requests — same graph, so
        they coalesce into the same shape bucket and (queue permitting)
        the same device dispatch."""
        return [
            self.submit(f, dep_src, dep_dst, **kwargs)
            for f in features_batch
        ]

    def analyze(
        self, features, dep_src, dep_dst,
        timeout_s: float = DEFAULT_TIMEOUT_S, **kwargs,
    ) -> ServeResponse:
        """Blocking submit: one request through the shared queue."""
        return self.submit(
            features, dep_src, dep_dst, **kwargs
        ).result(timeout_s)

    # -- coordinator facade --------------------------------------------------
    def as_engine(
        self,
        tenant: str = "coordinator",
        deadline_ms: Optional[float] = None,
        timeout_s: float = DEFAULT_TIMEOUT_S,
    ) -> "ServeEngineAdapter":
        return ServeEngineAdapter(
            self, tenant=tenant, deadline_ms=deadline_ms,
            timeout_s=timeout_s,
        )


class ServeEngineAdapter(EngineAPI):
    """EngineAPI facade over the serving queue: any caller written
    against the analyze boundary (the coordinator's correlate step, the
    CLI) runs through the shared scheduler unchanged, coalescing with
    whatever else is in flight."""

    def __init__(self, client: ServeClient, tenant: str,
                 deadline_ms: Optional[float], timeout_s: float):
        self.client = client
        self.tenant = tenant
        self.deadline_ms = deadline_ms
        self.timeout_s = timeout_s

    def analyze_arrays(self, features, dep_src, dep_dst, names=None,
                       k=None, timed=False) -> EngineResult:
        resp = self.client.analyze(
            features, dep_src, dep_dst, names=names, k=k or 5,
            tenant=self.tenant, deadline_ms=self.deadline_ms,
            timeout_s=self.timeout_s,
        )
        if resp.result is None:
            raise RuntimeError(
                f"serve: {resp.status}"
                + (f" ({resp.detail})" if resp.detail else "")
            )
        return resp.result


# ---------------------------------------------------------------------------
# Selftest (CLI `rca serve --selftest`, tier-1 smoke)
# ---------------------------------------------------------------------------


def serve_selftest(
    n_requests: int = 32,
    seed: int = 0,
    engine=None,
    chaos: bool = False,
    chaos_rate: float = 0.15,
    deadline_ms: float = 30_000.0,
    config=None,
    submitters: int = 4,
    timeout_s: float = 300.0,
    replicas: int = 1,
    replica_mix: str = "",
    kill_replica: bool = False,
) -> Dict[str, object]:
    """End-to-end scheduler smoke: ``n_requests`` mixed-tenant requests
    over three shape buckets, submitted from ``submitters`` concurrent
    threads with mixed priorities, a couple of them with already-expired
    deadlines (the shed contract must fire).  Asserts — and reports —
    that every request resolved (answered or shed), and that every ``ok``
    ranking is bit-identical to a solo analysis of the same request
    (the batching-parity contract), then returns the summary the CLI
    prints.  ``chaos`` wires a seeded fault hook into the dispatcher to
    exercise the breaker + degraded path (parity is then checked on the
    ok responses only — degraded ones are stale by contract).

    ``replicas`` > 1 (or a non-empty ``replica_mix``) runs the same
    contract through the :class:`rca_tpu.serve.pool.ServePool` — parity
    is then checked per replica KIND against that replica's own engine,
    the summary carries the per-replica occupancy / steal / breaker
    rows, and exactly-once is asserted via the sink's
    ``double_completions``.  ``kill_replica`` kills replica 0 mid-wave
    (the chaos seam behind ``rca serve --selftest --kill-replica``): the
    work-stealing rebalance must leave every request answered-or-shed
    with zero double completions."""
    import dataclasses as _dc

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.config import ServeConfig
    from rca_tpu.engine.runner import GraphEngine

    engine = engine or GraphEngine()
    fault_hook = None
    if chaos:
        from rca_tpu.resilience.chaos import seeded_fault_hook

        fault_hook = seeded_fault_hook(seed, rate=chaos_rate)
    cases = [
        synthetic_cascade_arrays(n, n_roots=1, seed=seed + i)
        for i, n in enumerate((48, 120, 260))
    ]
    tenants = [f"tenant-{c}" for c in "abcd"]
    rng = np.random.default_rng(seed)
    use_pool = replicas > 1 or bool(replica_mix) or kill_replica
    if use_pool:
        from rca_tpu.serve.pool import ServePool

        cfg = _dc.replace(
            config or ServeConfig.from_env(),
            replicas=max(replicas, 2 if kill_replica else 1),
            replica_mix=replica_mix,
        )
        loop = ServePool(config=cfg, fault_hook=fault_hook)
    else:
        loop = ServeLoop(
            engine=engine, config=config or ServeConfig.from_env(),
            fault_hook=fault_hook,
        )
    # parity oracles: the engine serving each replica kind (a pool's ok
    # response names its engine tag; the solo rerun must use the SAME
    # engine so dense-vs-sharded float differences cannot masquerade as
    # batching-parity failures)
    solo_by_tag = {"serve+single": engine}
    if use_pool:
        for r in loop.replicas:
            solo_by_tag.setdefault(r.dispatcher.engine_tag,
                                   r.dispatcher.engine)
    loop.queue.set_weight(tenants[0], 2.0)  # one heavy tenant
    specs = []
    for i in range(n_requests):
        case = cases[i % len(cases)]
        if i % 3 == 2:
            # sparse perturbation: a handful of dirty rows, so repeat
            # dispatches over this graph ride the dispatcher's resident
            # delta path — its bit-parity is under THIS selftest's
            # coalesced-vs-solo gate, not just unit tests (ISSUE 6)
            feats = case.features.copy()
            rows = rng.integers(0, case.features.shape[0], 4)
            feats[rows] = np.clip(
                feats[rows] + rng.uniform(
                    0, 0.2, (4, case.features.shape[1])
                ).astype(np.float32),
                0, 1,
            )
        else:
            feats = np.clip(
                case.features
                + rng.uniform(0, 0.05, case.features.shape).astype(
                    np.float32),
                0, 1,
            )
        specs.append({
            "case": case,
            "features": feats,
            "tenant": tenants[i % len(tenants)],
            "priority": 0 if i % 7 == 0 else 1,
            # a few requests arrive already expired: the shed contract
            # (no device slot, `shed` response) must fire
            "deadline_ms": -1.0 if i % 11 == 10 else deadline_ms,
        })
    requests: List[Optional[ServeRequest]] = [None] * n_requests
    with loop:
        client = ServeClient(loop)

        def submitter(worker: int) -> None:
            for i in range(worker, n_requests, submitters):
                s = specs[i]
                if kill_replica and worker == 0 and i >= n_requests // 2:
                    # chaos seam: replica 0 dies mid-wave; the steal
                    # protocol must keep every request answered-or-shed
                    loop.replicas[0].kill()
                requests[i] = client.submit(
                    s["features"], s["case"].dep_src, s["case"].dep_dst,
                    names=s["case"].names, tenant=s["tenant"], k=3,
                    priority=s["priority"], deadline_ms=s["deadline_ms"],
                )

        threads = [
            make_thread(submitter, name=f"selftest-submit-{w}",
                        daemon=True, args=(w,))
            for w in range(submitters)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        responses = [r.result(timeout_s) for r in requests]  # type: ignore

        # second wave (ISSUE 6): CLOSED-LOOP sparse repeats over the
        # largest graph.  The first wave established that graph's
        # resident base in the dispatcher, and each of these arrives
        # after the previous one resolved — so they dispatch separately
        # and must ride the delta-scatter path, putting its
        # coalesced-vs-solo bit parity under THIS selftest's gate.
        delta_specs: List[dict] = []
        delta_responses: List[ServeResponse] = []
        for j in range(4):
            case = cases[-1]
            feats = case.features.copy()
            rows = rng.integers(0, feats.shape[0], 3)
            feats[rows] = np.clip(
                feats[rows] + rng.uniform(
                    0, 0.2, (3, feats.shape[1])
                ).astype(np.float32),
                0, 1,
            )
            req = client.submit(
                feats, case.dep_src, case.dep_dst, names=case.names,
                tenant=tenants[j % len(tenants)], k=3,
            )
            delta_specs.append({"case": case, "features": feats})
            delta_responses.append(req.result(timeout_s))

        # kernelscope (ISSUE 12): snapshot BEFORE the loop stops (the
        # monitor disarms with it).  Every serve-path compile is a fresh
        # shape/width here — a repeat-signature compile means a cache
        # key drifted between bit-identical calls, and fails the
        # selftest like a parity break would.
        scope = loop.recompile_monitor.snapshot()

    by_status: Dict[str, int] = {}
    for resp in responses:
        by_status[resp.status] = by_status.get(resp.status, 0) + 1
    # without chaos the delta wave must be served ok (under chaos a
    # degraded answer is a legitimate outcome); parity below covers it
    delta_wave_ok = all(r.ok for r in delta_responses)
    # parity: every ok ranking must equal the solo analysis bit-for-bit
    # (delta-wave responses included — the resident delta path holds the
    # same contract as full staging)
    parity_checked = 0
    parity_ok = True
    for spec, resp in zip(
        list(specs) + delta_specs, list(responses) + delta_responses
    ):
        if not resp.ok:
            continue
        solo = solo_by_tag.get(resp.result.engine, engine).analyze_arrays(
            spec["features"], spec["case"].dep_src, spec["case"].dep_dst,
            spec["case"].names, k=3,
        )
        parity_checked += 1
        if solo.ranked != resp.ranked or not np.array_equal(
            solo.score, resp.result.score
        ):
            parity_ok = False
    expected_shed = sum(1 for s in specs if s["deadline_ms"] < 0)
    all_resolved = all(r.done() for r in requests)  # type: ignore
    summary = loop.metrics.summary()
    resident_delta_requests = sum(
        t["resident_delta_requests"] for t in summary["tenants"].values()
    )
    ok = (
        all_resolved
        and parity_ok
        and by_status.get("shed", 0) >= expected_shed
        # without chaos the device path must be clean: no errors, every
        # non-shed request served ok, and the closed-loop delta wave both
        # resolved ok AND actually rode the resident delta path.  Under
        # chaos, degraded/error are legitimate contract outcomes
        # (RESILIENCE.md) — the assertions that matter are resolution +
        # parity of the ok responses.
        and (chaos or (
            by_status.get("error", 0) == 0
            and by_status.get("ok", 0)
            == n_requests - by_status.get("shed", 0)
            and delta_wave_ok
            and resident_delta_requests >= 1
        ))
        # recompile watchdog: zero repeat-signature compiles across the
        # whole selftest (fresh widths/shapes are legitimate and not
        # counted — see kernelscope)
        and scope["recompiles"] == 0
    )
    out = {
        "ok": bool(ok),
        "requests": n_requests,
        "chaos": bool(chaos),
        "kernelscope": {
            "enabled": scope["enabled"],
            "compiles": scope["compiles"],
            "recompiles": scope["recompiles"],
            **({"recompiled": scope["recompiled"]}
               if scope["recompiled"] else {}),
        },
        "by_status": by_status,
        "expected_shed_min": expected_shed,
        "all_resolved": bool(all_resolved),
        "parity_checked": parity_checked,
        "parity_ok": bool(parity_ok),
        "resident_delta_requests": resident_delta_requests,
        "delta_wave_ok": bool(delta_wave_ok),
        "device_batches": loop.device_batches,
        "metrics": summary,
    }
    if use_pool:
        # pool-mode rows: exactly-once accounting + the per-replica
        # occupancy / steal / breaker table (metrics["replicas"]) the
        # CLI prints; a nonzero double_completions fails the selftest
        out["replicas"] = len(loop.replicas)
        out["replica_mix"] = [r.kind for r in loop.replicas]
        out["kill_replica"] = bool(kill_replica)
        out["steals_total"] = summary.get("steals_total", 0)
        out["double_completions"] = loop.sink.double_completions
        out["breaker_state"] = {
            str(r.replica_id): (
                r.breaker.state if r.alive() else "dead"
            )
            for r in loop.replicas
        }
        out["ok"] = bool(out["ok"] and loop.sink.double_completions == 0)
        if kill_replica:
            out["ok"] = bool(
                out["ok"] and out["steals_total"] >= 0
                and any(s == "dead"
                        for s in out["breaker_state"].values())
            )
    else:
        out["breaker_state"] = loop.breaker.state
    return out
