"""ServeLoop: the continuous-batching serve worker.

One worker drives the whole scheduler: it sheds expired requests, drains
the fair queue into the shape-bucket batcher, dispatches ready batches,
and fetches the PREVIOUS batch only after the next one is already in
flight — the PR-2 dispatch/fetch split applied to serving, so batch N's
device round trip hides behind batch N+1's host-side assembly instead of
serializing with it.

Resilience contract (RESILIENCE.md vocabulary):

- every request is completed EXACTLY once, whatever fails — the loop
  never lets an exception escape a scheduling iteration;
- a dispatch/fetch failure records the fault (bounded fault log), counts
  against a :class:`rca_tpu.resilience.policy.CircuitBreaker`, and
  answers the batch with the LAST KNOWN ranking for that graph
  (``degraded``) or ``error`` when none exists;
- an OPEN breaker answers immediately without touching the device (the
  degraded path is also the overload path: a broken device must not
  accumulate queue);
- deadline shedding happens at admission, in the queue, in the batcher,
  and once more at batch formation — an expired request NEVER consumes a
  device slot.  A request whose deadline lapses only while its batch is
  in flight is still answered ``ok`` with ``deadline_missed`` set (the
  slot was already spent; the caller decides what staleness means).

The loop body lives in :meth:`run_once` so policy tests can drive the
scheduler single-threaded with a fake clock; :meth:`start` runs the same
body on a daemon worker for real serving.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from rca_tpu.config import ServeConfig
from rca_tpu.observability.spans import default_tracer, device_annotation
from rca_tpu.resilience.policy import (
    CircuitBreaker,
    record_fault,
)
from rca_tpu.serve.batcher import ShapeBucketBatcher
from rca_tpu.serve.dispatcher import BatchDispatcher, BatchHandle
from rca_tpu.serve.metrics import ServeMetrics
from rca_tpu.serve.queue import RequestQueue
from rca_tpu.serve.replica import (
    STAGE_AHEAD_BATCHES as _STAGE_AHEAD_BATCHES,
    CompletionSink,
)
from rca_tpu.serve.request import ServeRequest, ServeResponse
from rca_tpu.util.threads import make_thread

#: idle park time when nothing is queued, staged, or in flight
_IDLE_WAIT_S = 0.05


class ServeLoop:
    def __init__(
        self,
        engine=None,
        config: Optional[ServeConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        store=None,
        fault_hook: Optional[Callable[[str], None]] = None,
        breaker: Optional[CircuitBreaker] = None,
        dispatcher: Optional[BatchDispatcher] = None,
        recorder=None,
        tracer=None,
        kernelscope: Optional[bool] = None,
    ):
        self.config = config or ServeConfig.from_env()
        self.clock = clock
        # kernelscope (ISSUE 12): the serve plane's recompile watchdog —
        # armed for the loop's lifetime (start→stop); a post-warmup
        # compilation of an already-compiled signature on the serve path
        # is a regression.  ``kernelscope=None`` follows RCA_KERNELSCOPE.
        from rca_tpu.observability.kernelscope import RecompileMonitor

        self.recompile_monitor = RecompileMonitor(enabled=kernelscope)
        # distributed tracing (ISSUE 11): admission mints each request's
        # root context; the loop records queue/batch/dispatch/fetch
        # spans; the sink closes the root at completion
        self.tracer = tracer if tracer is not None else default_tracer()
        self.queue = RequestQueue(self.config.queue_cap, clock=clock)
        self.batcher = ShapeBucketBatcher(
            self.config.max_batch, self.config.max_wait_us, clock=clock
        )
        self.metrics = ServeMetrics()
        self.dispatcher = dispatcher or BatchDispatcher(
            engine, fault_hook=fault_hook, metrics=self.metrics,
        )
        # an externally-built BatchDispatcher joins the loop's metrics
        # (cache + resident-reuse observability) unless it already has its
        # own; stub dispatchers without the attribute are left alone
        if getattr(self.dispatcher, "metrics", "absent") is None:
            self.dispatcher.metrics = self.metrics
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_after=1.0, clock=clock,
            name="serve.dispatch",
        )
        # optional investigation store: an ok response with an
        # investigation_id appends a serve note there (the store's fcntl
        # locking is what makes this safe from the worker thread while
        # submitters touch the same investigation)
        self.store = store
        # response delivery is shared machinery with the serve pool
        # (ISSUE 8): the sink owns the last-known ladder, exactly-once
        # accounting, store notes, and recorder frames
        self.sink = CompletionSink(
            self.metrics, clock, store=store, recorder=recorder,
            tracer=self.tracer,
        )
        self._inflight: Optional[BatchHandle] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.device_batches = 0   # batches actually dispatched to device
        # flight recorder (ISSUE 5): every OK response logs its full
        # request inputs + ranking as a self-contained serve frame
        self.recorder = recorder
        if recorder is not None:
            recorder.begin_session({
                "engine": type(self.dispatcher.engine).__name__,
                "max_batch": self.config.max_batch,
                "max_wait_us": self.config.max_wait_us,
                "queue_cap": self.config.queue_cap,
            })

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServeLoop":
        if self._thread is None or not self._thread.is_alive():
            self.recompile_monitor.start()
            self._stop.clear()
            self._thread = make_thread(
                self._run, name="rca-serve", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self.queue.kick()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.recompile_monitor.stop()

    def kernelscope_summary(self) -> dict:
        """The plane's compiler/device telemetry (ISSUE 12): recompile
        counts, one device-memory sample, and the live kernel-registry
        rows — rendered by ``/metrics`` and the selftest summary.  Cost
        analysis is exported only where already captured; a metrics
        scrape never triggers a compile."""
        from rca_tpu.engine.registry import kernel_set_hash, kernel_table
        from rca_tpu.observability.kernelscope import sample_device_memory

        out = dict(self.recompile_monitor.snapshot())
        out["device_memory"] = (
            sample_device_memory() if out["enabled"] else None
        )
        out["kernel_registry"] = kernel_table()
        # the grown kernel-set source hash (ISSUE 13): the winner-cache
        # invalidation key, exported so a scrape can tell WHICH kernel
        # set a plane's rows were timed under
        out["kernel_set"] = kernel_set_hash()
        return out

    def __enter__(self) -> "ServeLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def engine(self):
        return self.dispatcher.engine

    # -- admission -----------------------------------------------------------
    def submit(self, req: ServeRequest) -> bool:
        """Admit one request.  Returns whether it was QUEUED; either way
        the request will be completed (``queue_full``/``shed`` responses
        are delivered synchronously here), so ``req.result()`` always
        terminates."""
        now = self.clock()
        if self.tracer.enabled and req.trace is None:
            req.trace = self.tracer.new_context(parent=req.trace_parent)
        if req.expired(now):
            # dead on arrival: shed at admission, never queued
            self._respond_shed(req, detail="expired_at_admission")
            return False
        if not self.queue.submit(req):
            self.metrics.rejected(req.tenant)
            req.complete(ServeResponse(
                status="queue_full", request_id=req.request_id,
                tenant=req.tenant,
                detail=f"queue at capacity ({self.queue.cap})",
            ))
            return False
        self.metrics.submitted(req.tenant, len(self.queue))
        return True

    # -- scheduling iteration ------------------------------------------------
    def run_once(self) -> bool:
        """One scheduler iteration (shed → stage → dispatch → fetch the
        previous batch).  Returns whether any work happened — the worker
        parks when three consecutive concerns (queue, batcher, inflight)
        are empty.  Exposed for single-threaded policy tests."""
        now = self.clock()
        worked = False
        for req in self.queue.shed_expired(now):
            self._respond_shed(req, detail="expired_in_queue")
            worked = True
        for req in self.batcher.shed_expired(now):
            self._respond_shed(req, detail="expired_in_batcher")
            worked = True
        # stage ahead of the device, but boundedly: the queue keeps
        # backpressure accounting while the batcher only holds what the
        # next few dispatches can consume
        stage_cap = self.config.max_batch * _STAGE_AHEAD_BATCHES
        while self.batcher.staged() < stage_cap:
            req = self.queue.pop()
            if req is None:
                break
            if self.tracer.enabled and req.trace is not None:
                self.tracer.record(
                    "serve.queue", req.enqueued_at, now,
                    parent=req.trace,
                    attrs={"tenant": req.tenant,
                           "priority": req.priority},
                )
            self.batcher.offer(req)
            worked = True
        drain = self._inflight is None and len(self.queue) == 0
        batch = self.batcher.take_ready(now, drain=drain)
        handle = None
        if batch:
            worked = True
            live: List[ServeRequest] = []
            for req in batch:
                # last call: a deadline can lapse between staging and
                # batch formation, and an expired request must not ride
                # a device slot even when its batch is already formed
                if req.expired(now):
                    self._respond_shed(req, detail="expired_at_dispatch")
                else:
                    live.append(req)
            if live:
                if self.tracer.enabled:
                    for req in live:
                        if req.trace is not None:
                            self.tracer.record(
                                "serve.batch",
                                req.staged_at or now, now,
                                parent=req.trace,
                                attrs={"width": len(live)},
                            )
                handle = self._dispatch_guarded(live)
        if self._inflight is not None:
            # fetch the PREVIOUS batch only after this iteration's
            # dispatch is in flight: its round trip overlapped the
            # shed/stage/dispatch host work above
            self._fetch_guarded(self._inflight)
            self._inflight = None
            worked = True
        if handle is not None:
            self._inflight = handle
        return worked

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.run_once():
                timeout = self.batcher.next_ready_in() or _IDLE_WAIT_S
                self.queue.wait_for_work(min(timeout, _IDLE_WAIT_S))
        self._shutdown_drain()

    def _shutdown_drain(self) -> None:
        """Complete everything still in the system: the in-flight batch
        fetches normally (results exist), everything else errors out —
        a stopped loop must not leave submitters parked forever."""
        if self._inflight is not None:
            self._fetch_guarded(self._inflight)
            self._inflight = None
        pending: List[ServeRequest] = []
        while True:
            req = self.queue.pop()
            if req is None:
                break
            pending.append(req)
        pending.extend(self.batcher.take_ready(drain=True) or [])
        while self.batcher.staged():
            pending.extend(self.batcher.take_ready(drain=True) or [])
        for req in pending:
            self.sink.error(req, "serve loop stopped")

    # -- guarded device path -------------------------------------------------
    def _dispatch_guarded(
        self, batch: List[ServeRequest]
    ) -> Optional[BatchHandle]:
        if not self.breaker.allow():
            # open breaker: answer WITHOUT touching the device — the
            # degraded path doubles as load shedding while broken
            for req in batch:
                self._respond_degraded(req, detail="circuit_open")
            return None
        t0 = self.clock()
        try:
            with device_annotation("serve.dispatch"):
                handle = self.dispatcher.dispatch(batch, now=self.clock())
        except Exception as exc:
            record_fault("serve.dispatch", exc)
            self.breaker.record_failure()
            for req in batch:
                self._respond_degraded(
                    req, detail=f"dispatch_failed:{type(exc).__name__}"
                )
            return None
        if self.tracer.enabled:
            t1 = self.clock()
            for req in batch:
                if req.trace is not None:
                    # host pack/enqueue window + the per-request kernel
                    # attribution (which combine path THIS shape engaged)
                    self.tracer.record(
                        "serve.dispatch", t0, t1, parent=req.trace,
                        attrs={
                            "batch_size": len(batch),
                            "engine": getattr(
                                self.dispatcher, "engine_tag", ""
                            ),
                            "kernel": getattr(handle, "kernel", None),
                            "explain": bool(
                                getattr(req, "explain", False)
                            ),
                            "resident_delta": bool(getattr(
                                handle, "resident_delta", False
                            )),
                        },
                    )
        self.device_batches += 1
        return handle

    def _fetch_guarded(self, handle: BatchHandle) -> None:
        t0 = self.clock()
        try:
            with device_annotation("serve.fetch"):
                results = self.dispatcher.fetch(handle)
        except Exception as exc:
            # async dispatch errors surface at the fetch — same breaker,
            # same degraded answer
            record_fault("serve.fetch", exc)
            self.breaker.record_failure()
            for req in handle.requests:
                self._respond_degraded(
                    req, detail=f"fetch_failed:{type(exc).__name__}"
                )
            return
        self.breaker.record_success()
        if self.tracer.enabled:
            t1 = self.clock()
            for req in handle.requests:
                if req.trace is not None:
                    self.tracer.record(
                        "serve.fetch", t0, t1, parent=req.trace,
                        attrs={
                            "batch_size": len(handle.requests),
                            "inflight_ms": round(max(
                                0.0, (t0 - handle.dispatched_at) * 1e3
                            ), 3),
                        },
                    )
        width = len(handle.requests)
        self.metrics.record_batch(width)
        for req, result in zip(handle.requests, results):
            self.sink.ok(req, result, width, handle.dispatched_at)

    # -- response helpers (shared with the pool via CompletionSink) ----------
    def _respond_shed(self, req: ServeRequest, detail: str) -> None:
        self.sink.shed(req, detail)

    def _respond_degraded(self, req: ServeRequest, detail: str) -> None:
        self.sink.degraded(req, detail)
