"""The federation's internal wire protocol (ISSUE 15).

Coordinator and worker processes speak length-prefixed JSON frames over
one TCP connection per worker: a 4-byte big-endian payload length, then
the UTF-8 JSON payload.  JSON because the analyze arrays already have a
proven bit-exact JSON encoding (:mod:`rca_tpu.gateway.wire` — float32 →
JSON → float32 is the identity, which is what lets the federation
selftest demand POOL-vs-FEDERATION bit parity instead of tolerances);
length-prefixed because a frame boundary must survive a worker dying
mid-write (a short read is a clean, detectable connection death, never
a half-parsed message).

Message vocabulary (``t`` field):

=============  =========  =================================================
frame          direction  meaning
=============  =========  =================================================
``hello``      w → c      worker introduces itself (worker_id, pid, engine,
                          distributed-bootstrap info; optional lease_id
                          when re-joining — a STALE lease is rejected and
                          the worker must re-hello fresh after a seeded
                          jittered backoff.  ISSUE 16 adds two OPTIONAL
                          placement-evidence fields: ``registry`` — the
                          kernel registry's winning per-shape timings,
                          ``{n_pad: winner_ms}`` — and ``headroom`` —
                          kernelscope's ``{"bytes_in_use": N}``.  Absent
                          fields mean 'no evidence': the worker places
                          by pure rendezvous)
``lease``      c → w      lease grant: lease_id + ttl_s + heartbeat_s
``reject``     c → w      hello/heartbeat refused (stale_lease, bad_proto)
``hb``         w → c      heartbeat (renews the lease)
``hb_ack``     c → w      heartbeat acknowledged
``req``        c → w      one analyze request (gateway-wire analyze body)
``resp``       w → c      terminal answer for one request_id
``hang``       c → w      CHAOS: stop heartbeating for ``for_s`` seconds
                          (the socket stays open — ``worker_hang``)
``drain``      c → w      stop accepting, finish in flight, answer
                          ``drained``, exit (fleet stop AND autoscale
                          scale-down both retire workers with this — the
                          coordinator's ``draining`` flag on the handle
                          distinguishes the two when ``drained`` lands)
``drained``    w → c      drain complete (carries ``served`` — the
                          worker's lifetime answer count, reported in
                          the scale-down event)
=============  =========  =================================================

The codec refuses frames over :data:`MAX_FRAME` loudly — an unbounded
length prefix is how one corrupt frame becomes an OOM.
"""

from __future__ import annotations

import json
import struct
import socket
from typing import Any, Dict, List, Optional

from rca_tpu.util.threads import make_lock

#: protocol version, checked at hello (mismatch = reject, not a guess)
PROTO = 1

#: hard frame cap: analyze bodies are feature matrices — 64 MiB covers
#: a 1M-row float32 wire body with room; anything larger is corruption
MAX_FRAME = 64 * 1024 * 1024

_LEN = struct.Struct(">I")


class FrameError(ConnectionError):
    """A malformed frame (oversized length, non-JSON payload) — the
    connection is poisoned and must be dropped, not resynchronized."""


class FrameConn:
    """One framed connection: concurrent senders serialize on a lock
    (responses, heartbeats, and chaos frames interleave), reads are
    single-threaded by construction (one reader thread per connection).

    ``recv`` returns None on clean EOF — a dead peer is an ordinary
    value, not an exception, because worker death is the event the
    federation exists to absorb."""

    def __init__(self, sock: socket.socket, name: str = "fed"):
        self.sock = sock
        self.name = name
        self._wlock = make_lock("FrameConn._wlock")
        # one reader thread per connection by construction, but the
        # reader differs by deployment (coordinator conn loop, worker
        # main, thread-mode fleet members) — the lock makes the buffer
        # read-modify-write atomic whichever thread owns the read side
        self._rlock = make_lock("FrameConn._rlock")
        self._rbuf = b""
        self.closed = False

    # -- send ----------------------------------------------------------------
    def send(self, msg: Dict[str, Any]) -> bool:
        """Frame + write one message; False when the peer is gone (the
        caller treats that as worker/coordinator death, exactly like a
        recv EOF)."""
        payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        if len(payload) > MAX_FRAME:
            raise FrameError(
                f"{self.name}: outbound frame {len(payload)} B over the "
                f"{MAX_FRAME} B cap"
            )
        data = _LEN.pack(len(payload)) + payload
        with self._wlock:
            if self.closed:
                return False
            try:
                self.sock.sendall(data)
                return True
            except OSError:
                self.closed = True
                return False

    # -- recv ----------------------------------------------------------------
    def _read_exact(self, n: int) -> Optional[bytes]:
        while len(self._rbuf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError:
                return None
            if not chunk:
                return None   # EOF mid-frame == peer death, clean stop
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def recv(self) -> Optional[Dict[str, Any]]:
        """The next message, or None when the peer is gone."""
        with self._rlock:
            head = self._read_exact(_LEN.size)
            if head is None:
                return None
            (length,) = _LEN.unpack(head)
            if length > MAX_FRAME:
                raise FrameError(
                    f"{self.name}: inbound frame claims {length} B "
                    f"(cap {MAX_FRAME} B) — poisoned stream"
                )
            payload = self._read_exact(length)
        if payload is None:
            return None
        try:
            msg = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise FrameError(f"{self.name}: non-JSON frame: {exc}")
        if not isinstance(msg, dict) or "t" not in msg:
            raise FrameError(f"{self.name}: frame without a 't' field")
        return msg

    def close(self) -> None:
        with self._wlock:
            self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


# -- request/response bodies --------------------------------------------------

def encode_request(req: Any) -> Dict[str, Any]:
    """A queued :class:`rca_tpu.serve.request.ServeRequest` → the ``req``
    frame.  The analyze payload reuses the gateway codec, inheriting its
    bit-parity argument verbatim."""
    from rca_tpu.gateway.wire import encode_analyze

    return {
        "t": "req",
        "request_id": req.request_id,
        "priority": int(req.priority),
        "explain": bool(getattr(req, "explain", False)),
        "analyze": encode_analyze(
            req.features, req.dep_src, req.dep_dst, names=req.names,
            tenant=req.tenant, k=req.k,
        ),
    }


def decode_request_kwargs(msg: Dict[str, Any]) -> Dict[str, Any]:
    """``req`` frame → ``ServeRequest`` kwargs on the worker side (same
    decoder the gateway trusts; a malformed frame raises WireError and
    the worker answers ``error`` for that request_id)."""
    from rca_tpu.gateway.wire import decode_analyze

    kwargs = decode_analyze(msg["analyze"])
    kwargs.pop("deadline_ms", None)     # deadlines live on the coordinator
    kwargs.pop("investigation_id", None)
    kwargs["priority"] = int(msg.get("priority", 1))
    kwargs["explain"] = bool(msg.get("explain", False))
    return kwargs


def encode_response(request_id: str, resp: Any, engine: str) -> Dict[str, Any]:
    """A worker-local :class:`ServeResponse` → the ``resp`` frame."""
    return {
        "t": "resp",
        "request_id": request_id,
        "status": resp.status,
        "ranked": resp.ranked,
        "detail": resp.detail,
        "batch_size": int(resp.batch_size),
        "engine": getattr(resp.result, "engine", None) or engine,
    }


class WireResult:
    """The coordinator-side stand-in for an ``EngineResult`` on wire
    responses: carries what crossed the process boundary (ranking +
    engine tag) so ``response_body`` and the parity gates read it like
    a local result; everything device-resident stayed in the worker."""

    __slots__ = ("ranked", "engine")

    def __init__(self, ranked: List[dict], engine: str):
        self.ranked = ranked
        self.engine = engine
