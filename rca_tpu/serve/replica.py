"""Engine replicas for the serve pool (ISSUE 8 tentpole).

A :class:`ReplicaWorker` is one slice of the serving plane: its own
engine (dense on one device, or sharded over a device group carved from
the mesh), its own :class:`rca_tpu.serve.dispatcher.BatchDispatcher`
(prepared-graph cache + resident bases), its own
:class:`rca_tpu.serve.batcher.ShapeBucketBatcher`, its own
:class:`rca_tpu.resilience.policy.CircuitBreaker`, and its own worker
thread.  The :class:`rca_tpu.serve.pool.ServePool` routes shape buckets
from the ONE shared queue into replicas; everything the replica answers
flows through the pool-wide :class:`CompletionSink`, which owns the
exactly-once completion accounting and the degradation ladder's
last-known rankings.

Concurrency discipline (gravelock, ANALYSIS.md): worker threads are
spawned via :func:`rca_tpu.util.threads.make_thread`; every mutable
replica attribute the router or a stealing peer can touch is guarded by
``ReplicaWorker._lock``, and the lock is NEVER held across a device
dispatch or fetch — those run between critical sections, so stealing a
dying replica's staged work never waits on its device round trip.  Lock
order (one-way, no cycles): ``ServePool._route_lock`` →
``ReplicaWorker._lock`` → ``BatchDispatcher._graphs_lock``;
``CompletionSink._lock`` and ``ServeMetrics._lock`` are leaves.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Callable, List, Optional

from rca_tpu.config import ServeConfig
from rca_tpu.observability.spans import default_tracer, device_annotation
from rca_tpu.resilience.policy import (
    CircuitBreaker,
    record_fault,
    suppressed,
)
from rca_tpu.serve.batcher import ShapeBucketBatcher
from rca_tpu.serve.dispatcher import BatchDispatcher, BatchHandle
from rca_tpu.serve.request import GraphKey, ServeRequest, ServeResponse
from rca_tpu.util.threads import make_lock, make_thread

#: last-known rankings kept pool-wide for degraded responses
LAST_KNOWN_CAP = 128
#: staging window: how far one replica reads ahead of its device
STAGE_AHEAD_BATCHES = 4


class ReplicaKilled(RuntimeError):
    """Raised inside a replica's scheduling iteration after
    :meth:`ReplicaWorker.kill` — the chaos/test seam for replica death
    (the worker's crash handler turns it into the same rebalance a real
    scheduling-loop exception triggers)."""


class CompletionSink:
    """The ONE place serve responses are delivered (pool-wide).

    Shared by every replica (and by the single-replica
    :class:`rca_tpu.serve.loop.ServeLoop`), it owns:

    - **exactly-once accounting**: ``ServeRequest.complete`` is
      first-writer-wins; a second completion attempt (a steal racing the
      original owner) is counted in ``double_completions`` — the
      replica-kill tests assert it stays ZERO, which is the proof the
      steal protocol never re-serves an already-answered request;
    - **the degradation ladder's memory**: last-known rankings per graph
      key, shared pool-wide so ANY replica can serve a stale answer for
      a graph some other replica computed;
    - the optional investigation-store note and flight-recorder frame
      for ok responses (serialized under the sink lock — with N workers
      the recorder is no longer single-writer).
    """

    def __init__(self, metrics, clock: Callable[[], float],
                 store=None, recorder=None, tracer=None):
        self.metrics = metrics
        self.clock = clock
        self.store = store
        self.recorder = recorder
        # tracing + SLO telemetry (ISSUE 11): the sink is where every
        # request terminates exactly once, so it is where the root
        # ``serve.request`` span and the duration/burn sample belong
        self.tracer = tracer if tracer is not None else default_tracer()
        self._lock = make_lock("CompletionSink._lock")
        self._last_known: "collections.OrderedDict[GraphKey, List[dict]]" = (
            collections.OrderedDict()
        )
        self.double_completions = 0

    # -- exactly-once core ---------------------------------------------------
    def _complete(self, req: ServeRequest, resp: ServeResponse) -> bool:
        if req.complete(resp):
            # exactly-once telemetry rides the exactly-once completion:
            # a losing steal-race completion records neither a duration
            # sample nor a (duplicate) root span
            self._observe(req, resp.status)
            return True
        with self._lock:
            self.double_completions += 1
        return False

    def _observe(self, req: ServeRequest, status: str) -> None:
        """Terminal telemetry for one completed request: the per-tenant
        duration histogram + SLO burn sample (``degraded`` counts as
        served — stale by contract, not a failure; ``shed``/``error``
        burn budget at any speed), and the request's root span, closed
        under its pre-minted identity so every child recorded along the
        way is already parented correctly."""
        now = self.clock()
        start = req.enqueued_at if req.enqueued_at > 0.0 else now
        self.metrics.request_duration(
            req.tenant, max(0.0, now - start),
            ok=status in ("ok", "degraded"),
        )
        if self.tracer.enabled and req.trace is not None:
            self.tracer.record(
                "serve.request", start, now,
                parent=req.trace_parent, context=req.trace,
                attrs={
                    "tenant": req.tenant, "status": status,
                    "request_id": req.request_id,
                },
            )

    # -- last-known ladder ---------------------------------------------------
    def remember(self, key: GraphKey, ranked: List[dict]) -> None:
        with self._lock:
            self._last_known[key] = ranked
            self._last_known.move_to_end(key)
            while len(self._last_known) > LAST_KNOWN_CAP:
                self._last_known.popitem(last=False)

    def last_known(self, key: GraphKey) -> Optional[List[dict]]:
        with self._lock:
            return self._last_known.get(key)

    # -- response paths ------------------------------------------------------
    def ok(self, req: ServeRequest, result, width: int,
           dispatched_at: float) -> None:
        ranked = [dict(r) for r in result.ranked]
        self.remember(req.graph_key, ranked)
        provenance = None
        if getattr(req, "explain", False):
            # causelens (ISSUE 14): one extra fused dispatch, charged to
            # the explaining request only.  An attribution failure must
            # never fail the ranking — the stub says what broke instead.
            self.metrics.explained(req.tenant)
            try:
                provenance = result.attribution()
            except Exception as exc:  # noqa: BLE001 - degrade, but say so
                record_fault("serve.explain", exc)
                provenance = {
                    "error": f"{type(exc).__name__}: {exc}",
                }
        if self.recorder is not None:
            # a recording failure must not fail the response; the sink
            # lock serializes frames now that N workers write through it
            with suppressed("serve.record"):
                with self._lock:
                    self.recorder.record_serve(req, ranked)
        queue_ms = max(0.0, (dispatched_at - req.enqueued_at) * 1e3)
        self.metrics.answered(req.tenant, queue_ms)
        self._store_note(req, result)
        if (provenance is not None and self.store is not None
                and req.investigation_id is not None):
            # `rca why <investigation-id>` reads this back (ISSUE 14)
            with suppressed("serve.store_provenance"):
                self.store.set_provenance(
                    req.investigation_id, provenance,
                )
        self._complete(req, ServeResponse(
            status="ok", request_id=req.request_id, tenant=req.tenant,
            ranked=ranked, queue_ms=round(queue_ms, 3), batch_size=width,
            deadline_missed=req.expired(self.clock()),
            result=result, provenance=provenance,
        ))

    def shed(self, req: ServeRequest, detail: str) -> None:
        self.metrics.shed(req.tenant)
        self._complete(req, ServeResponse(
            status="shed", request_id=req.request_id, tenant=req.tenant,
            detail=detail,
        ))

    def degraded(self, req: ServeRequest, detail: str) -> None:
        """Last-known ranking when one exists, ``error`` otherwise — the
        ladder's bottom rungs."""
        stale = self.last_known(req.graph_key)
        if stale is not None:
            self.metrics.degraded(req.tenant)
            self._complete(req, ServeResponse(
                status="degraded", request_id=req.request_id,
                tenant=req.tenant, ranked=[dict(r) for r in stale],
                detail=detail + " (serving last known ranking)",
            ))
        else:
            self.error(req, detail)

    def error(self, req: ServeRequest, detail: str) -> None:
        self.metrics.errors(req.tenant)
        self._complete(req, ServeResponse(
            status="error", request_id=req.request_id, tenant=req.tenant,
            detail=detail,
        ))

    def _store_note(self, req: ServeRequest, result) -> None:
        """Optional investigation-store append for served requests — the
        store's fcntl locking makes this safe from any worker thread; a
        store failure never fails the response."""
        if self.store is None or req.investigation_id is None:
            return
        top = result.ranked[0]["component"] if result.ranked else None
        with suppressed("serve.store_note"):
            self.store.add_message(
                req.investigation_id, "serve",
                {
                    "request_id": req.request_id,
                    "tenant": req.tenant,
                    "top_component": top,
                    "engine": result.engine,
                },
            )
            if self.recorder is not None:
                self.store.set_recording_ref(
                    req.investigation_id, str(self.recorder.path)
                )


class ReplicaWorker:
    """One engine replica behind the pool's shared queue.

    Life cycle: the pool routes requests in via :meth:`offer`; the
    worker thread (or the pool's fake-clock ``run_once`` driver) forms
    shape-bucket batches, dispatches them breaker-guarded, and fetches
    one batch behind (the PR-2/3 dispatch/fetch split, per replica).  A
    dead or breaker-open replica's staged work is taken back via
    :meth:`take_staged`/:meth:`take_inflight` by the pool's
    work-stealing rebalance.
    """

    def __init__(
        self,
        replica_id: int,
        engine=None,
        kind: str = "dense",
        device=None,
        config: Optional[ServeConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        sink: Optional[CompletionSink] = None,
        metrics=None,
        fault_hook: Optional[Callable[[str], None]] = None,
        breaker: Optional[CircuitBreaker] = None,
        dispatcher: Optional[BatchDispatcher] = None,
        pool=None,
        tracer=None,
    ):
        self.replica_id = int(replica_id)
        self.kind = kind
        self.tracer = tracer if tracer is not None else default_tracer()
        #: the device this replica commits its dispatches to (dense
        #: replicas; sharded ones place through their engine's mesh)
        self.device = device
        self.config = config or ServeConfig.from_env()
        self.clock = clock
        self.sink = sink
        self.metrics = metrics
        self.pool = pool
        self.batcher = ShapeBucketBatcher(
            self.config.max_batch, self.config.max_wait_us, clock=clock
        )
        self.dispatcher = dispatcher or BatchDispatcher(
            engine, fault_hook=fault_hook, metrics=metrics,
        )
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_after=1.0, clock=clock,
            name=f"serve.replica{self.replica_id}",
        )
        self._lock = make_lock("ReplicaWorker._lock")
        self._inflight: Optional[BatchHandle] = None
        self._alive = True
        self._killed = False
        self._stop = False
        self._thread = None
        self._device_batches = 0
        self._last_state = ""

    # -- state the router reads ----------------------------------------------
    def alive(self) -> bool:
        with self._lock:
            return self._alive and not self._killed

    def routable(self) -> bool:
        """May the router assign NEW work here?  Alive and breaker not
        open (half-open replicas take work — that traffic is the probe
        that closes the breaker)."""
        return self.alive() and self.breaker.state != "open"

    def occupancy(self) -> int:
        """Requests this replica currently holds (staged + in flight)."""
        with self._lock:
            inflight = (
                len(self._inflight.requests)
                if self._inflight is not None else 0
            )
            return self.batcher.staged() + inflight

    def has_room(self) -> bool:
        with self._lock:
            staged = self.batcher.staged()
        return staged < self.config.max_batch * STAGE_AHEAD_BATCHES

    def has_graph(self, key: GraphKey) -> bool:
        return self.dispatcher.has_graph(key)

    @property
    def device_batches(self) -> int:
        with self._lock:
            return self._device_batches

    # -- routing surface -----------------------------------------------------
    def offer(self, req: ServeRequest) -> bool:
        """Stage one routed request; False when this replica died between
        the router's liveness check and the offer (the router then
        re-places the request)."""
        with self._lock:
            if not self._alive or self._killed or self._stop:
                return False
            self.batcher.offer(req)
            return True

    # -- steal surface (pool rebalance; see pool.rebalance_from) -------------
    def take_staged(self) -> List[ServeRequest]:
        """Drain EVERYTHING staged here (steal path).  Idempotent: a
        second taker gets an empty list."""
        out: List[ServeRequest] = []
        with self._lock:
            while self.batcher.staged():
                batch = self.batcher.take_ready(drain=True)
                if not batch:
                    break
                out.extend(batch)
        return out

    def take_inflight(self) -> Optional[BatchHandle]:
        """Atomically claim the in-flight batch (steal path) — the taker
        owns its fetch; a second taker gets None, which is what makes
        double-completion impossible by construction."""
        with self._lock:
            handle, self._inflight = self._inflight, None
            return handle

    def kill(self) -> None:
        """Chaos/test seam: the next scheduling iteration raises
        :class:`ReplicaKilled`, driving the same crash-and-rebalance path
        a real worker death takes."""
        with self._lock:
            self._killed = True

    def mark_dead(self, exc: Optional[BaseException] = None) -> None:
        with self._lock:
            self._alive = False
        if exc is not None and not isinstance(exc, ReplicaKilled):
            record_fault(f"serve.replica{self.replica_id}", exc)
        self._note_state("dead")

    def _note_state(self, state: Optional[str] = None) -> None:
        if self.metrics is None:
            return
        if state is None:
            state = self.breaker.state if self.alive() else "dead"
        with self._lock:
            changed = state != self._last_state
            self._last_state = state
        if changed:
            self.metrics.replica_state(self.replica_id, state)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaWorker":
        if self._thread is None or not self._thread.is_alive():
            with self._lock:
                self._stop = False
            self._thread = make_thread(
                self._run, name=f"rca-serve-replica{self.replica_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def request_stop(self) -> None:
        with self._lock:
            self._stop = True

    def join(self, timeout: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- scheduling ----------------------------------------------------------
    def run_once(self, now: Optional[float] = None) -> bool:
        """One replica scheduling iteration: shed → form batch →
        breaker-guarded dispatch → fetch the PREVIOUS batch (its device
        round trip overlapped this iteration's host work).  Raises
        :class:`ReplicaKilled` after :meth:`kill` — callers (the worker
        thread's crash handler, the pool's fake-clock driver) turn that
        into death + rebalance."""
        if now is None:
            now = self.clock()
        with self._lock:
            if self._killed:
                raise ReplicaKilled(
                    f"replica {self.replica_id} killed"
                )
            expired = self.batcher.shed_expired(now)
        worked = False
        for req in expired:
            self.sink.shed(req, detail="expired_in_batcher")
            worked = True
        # open breaker: complete what is already in flight (the dispatch
        # happened — fetch either serves it or degrades it; submitters
        # must not park until the half-open probe), then hand staged
        # work back to the pool (work-stealing rebalance); with no pool
        # (or stealing off) the ladder answers degraded instead
        if self.breaker.state == "open":
            self._note_state()
            prev = self.take_inflight()
            if prev is not None:
                self._fetch_guarded(prev)
                worked = True
            if self.pool is not None:
                worked |= self.pool.rebalance_from(
                    self, reason="breaker_open"
                ) > 0
            return worked
        with self._lock:
            drain = (
                self._inflight is None
                and (self.pool is None or len(self.pool.queue) == 0)
            )
            batch = self.batcher.take_ready(now, drain=drain)
        handle = None
        if batch:
            worked = True
            live: List[ServeRequest] = []
            for req in batch:
                # last call: an expired request must not ride a device
                # slot even when its batch is already formed
                if req.expired(now):
                    self.sink.shed(req, detail="expired_at_dispatch")
                else:
                    live.append(req)
            if live:
                if self.tracer.enabled:
                    for req in live:
                        if req.trace is not None:
                            # batcher staging wait, on the replica that
                            # actually formed the batch (a steal restamps
                            # staged_at, so the span never spans replicas)
                            self.tracer.record(
                                "serve.batch",
                                req.staged_at or now, now,
                                parent=req.trace,
                                attrs={"replica": self.replica_id,
                                       "width": len(live)},
                            )
                handle = self._dispatch_guarded(live)
        prev = self.take_inflight()
        if prev is not None:
            # fetch the PREVIOUS batch only after this iteration's
            # dispatch is in flight
            self._fetch_guarded(prev)
            worked = True
        if handle is not None:
            with self._lock:
                self._inflight = handle
        if worked and self.metrics is not None:
            self.metrics.replica_occupancy(
                self.replica_id, self.occupancy()
            )
        self._note_state()
        return worked

    def drain_inflight(self) -> None:
        """Fetch whatever is still in flight (clean-shutdown path — the
        results exist; submitters must not park forever)."""
        prev = self.take_inflight()
        if prev is not None:
            self._fetch_guarded(prev)

    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    if self._stop:
                        break
                worked = False
                if self.pool is not None:
                    worked |= self.pool.route_once()
                worked |= self.run_once()
                if not worked and self.pool is not None:
                    with self._lock:
                        timeout = self.batcher.next_ready_in()
                    self.pool.park(timeout)
        except Exception as exc:  # noqa: BLE001 - crash = replica death
            self.mark_dead(exc)
            if self.pool is not None:
                self.pool.rebalance_from(self, reason="replica_death")
            return
        self.drain_inflight()

    # -- guarded device path -------------------------------------------------
    def _device_ctx(self):
        """Dense replicas commit their dispatches to their carved device;
        sharded replicas place through the engine's mesh."""
        if self.device is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)

    def _dispatch_guarded(
        self, batch: List[ServeRequest]
    ) -> Optional[BatchHandle]:
        if not self.breaker.allow():
            # raced from half-open to open (or another probe is out):
            # give the batch back to the pool rather than burning it
            if self.pool is not None:
                self.pool.redistribute(
                    batch, exclude=self, reason="breaker_open"
                )
            else:
                for req in batch:
                    self.sink.degraded(req, detail="circuit_open")
            return None
        t0 = self.clock()
        try:
            with self._device_ctx(), device_annotation("serve.dispatch"):
                handle = self.dispatcher.dispatch(batch, now=self.clock())
        except Exception as exc:
            record_fault(f"serve.replica{self.replica_id}.dispatch", exc)
            self.breaker.record_failure()
            for req in batch:
                self.sink.degraded(
                    req, detail=f"dispatch_failed:{type(exc).__name__}"
                )
            return None
        self._dispatch_spans(batch, handle, t0, self.clock())
        with self._lock:
            self._device_batches += 1
        return handle

    def _dispatch_spans(
        self, batch: List[ServeRequest], handle, t0: float, t1: float,
    ) -> None:
        """One serve.dispatch span per traced request: the host-side
        pack/enqueue window, stamped with the engaged kernel path and
        whether the resident delta path carried the upload — the
        per-request answer to ``pallas_engaged: false``."""
        if not self.tracer.enabled:
            return
        for req in batch:
            if req.trace is not None:
                self.tracer.record(
                    "serve.dispatch", t0, t1, parent=req.trace,
                    attrs={
                        "batch_size": len(batch),
                        "replica": self.replica_id,
                        "engine": getattr(
                            self.dispatcher, "engine_tag", ""
                        ),
                        "kernel": getattr(handle, "kernel", None),
                        "explain": bool(getattr(req, "explain", False)),
                        "resident_delta": bool(getattr(
                            handle, "resident_delta", False
                        )),
                    },
                )

    def _fetch_guarded(self, handle: BatchHandle) -> None:
        t0 = self.clock()
        try:
            with self._device_ctx(), device_annotation("serve.fetch"):
                results = self.dispatcher.fetch(handle)
        except Exception as exc:
            record_fault(f"serve.replica{self.replica_id}.fetch", exc)
            self.breaker.record_failure()
            for req in handle.requests:
                self.sink.degraded(
                    req, detail=f"fetch_failed:{type(exc).__name__}"
                )
            return
        if self.tracer.enabled:
            t1 = self.clock()
            for req in handle.requests:
                if req.trace is not None:
                    # the device round-trip sync: dispatched_at→t0 is the
                    # overlapped in-flight window, t0→t1 the actual wait
                    self.tracer.record(
                        "serve.fetch", t0, t1, parent=req.trace,
                        attrs={
                            "batch_size": len(handle.requests),
                            "replica": self.replica_id,
                            "inflight_ms": round(max(
                                0.0, (t0 - handle.dispatched_at) * 1e3
                            ), 3),
                        },
                    )
        self.breaker.record_success()
        width = len(handle.requests)
        if self.metrics is not None:
            self.metrics.record_batch(width)
            self.metrics.replica_batch(self.replica_id, width)
        for req, result in zip(handle.requests, results):
            self.sink.ok(req, result, width, handle.dispatched_at)


def build_replica_engines(
    specs,
    devices=None,
    config=None,
    params=None,
):
    """``(kind, group_size|None)`` specs (from
    :func:`rca_tpu.config.parse_replica_mix`) → ``(kind, engine,
    device|None)`` triples, with device groups carved contiguously from
    the visible devices (:func:`rca_tpu.parallel.mesh.
    carve_device_groups`) and sharded sub-meshes built over the axes the
    partition-rule table names (:data:`rca_tpu.parallel.rules.
    GRAPH_RULES`) — replica construction, graph-tensor sharding, and
    device-group assignment all read the one rule table."""
    import jax

    from rca_tpu.parallel.mesh import carve_device_groups, make_mesh
    from rca_tpu.parallel.rules import GRAPH_RULES

    devices = list(devices if devices is not None else jax.devices())
    n = max(1, len(specs))
    sizes = [
        group if group is not None
        else (1 if kind == "dense" else max(1, len(devices) // n))
        for kind, group in specs
    ]
    groups = carve_device_groups(sizes, devices)
    batch_axis, shard_axis = GRAPH_RULES.mesh_axes()
    out = []
    for (kind, _), group in zip(specs, groups):
        if kind == "sharded":
            from rca_tpu.engine.sharded_runner import ShardedGraphEngine

            mesh = make_mesh(
                [(batch_axis, 1), (shard_axis, len(group))], group
            )
            out.append((kind, ShardedGraphEngine(
                mesh=mesh, config=config, params=params,
            ), None))
        else:
            from rca_tpu.engine.runner import GraphEngine

            out.append((
                kind, GraphEngine(config=config, params=params), group[0],
            ))
    return out
